// kwok_native: C++ runtime core for kwok-tpu's host-side scheduler.
//
// Implements the framework's delay/weight scheduling structure as a
// native binary-heap pair keyed by (deadline, seq) with weight-bucket
// ready queues — the C++ counterpart of the reference's
// WeightDelayingQueue (reference pkg/utils/queue/
// weight_delaying_queue.go:29-163: time-ordered heap feeding per-weight
// buckets, lower weight served first).  Python drives it through a flat
// C ABI via ctypes; items are opaque int64 handles mapped back to
// Python objects by the binding layer.
//
// Also exports a batched FNV-1a 64 hash for string interning.
//
// Build: g++ -O3 -shared -fPIC -o libkwok_native.so kwok_native.cpp

#include <cstdint>
#include <cstring>
#include <map>
#include <queue>
#include <unordered_map>
#include <vector>

namespace {

struct Entry {
    double deadline;
    uint64_t seq;  // FIFO tiebreak within one deadline
    int64_t id;
    int32_t weight;
};

struct EntryCmp {
    bool operator()(const Entry& a, const Entry& b) const {
        if (a.deadline != b.deadline) return a.deadline > b.deadline;
        return a.seq > b.seq;  // min-heap: earlier seq first
    }
};

struct ReadyItem {
    uint64_t seq;
    int64_t id;
};

class DelayHeap {
   public:
    // Schedule id to become ready at `deadline` with `weight`.
    // Re-adding an id reschedules it (cancels the previous entry).
    void add(int64_t id, int32_t weight, double deadline) {
        uint64_t seq = next_seq_++;
        live_[id] = {deadline, weight, seq};
        heap_.push(Entry{deadline, seq, id, weight});
    }

    // Remove id wherever it lives (pending heap or ready bucket).
    // Returns 1 if it was scheduled/ready, 0 otherwise.
    int cancel(int64_t id) {
        auto it = live_.find(id);
        if (it == live_.end()) return 0;
        live_.erase(it);  // heap/bucket entries become stale; skipped on pop
        return 1;
    }

    // Move everything due at `now` into the weight buckets.
    void promote(double now) {
        while (!heap_.empty() && heap_.top().deadline <= now) {
            Entry e = heap_.top();
            heap_.pop();
            auto it = live_.find(e.id);
            // stale if cancelled or rescheduled since
            if (it == live_.end() || it->second.seq != e.seq) continue;
            ready_[e.weight].push_back(ReadyItem{e.seq, e.id});
            it->second.ready = true;
        }
    }

    // Pop up to `max_out` ready ids, lowest weight bucket first, FIFO
    // within a bucket.  Returns the count written to out.
    int pop_ready(int64_t* out, int max_out) {
        int n = 0;
        auto bucket = ready_.begin();
        while (bucket != ready_.end() && n < max_out) {
            auto& vec = bucket->second;
            while (cursor_[bucket->first] < vec.size() && n < max_out) {
                ReadyItem item = vec[cursor_[bucket->first]++];
                auto it = live_.find(item.id);
                if (it == live_.end() || it->second.seq != item.seq) continue;
                live_.erase(it);
                out[n++] = item.id;
            }
            if (cursor_[bucket->first] >= vec.size()) {
                cursor_.erase(bucket->first);
                bucket = ready_.erase(bucket);
            } else {
                ++bucket;
            }
        }
        return n;
    }

    // Next pending deadline, or -1 when the heap is empty (after
    // skipping stale entries).
    double next_deadline() {
        while (!heap_.empty()) {
            const Entry& e = heap_.top();
            auto it = live_.find(e.id);
            if (it == live_.end() || it->second.seq != e.seq ||
                it->second.ready) {
                heap_.pop();
                continue;
            }
            return e.deadline;
        }
        return -1.0;
    }

    int ready_count() const {
        int n = 0;
        for (const auto& kv : ready_) {
            auto cur = cursor_.find(kv.first);
            size_t skip = cur == cursor_.end() ? 0 : cur->second;
            for (size_t i = skip; i < kv.second.size(); ++i) {
                auto it = live_.find(kv.second[i].id);
                if (it != live_.end() && it->second.seq == kv.second[i].seq)
                    ++n;
            }
        }
        return n;
    }

    int size() const { return static_cast<int>(live_.size()); }

   private:
    struct Live {
        double deadline;
        int32_t weight;
        uint64_t seq;
        bool ready = false;
    };
    std::priority_queue<Entry, std::vector<Entry>, EntryCmp> heap_;
    std::map<int32_t, std::vector<ReadyItem>> ready_;  // weight-ordered
    std::map<int32_t, size_t> cursor_;  // consumed prefix per bucket
    std::unordered_map<int64_t, Live> live_;
    uint64_t next_seq_ = 0;
};

}  // namespace

extern "C" {

void* kn_heap_new() { return new DelayHeap(); }

void kn_heap_free(void* h) { delete static_cast<DelayHeap*>(h); }

void kn_heap_add(void* h, int64_t id, int32_t weight, double deadline) {
    static_cast<DelayHeap*>(h)->add(id, weight, deadline);
}

int kn_heap_cancel(void* h, int64_t id) {
    return static_cast<DelayHeap*>(h)->cancel(id);
}

void kn_heap_promote(void* h, double now) {
    static_cast<DelayHeap*>(h)->promote(now);
}

int kn_heap_pop_ready(void* h, int64_t* out, int max_out) {
    return static_cast<DelayHeap*>(h)->pop_ready(out, max_out);
}

double kn_heap_next_deadline(void* h) {
    return static_cast<DelayHeap*>(h)->next_deadline();
}

int kn_heap_ready_count(void* h) {
    return static_cast<DelayHeap*>(h)->ready_count();
}

int kn_heap_size(void* h) { return static_cast<DelayHeap*>(h)->size(); }

// Batched FNV-1a 64: hash n strings packed into buf at offs/lens.
void kn_fnv1a64_batch(const char* buf, const int64_t* offs,
                      const int64_t* lens, int n, uint64_t* out) {
    for (int i = 0; i < n; ++i) {
        uint64_t hash = 14695981039346656037ull;
        const char* p = buf + offs[i];
        for (int64_t j = 0; j < lens[i]; ++j) {
            hash ^= static_cast<unsigned char>(p[j]);
            hash *= 1099511628211ull;
        }
        out[i] = hash;
    }
}

}  // extern "C"
