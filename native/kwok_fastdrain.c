/* kwok_fastdrain — CPython extension for the device drain's per-row
 * hot loops (VERDICT r02 next-#1: C-backed substitution + columnar
 * store commit so per-op dicts/copies disappear).
 *
 * Everything here is a drop-in accelerator for a pure-Python
 * equivalent that stays in-tree (engine/render_plan.py,
 * cluster/store.py, controllers/device_player.py); when the toolchain
 * is missing the Python paths run instead.
 *
 * Functions:
 *   build(comp, vals)                -> patch        (render_plan._build)
 *   status_commit(objects, items, rv_start, namespaced, ev_cls)
 *                                    -> (results, evs, last_rv)
 *   filter_stale(evs, rows, written) -> [ev, ...]    (self-echo drop)
 *   cache_apply(cache, evs)          -> None         (informer mirror)
 *   fast_group(...)                  -> (noops, slow_rows)  (drain loop)
 *   confirm_batch(...)               -> (n_ok, releases, fallbacks)
 *
 * Types:
 *   WatchEvent — slot-backed (type, object, rv) event; swapped in for
 *   the Python dataclass by cluster/store.py so status_commit can
 *   allocate events without a Python-level __init__ call per row.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stddef.h>
#include <stdlib.h>

/* Py_T_* member-def names are 3.12+; map to the structmember.h
 * spellings on older CPythons so the extension builds on 3.10/3.11. */
#if PY_VERSION_HEX < 0x030c0000
#include <structmember.h>
#ifndef Py_T_OBJECT_EX
#define Py_T_OBJECT_EX T_OBJECT_EX
#endif
#ifndef Py_T_LONGLONG
#define Py_T_LONGLONG T_LONGLONG
#endif
#endif

static PyObject *s_metadata, *s_namespace, *s_name, *s_resourceVersion,
    *s_status, *s_MODIFIED, *s_DELETED, *s_default, *s_empty, *s_type,
    *s_object, *s_spec, *s_labels, *s_annotations, *s_ownerReferences,
    *s_deletionTimestamp, *s_finalizers;

/* ------------------------------------------------------------ WatchEvent */

typedef struct {
    PyObject_HEAD
    PyObject *type;
    PyObject *object;
    long long rv;
} FastEvent;

static PyTypeObject FastEventType; /* fwd */

static PyObject *
fastevent_new(PyTypeObject *tp, PyObject *args, PyObject *kwds)
{
    static char *kwlist[] = {"type", "object", "rv", NULL};
    PyObject *type, *object;
    long long rv = 0;
    if (!PyArg_ParseTupleAndKeywords(args, kwds, "OO|L", kwlist, &type,
                                     &object, &rv))
        return NULL;
    FastEvent *ev = (FastEvent *)tp->tp_alloc(tp, 0);
    if (!ev)
        return NULL;
    Py_INCREF(type);
    ev->type = type;
    Py_INCREF(object);
    ev->object = object;
    ev->rv = rv;
    return (PyObject *)ev;
}

static void
fastevent_dealloc(FastEvent *ev)
{
    Py_XDECREF(ev->type);
    Py_XDECREF(ev->object);
    Py_TYPE(ev)->tp_free((PyObject *)ev);
}

static PyObject *
fastevent_richcompare(PyObject *a, PyObject *b, int op)
{
    if (op != Py_EQ && op != Py_NE)
        Py_RETURN_NOTIMPLEMENTED;
    if (!PyObject_TypeCheck(a, &FastEventType) ||
        !PyObject_TypeCheck(b, &FastEventType))
        Py_RETURN_NOTIMPLEMENTED;
    FastEvent *x = (FastEvent *)a, *y = (FastEvent *)b;
    int eq = x->rv == y->rv;
    if (eq) {
        eq = PyObject_RichCompareBool(x->type, y->type, Py_EQ);
        if (eq < 0)
            return NULL;
    }
    if (eq) {
        eq = PyObject_RichCompareBool(x->object, y->object, Py_EQ);
        if (eq < 0)
            return NULL;
    }
    if (op == Py_NE)
        eq = !eq;
    if (eq)
        Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static PyMemberDef fastevent_members[] = {
    {"type", Py_T_OBJECT_EX, offsetof(FastEvent, type), 0, NULL},
    {"object", Py_T_OBJECT_EX, offsetof(FastEvent, object), 0, NULL},
    {"rv", Py_T_LONGLONG, offsetof(FastEvent, rv), 0, NULL},
    {NULL},
};

static PyTypeObject FastEventType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "kwok_fastdrain.WatchEvent",
    .tp_basicsize = sizeof(FastEvent),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = fastevent_new,
    .tp_dealloc = (destructor)fastevent_dealloc,
    .tp_richcompare = fastevent_richcompare,
    .tp_members = fastevent_members,
};

/* ---------------------------------------------------------------- build */

static PyObject *
build_node(PyObject *comp, PyObject *vals)
{
    PyObject *kind = PyTuple_GET_ITEM(comp, 0);
    PyObject *orig = PyTuple_GET_ITEM(comp, 1);
    PyObject *items = PyTuple_GET_ITEM(comp, 2);
    const char *k = PyUnicode_AsUTF8(kind);
    if (!k)
        return NULL;
    switch (k[0]) {
    case 'x': { /* exact token: typed substitution */
        PyObject *v = PyDict_GetItemWithError(vals, orig);
        if (!v) {
            if (!PyErr_Occurred())
                PyErr_SetObject(PyExc_KeyError, orig);
            return NULL;
        }
        Py_INCREF(v);
        return v;
    }
    case 's': { /* string leaf with embedded tokens */
        PyObject *cur = orig;
        Py_INCREF(cur);
        Py_ssize_t n = PyList_GET_SIZE(items);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *tok = PyList_GET_ITEM(items, i);
            PyObject *v = PyDict_GetItemWithError(vals, tok);
            if (!v) {
                Py_DECREF(cur);
                if (!PyErr_Occurred())
                    PyErr_SetObject(PyExc_KeyError, tok);
                return NULL;
            }
            PyObject *vs = PyObject_Str(v);
            if (!vs) {
                Py_DECREF(cur);
                return NULL;
            }
            PyObject *next = PyUnicode_Replace(cur, tok, vs, -1);
            Py_DECREF(vs);
            Py_DECREF(cur);
            if (!next)
                return NULL;
            cur = next;
        }
        return cur;
    }
    case 'd': {
        PyObject *out = PyDict_Copy(orig);
        if (!out)
            return NULL;
        Py_ssize_t n = PyList_GET_SIZE(items);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *pair = PyList_GET_ITEM(items, i);
            PyObject *key = PyTuple_GET_ITEM(pair, 0);
            PyObject *child = PyTuple_GET_ITEM(pair, 1);
            PyObject *v = build_node(child, vals);
            if (!v || PyDict_SetItem(out, key, v) < 0) {
                Py_XDECREF(v);
                Py_DECREF(out);
                return NULL;
            }
            Py_DECREF(v);
        }
        return out;
    }
    case 'l': {
        PyObject *out = PySequence_List(orig);
        if (!out)
            return NULL;
        Py_ssize_t n = PyList_GET_SIZE(items);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *pair = PyList_GET_ITEM(items, i);
            Py_ssize_t idx = PyLong_AsSsize_t(PyTuple_GET_ITEM(pair, 0));
            PyObject *child = PyTuple_GET_ITEM(pair, 1);
            PyObject *v = build_node(child, vals);
            if (!v) {
                Py_DECREF(out);
                return NULL;
            }
            if (PyList_SetItem(out, idx, v) < 0) { /* steals v */
                Py_DECREF(out);
                return NULL;
            }
        }
        return out;
    }
    default:
        PyErr_SetString(PyExc_ValueError, "bad comp node kind");
        return NULL;
    }
}

static PyObject *
py_build(PyObject *self, PyObject *args)
{
    PyObject *comp, *vals;
    if (!PyArg_ParseTuple(args, "OO", &comp, &vals))
        return NULL;
    return build_node(comp, vals);
}

/* -------------------------------------------------------- status_commit */

static PyObject *
py_status_commit(PyObject *self, PyObject *args)
{
    PyObject *objects, *items, *ev_cls;
    long long rv;
    int namespaced;
    if (!PyArg_ParseTuple(args, "OOLpO", &objects, &items, &rv, &namespaced,
                          &ev_cls))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(items);
    PyObject *results = PyList_New(0);
    PyObject *evs = PyList_New(0);
    if (!results || !evs)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(items, i); /* (ns, name, status) */
        PyObject *ns = PyTuple_GET_ITEM(item, 0);
        PyObject *name = PyTuple_GET_ITEM(item, 1);
        PyObject *status = PyTuple_GET_ITEM(item, 2);
        PyObject *keyns;
        if (namespaced)
            keyns = (ns != Py_None && PyObject_IsTrue(ns)) ? ns : s_default;
        else
            keyns = s_empty;
        PyObject *key = PyTuple_Pack(2, keyns, name);
        if (!key)
            goto fail;
        PyObject *cur = PyDict_GetItemWithError(objects, key);
        if (!cur) {
            Py_DECREF(key);
            if (PyErr_Occurred())
                goto fail;
            if (PyList_Append(results, Py_None) < 0)
                goto fail;
            continue;
        }
        PyObject *newobj = PyDict_Copy(cur);
        if (!newobj) {
            Py_DECREF(key);
            goto fail;
        }
        if (PyDict_SetItem(newobj, s_status, status) < 0)
            goto fail_new;
        PyObject *meta = PyDict_GetItemWithError(cur, s_metadata);
        if (!meta) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_KeyError, "metadata");
            goto fail_new;
        }
        PyObject *nm = PyDict_Copy(meta);
        if (!nm)
            goto fail_new;
        rv += 1;
        PyObject *rvs = PyUnicode_FromFormat("%lld", rv);
        if (!rvs || PyDict_SetItem(nm, s_resourceVersion, rvs) < 0) {
            Py_XDECREF(rvs);
            Py_DECREF(nm);
            goto fail_new;
        }
        Py_DECREF(rvs);
        if (PyDict_SetItem(newobj, s_metadata, nm) < 0) {
            Py_DECREF(nm);
            goto fail_new;
        }
        Py_DECREF(nm);
        if (PyDict_SetItem(objects, key, newobj) < 0)
            goto fail_new;
        Py_DECREF(key);
        key = NULL;
        {
            PyObject *ev;
            if (ev_cls == (PyObject *)&FastEventType) {
                /* direct slot alloc: no Python __init__ per row */
                FastEvent *fe = PyObject_New(FastEvent, &FastEventType);
                if (!fe)
                    goto fail_new2;
                Py_INCREF(s_MODIFIED);
                fe->type = s_MODIFIED;
                Py_INCREF(newobj);
                fe->object = newobj;
                fe->rv = rv;
                ev = (PyObject *)fe;
            } else {
                ev = PyObject_CallFunction(ev_cls, "OOL", s_MODIFIED,
                                           newobj, rv);
            }
            if (!ev)
                goto fail_new2;
            if (PyList_Append(evs, ev) < 0) {
                Py_DECREF(ev);
                goto fail_new2;
            }
            Py_DECREF(ev);
        }
        {
            PyObject *res = Py_BuildValue("(LO)", rv, newobj);
            if (!res)
                goto fail_new2;
            if (PyList_Append(results, res) < 0) {
                Py_DECREF(res);
                goto fail_new2;
            }
            Py_DECREF(res);
        }
        Py_DECREF(newobj);
        continue;
    fail_new:
        Py_DECREF(key);
    fail_new2:
        Py_DECREF(newobj);
        goto fail;
    }
    return Py_BuildValue("(NNL)", results, evs, rv);
fail:
    Py_XDECREF(results);
    Py_XDECREF(evs);
    return NULL;
}

/* ------------------------------------------------- status_commit_inplace */

/* The zero-copy commit lane: when the store has no event consumers for
 * this batch (the only live watcher is the excluded self-consumer),
 * there is nobody to hand instances to — so the stored object is
 * mutated IN PLACE (status replaced, resourceVersion bumped) with no
 * object/metadata copies, no event allocation, and no history append.
 * The store records a gap marker instead; watch resumes older than it
 * get Expired and re-list (legal watch semantics).
 *
 *   status_commit_inplace(objects, items, rv_start, namespaced)
 *     -> (results, last_rv)
 */
static PyObject *
py_status_commit_inplace(PyObject *self, PyObject *args)
{
    PyObject *objects, *items;
    long long rv;
    int namespaced;
    if (!PyArg_ParseTuple(args, "OOLp", &objects, &items, &rv, &namespaced))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(items);
    PyObject *results = PyList_New(0);
    if (!results)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(items, i); /* (ns, name, status) */
        PyObject *ns = PyTuple_GET_ITEM(item, 0);
        PyObject *name = PyTuple_GET_ITEM(item, 1);
        PyObject *status = PyTuple_GET_ITEM(item, 2);
        PyObject *keyns;
        if (namespaced)
            keyns = (ns != Py_None && PyObject_IsTrue(ns)) ? ns : s_default;
        else
            keyns = s_empty;
        PyObject *key = PyTuple_Pack(2, keyns, name);
        if (!key)
            goto fail;
        PyObject *cur = PyDict_GetItemWithError(objects, key);
        Py_DECREF(key);
        if (!cur) {
            if (PyErr_Occurred())
                goto fail;
            if (PyList_Append(results, Py_None) < 0)
                goto fail;
            continue;
        }
        PyObject *meta = PyDict_GetItemWithError(cur, s_metadata);
        if (!meta || !PyDict_Check(meta)) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_KeyError, "metadata");
            goto fail;
        }
        rv += 1;
        PyObject *rvs = PyUnicode_FromFormat("%lld", rv);
        if (!rvs)
            goto fail;
        if (PyDict_SetItem(meta, s_resourceVersion, rvs) < 0) {
            Py_DECREF(rvs);
            goto fail;
        }
        Py_DECREF(rvs);
        if (PyDict_SetItem(cur, s_status, status) < 0)
            goto fail;
        {
            PyObject *res = Py_BuildValue("(LO)", rv, cur);
            if (!res)
                goto fail;
            if (PyList_Append(results, res) < 0) {
                Py_DECREF(res);
                goto fail;
            }
            Py_DECREF(res);
        }
    }
    return Py_BuildValue("(NL)", results, rv);
fail:
    Py_DECREF(results);
    return NULL;
}

/* --------------------------------------------------------- filter_stale */

/* parse a resourceVersion string to int; returns 0 and sets *ok=0 when
 * non-numeric */
static long long
rv_to_ll(PyObject *rvs, int *ok)
{
    *ok = 0;
    if (!rvs || !PyUnicode_Check(rvs))
        return 0;
    const char *sp = PyUnicode_AsUTF8(rvs);
    if (!sp || !*sp)
        return 0;
    char *end = NULL;
    long long v = strtoll(sp, &end, 10);
    if (end && *end == '\0')
        *ok = 1;
    return v;
}

static PyObject *
py_filter_stale(PyObject *self, PyObject *args)
{
    PyObject *evs, *rows, *written;
    if (!PyArg_ParseTuple(args, "OOO", &evs, &rows, &written))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(evs);
    PyObject *out = PyList_New(0);
    if (!out)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *ev = PyList_GET_ITEM(evs, i);
        int keep = 1;
        PyObject *type = PyObject_GetAttr(ev, s_type);
        if (!type)
            goto err;
        int is_mod = PyUnicode_Check(type) &&
                     PyUnicode_Compare(type, s_MODIFIED) == 0;
        Py_DECREF(type);
        if (is_mod) {
            PyObject *obj = PyObject_GetAttr(ev, s_object);
            if (!obj)
                goto err;
            PyObject *meta = PyDict_GetItemWithError(obj, s_metadata);
            if (meta && PyDict_Check(meta)) {
                PyObject *ns = PyDict_GetItemWithError(meta, s_namespace);
                PyObject *name = PyDict_GetItemWithError(meta, s_name);
                if (!ns || ns == Py_None)
                    ns = s_empty;
                if (!name || name == Py_None)
                    name = s_empty;
                PyObject *key = PyTuple_Pack(2, ns, name);
                if (!key) {
                    Py_DECREF(obj);
                    goto err;
                }
                PyObject *row = PyDict_GetItemWithError(rows, key);
                Py_DECREF(key);
                if (row) {
                    Py_ssize_t ridx = PyLong_AsSsize_t(row);
                    PyObject *last =
                        (ridx >= 0 && ridx < PyList_GET_SIZE(written))
                            ? PyList_GET_ITEM(written, ridx)
                            : NULL;
                    if (last && last != Py_None) {
                        PyObject *rvs =
                            PyDict_GetItemWithError(meta, s_resourceVersion);
                        if (rvs && PyUnicode_Check(rvs) &&
                            PyUnicode_Check(last)) {
                            if (PyUnicode_Compare(rvs, last) == 0) {
                                keep = 0;
                            } else {
                                int ok1, ok2;
                                long long a = rv_to_ll(rvs, &ok1);
                                long long b = rv_to_ll(last, &ok2);
                                if (ok1 && ok2 && a <= b)
                                    keep = 0;
                            }
                        }
                    }
                }
            }
            Py_DECREF(obj);
        }
        if (PyErr_Occurred())
            goto err;
        if (keep && PyList_Append(out, ev) < 0)
            goto err;
    }
    return out;
err:
    Py_DECREF(out);
    return NULL;
}

/* ----------------------------------------------------------- fast_group */

/* Per-row drain loop for one (stage, sig) group on the columnar fast
 * path (mirror of the Python loop in
 * controllers/device_player.py::_drain_tick):
 *
 *   fast_group(objects, rows, s_idx, comp, bound, vals_cache,
 *              row_vals_cb, check_noop, has_null, all_top_plain,
 *              top_plain, merge_cb, fast_rows, fast_items)
 *     -> (noop_count, slow_rows)
 *
 * Per row: resolve (or compute via row_vals_cb) the sentinel vals,
 * build the patch, merge it onto the current status (wholesale-replace
 * shortcut when the plan allows; merge_cb = apply_merge_patch
 * otherwise), optionally drop pure no-ops, and append
 * (ns, name, new_status) to fast_items.  Rows whose build/merge raises
 * land in slow_rows for the per-row fallback path. */
static PyObject *
py_fast_group(PyObject *self, PyObject *args)
{
    PyObject *objects, *rows, *s_idx, *comp, *bound, *vals_cache,
        *row_vals_cb, *top_plain, *merge_cb, *fast_rows, *fast_items;
    int check_noop, has_null, all_top_plain;
    if (!PyArg_ParseTuple(args, "OOOOOOOiiiOOOO", &objects, &rows, &s_idx,
                          &comp, &bound, &vals_cache, &row_vals_cb,
                          &check_noop, &has_null, &all_top_plain, &top_plain,
                          &merge_cb, &fast_rows, &fast_items))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(rows);
    long long noops = 0;
    PyObject *slow_rows = PyList_New(0);
    if (!slow_rows)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *row_obj = PyList_GET_ITEM(rows, i);
        Py_ssize_t row = PyLong_AsSsize_t(row_obj);
        if (row < 0 && PyErr_Occurred())
            goto err;
        PyObject *obj = PyList_GET_ITEM(objects, row);
        if (obj == Py_None)
            continue;
        PyObject *patch; /* owned */
        if (comp == Py_None) {
            patch = bound; /* tick-static: shared by rows */
            Py_INCREF(patch);
        } else {
            /* vals_cache is row-indexed (caller guarantees length >=
             * capacity; bounds-checked anyway — an IndexError must not
             * become a use-after-free) */
            if (row >= PyList_GET_SIZE(vals_cache)) {
                PyErr_SetString(PyExc_IndexError,
                                "vals_cache shorter than row index");
                goto err;
            }
            PyObject *rowc = PyList_GET_ITEM(vals_cache, row);
            if (rowc == Py_None) {
                rowc = PyDict_New();
                if (!rowc)
                    goto err;
                Py_INCREF(rowc); /* keep ours across the steal */
                if (PyList_SetItem(vals_cache, row, rowc) < 0) {
                    Py_DECREF(rowc);
                    goto err;
                }
                Py_DECREF(rowc); /* the list holds it now */
            }
            PyObject *vals = PyDict_GetItemWithError(rowc, s_idx);
            if (!vals) {
                if (PyErr_Occurred())
                    goto err;
                vals = PyObject_CallFunctionObjArgs(row_vals_cb, obj, NULL);
                if (!vals) {
                    PyErr_Clear();
                    if (PyList_Append(slow_rows, row_obj) < 0)
                        goto err;
                    continue;
                }
                if (PyDict_SetItem(rowc, s_idx, vals) < 0) {
                    Py_DECREF(vals);
                    goto err;
                }
                Py_DECREF(vals); /* rowc keeps it alive */
            }
            patch = build_node(comp, vals);
            if (!patch) {
                PyErr_Clear();
                if (PyList_Append(slow_rows, row_obj) < 0)
                    goto err;
                continue;
            }
        }
        PyObject *cur = PyDict_GetItemWithError(obj, s_status); /* borrowed */
        if (!cur && PyErr_Occurred()) {
            Py_DECREF(patch);
            goto err;
        }
        if (cur == Py_None)
            cur = NULL;
        PyObject *new_status; /* owned */
        if (!cur || (PyDict_Check(cur) && PyDict_GET_SIZE(cur) == 0)) {
            new_status = patch;
            Py_INCREF(new_status);
            if (check_noop && PyDict_Check(patch) &&
                PyDict_GET_SIZE(patch) == 0) {
                noops++;
                Py_DECREF(new_status);
                Py_DECREF(patch);
                continue;
            }
        } else if (!has_null && all_top_plain && PyDict_Check(cur)) {
            int subset = 1;
            Py_ssize_t pos = 0;
            PyObject *k, *v;
            while (PyDict_Next(cur, &pos, &k, &v)) {
                int in = PySet_Contains(top_plain, k);
                if (in < 0) {
                    Py_DECREF(patch);
                    goto err;
                }
                if (!in) {
                    subset = 0;
                    break;
                }
            }
            if (subset) {
                new_status = patch;
                Py_INCREF(new_status);
            } else {
                new_status = PyDict_Copy(cur);
                if (!new_status || PyDict_Update(new_status, patch) < 0) {
                    Py_XDECREF(new_status);
                    Py_DECREF(patch);
                    goto err;
                }
            }
        } else {
            new_status =
                PyObject_CallFunctionObjArgs(merge_cb, cur, patch, NULL);
            if (!new_status) {
                PyErr_Clear();
                Py_DECREF(patch);
                if (PyList_Append(slow_rows, row_obj) < 0)
                    goto err;
                continue;
            }
        }
        Py_DECREF(patch);
        if (check_noop && cur) {
            int same = PyObject_RichCompareBool(new_status, cur, Py_EQ);
            if (same < 0) {
                Py_DECREF(new_status);
                goto err;
            }
            if (same) {
                noops++;
                Py_DECREF(new_status);
                continue;
            }
        }
        PyObject *meta = PyDict_GetItemWithError(obj, s_metadata);
        if (!meta || !PyDict_Check(meta)) {
            Py_DECREF(new_status);
            if (PyErr_Occurred())
                goto err;
            continue;
        }
        PyObject *ns = PyDict_GetItemWithError(meta, s_namespace);
        if (!ns) {
            if (PyErr_Occurred()) {
                Py_DECREF(new_status);
                goto err;
            }
            ns = Py_None;
        }
        PyObject *name = PyDict_GetItemWithError(meta, s_name);
        if (!name || name == Py_None) {
            if (PyErr_Occurred()) {
                Py_DECREF(new_status);
                goto err;
            }
            name = s_empty;
        }
        PyObject *item = PyTuple_Pack(3, ns, name, new_status);
        Py_DECREF(new_status);
        if (!item)
            goto err;
        if (PyList_Append(fast_items, item) < 0) {
            Py_DECREF(item);
            goto err;
        }
        Py_DECREF(item);
        if (PyList_Append(fast_rows, row_obj) < 0)
            goto err;
    }
    return Py_BuildValue("(LN)", noops, slow_rows);
err:
    Py_DECREF(slow_rows);
    return NULL;
}

/* ----------------------------------------------------------- fused_group */

/* The one-pass drain: build + in-place store commit + confirm for one
 * (stage, sig) chunk on the zero-copy lane (device_player._drain_tick;
 * the store grants the lane — its mutex held — via
 * ResourceStore.status_lane).  Fuses what fast_group + apply_status_batch
 * + confirm_batch did in three passes, so each row's dict graph is
 * touched once while hot, and the intermediate (ns, name, status)
 * tuples, results lists and the second key probe disappear.
 *
 *   fused_group(objects, keys, rows, s_idx, comp, bound, vals_cache,
 *               row_vals_cb, all_top_plain, top_plain, store_objects,
 *               rv_start, written)
 *     -> (n_ok, new_rv, slow_rows, release_rows, skipped)
 *
 * Caller guarantees (gated in device_player): plan.has_now (no no-op
 * check needed — timestamps strictly increase) and not plan.has_null
 * (merge is wholesale replace or top-level dict update).  Per row the
 * store commit applies only when the row mirror IS the stored instance
 * (``store_objects[keys[row]] is objects[row]``); a mirror gone stale
 * under a concurrent external write is skipped (counted in ``skipped``)
 * — the informer event for that write refreshes the row next tick, and
 * committing through a stale mirror would strand the transition in an
 * object the store no longer owns.  Missing keys land in release_rows
 * (NotFound).  Build failures land in slow_rows for the per-row path. */
static PyObject *
py_fused_group(PyObject *self, PyObject *args)
{
    PyObject *objects, *keys, *rows, *s_idx, *comp, *bound, *vals_cache,
        *row_vals_cb, *top_plain, *store_objects, *written;
    int all_top_plain;
    long long rv;
    if (!PyArg_ParseTuple(args, "OOOOOOOOiOOLO", &objects, &keys, &rows,
                          &s_idx, &comp, &bound, &vals_cache, &row_vals_cb,
                          &all_top_plain, &top_plain, &store_objects, &rv,
                          &written))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(rows);
    long long n_ok = 0, skipped = 0;
    PyObject *slow_rows = PyList_New(0);
    PyObject *release_rows = PyList_New(0);
    if (!slow_rows || !release_rows)
        goto err;
    Py_ssize_t n_objects = PyList_GET_SIZE(objects);
    Py_ssize_t n_keys = PyList_GET_SIZE(keys);
    for (Py_ssize_t i = 0; i < n; i++) {
        /* prefetch ahead: the row list is known, so the object-list
         * slots and the object headers for upcoming rows can start
         * their DRAM fetches now (the drain is memory-bound at 1M
         * rows: every row's dict graph is cold) */
        if (i + 8 < n) {
            PyObject *r8 = PyList_GET_ITEM(rows, i + 8);
            Py_ssize_t v8 = PyLong_AsSsize_t(r8);
            if (v8 >= 0 && v8 < n_objects) {
                __builtin_prefetch(&((PyListObject *)objects)->ob_item[v8]);
                if (v8 < n_keys)
                    __builtin_prefetch(&((PyListObject *)keys)->ob_item[v8]);
            }
        }
        if (i + 4 < n) {
            PyObject *r4 = PyList_GET_ITEM(rows, i + 4);
            Py_ssize_t v4 = PyLong_AsSsize_t(r4);
            if (v4 >= 0 && v4 < n_objects)
                __builtin_prefetch(PyList_GET_ITEM(objects, v4));
        }
        PyErr_Clear(); /* PyLong_AsSsize_t above cannot fail on ints */
        PyObject *row_obj = PyList_GET_ITEM(rows, i);
        Py_ssize_t row = PyLong_AsSsize_t(row_obj);
        if (row < 0 && PyErr_Occurred())
            goto err;
        if (row >= n_objects)
            continue;
        PyObject *obj = PyList_GET_ITEM(objects, row);
        if (obj == Py_None)
            continue;
        PyObject *key = (row < n_keys) ? PyList_GET_ITEM(keys, row) : Py_None;
        if (key == Py_None) {
            if (PyList_Append(slow_rows, row_obj) < 0)
                goto err;
            continue;
        }
        PyObject *cur_store = PyDict_GetItemWithError(store_objects, key);
        if (!cur_store) {
            if (PyErr_Occurred())
                goto err;
            if (PyList_Append(release_rows, row_obj) < 0)
                goto err;
            continue;
        }
        if (cur_store != obj) {
            /* The row mirror can be a deep COPY of the stored object
             * (slow-path patch echoes return copies): same logical
             * state, different instance.  Under the store lock, equal
             * resourceVersions prove equal state — adopt the stored
             * instance into the mirror (re-syncing future rounds to
             * pointer equality) and commit through it.  A differing rv
             * is a genuinely stale mirror (concurrent external write):
             * skip; the informer event refreshes the row. */
            PyObject *om = PyDict_GetItemWithError(obj, s_metadata);
            PyObject *sm = PyDict_GetItemWithError(cur_store, s_metadata);
            if (PyErr_Occurred())
                goto err;
            PyObject *orv = om && PyDict_Check(om)
                                ? PyDict_GetItemWithError(om, s_resourceVersion)
                                : NULL;
            PyObject *srv = sm && PyDict_Check(sm)
                                ? PyDict_GetItemWithError(sm, s_resourceVersion)
                                : NULL;
            if (PyErr_Occurred())
                goto err;
            if (!orv || !srv || !PyUnicode_Check(orv) ||
                !PyUnicode_Check(srv) || PyUnicode_Compare(orv, srv) != 0) {
                if (PyErr_Occurred())
                    goto err;
                skipped++;
                continue;
            }
            Py_INCREF(cur_store);
            if (PyList_SetItem(objects, row, cur_store) < 0) /* steals */
                goto err;
            obj = cur_store;
        }
        PyObject *patch; /* owned */
        if (comp == Py_None) {
            patch = bound;
            Py_INCREF(patch);
        } else {
            if (row >= PyList_GET_SIZE(vals_cache)) {
                PyErr_SetString(PyExc_IndexError,
                                "vals_cache shorter than row index");
                goto err;
            }
            PyObject *rowc = PyList_GET_ITEM(vals_cache, row);
            if (rowc == Py_None) {
                rowc = PyDict_New();
                if (!rowc)
                    goto err;
                Py_INCREF(rowc);
                if (PyList_SetItem(vals_cache, row, rowc) < 0) {
                    Py_DECREF(rowc);
                    goto err;
                }
                Py_DECREF(rowc);
            }
            PyObject *vals = PyDict_GetItemWithError(rowc, s_idx);
            if (!vals) {
                if (PyErr_Occurred())
                    goto err;
                vals = PyObject_CallFunctionObjArgs(row_vals_cb, obj, NULL);
                if (!vals) {
                    PyErr_Clear();
                    if (PyList_Append(slow_rows, row_obj) < 0)
                        goto err;
                    continue;
                }
                if (PyDict_SetItem(rowc, s_idx, vals) < 0) {
                    Py_DECREF(vals);
                    goto err;
                }
                Py_DECREF(vals);
            }
            patch = build_node(comp, vals);
            if (!patch) {
                PyErr_Clear();
                if (PyList_Append(slow_rows, row_obj) < 0)
                    goto err;
                continue;
            }
        }
        PyObject *cur = PyDict_GetItemWithError(obj, s_status);
        if (!cur && PyErr_Occurred()) {
            Py_DECREF(patch);
            goto err;
        }
        if (cur == Py_None)
            cur = NULL;
        PyObject *new_status; /* owned */
        if (!cur || (PyDict_Check(cur) && PyDict_GET_SIZE(cur) == 0)) {
            new_status = patch;
            Py_INCREF(new_status);
        } else if (all_top_plain && PyDict_Check(cur)) {
            int subset = 1;
            Py_ssize_t pos = 0;
            PyObject *k, *v;
            while (PyDict_Next(cur, &pos, &k, &v)) {
                int in = PySet_Contains(top_plain, k);
                if (in < 0) {
                    Py_DECREF(patch);
                    goto err;
                }
                if (!in) {
                    subset = 0;
                    break;
                }
            }
            if (subset) {
                new_status = patch;
                Py_INCREF(new_status);
            } else {
                new_status = PyDict_Copy(cur);
                if (!new_status || PyDict_Update(new_status, patch) < 0) {
                    Py_XDECREF(new_status);
                    Py_DECREF(patch);
                    goto err;
                }
            }
        } else {
            /* non-dict or mixed shapes are excluded by the caller's
             * gate (not has_null, all_top_plain) — but a hand-mutated
             * status can still surprise; send it to the slow path */
            Py_DECREF(patch);
            if (PyList_Append(slow_rows, row_obj) < 0)
                goto err;
            continue;
        }
        Py_DECREF(patch);
        /* in-place commit: bump rv, splice status — the mirror IS the
         * stored instance (checked above), so there is no confirm pass */
        PyObject *meta = PyDict_GetItemWithError(obj, s_metadata);
        if (!meta || !PyDict_Check(meta)) {
            Py_DECREF(new_status);
            if (PyErr_Occurred())
                goto err;
            continue;
        }
        rv += 1;
        PyObject *rvs = PyUnicode_FromFormat("%lld", rv);
        if (!rvs) {
            Py_DECREF(new_status);
            goto err;
        }
        if (PyDict_SetItem(meta, s_resourceVersion, rvs) < 0 ||
            PyDict_SetItem(obj, s_status, new_status) < 0) {
            Py_DECREF(rvs);
            Py_DECREF(new_status);
            goto err;
        }
        Py_DECREF(new_status);
        if (row < PyList_GET_SIZE(written)) {
            if (PyList_SetItem(written, row, rvs) < 0) /* steals rvs */
                goto err;
        } else {
            Py_DECREF(rvs);
        }
        n_ok++;
    }
    return Py_BuildValue("(LLNNL)", n_ok, rv, slow_rows, release_rows,
                         skipped);
err:
    Py_XDECREF(slow_rows);
    Py_XDECREF(release_rows);
    return NULL;
}

/* -------------------------------------------------------- confirm_batch */

/* missing-treated-as-None equality with a pointer shortcut: the store's
 * status commit shares every unchanged subtree, so the common case is
 * pointer-equal */
static int
eq_field(PyObject *a, PyObject *b)
{
    if (!a)
        a = Py_None;
    if (!b)
        b = Py_None;
    if (a == b)
        return 1;
    return PyObject_RichCompareBool(a, b, Py_EQ);
}

/* Post-commit accounting for the columnar drain (mirror of the Python
 * loop after _store_status_batch in device_player._drain_tick):
 *
 *   confirm_batch(results, rows, items, objects, written, cache)
 *     -> (n_ok, releases, fallback_idx)
 *
 * Per result: None -> the object is gone, its (ns, name) key lands in
 * releases; (rv, obj) -> record the written resourceVersion, adopt the
 * store's echo into the row mirror when nothing beyond status changed
 * (pointer-first compare on spec/labels/annotations/ownerReferences/
 * deletionTimestamp/finalizers), else report the result index in
 * fallback_idx for a full host re-extract.  ``cache`` (may be None) is
 * the informer mirror to maintain directly when the store excluded our
 * own watcher from event delivery; entries only move forward in
 * resourceVersion. */
static PyObject *
py_confirm_batch(PyObject *self, PyObject *args)
{
    PyObject *results, *rows, *items, *objects, *written, *cache;
    if (!PyArg_ParseTuple(args, "OOOOOO", &results, &rows, &items, &objects,
                          &written, &cache))
        return NULL;
    if (cache == Py_None)
        cache = NULL;
    Py_ssize_t n = PyList_GET_SIZE(rows);
    long long n_ok = 0;
    PyObject *releases = PyList_New(0);
    PyObject *fallbacks = PyList_New(0);
    if (!releases || !fallbacks)
        goto err;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *res = PyList_GET_ITEM(results, i);
        PyObject *row_obj = PyList_GET_ITEM(rows, i);
        if (res == Py_None) {
            PyObject *item = PyList_GET_ITEM(items, i);
            PyObject *ns = PyTuple_GET_ITEM(item, 0);
            int truthy = (ns != Py_None) ? PyObject_IsTrue(ns) : 0;
            if (truthy < 0)
                goto err;
            PyObject *key = PyTuple_Pack(2, truthy ? ns : s_empty,
                                         PyTuple_GET_ITEM(item, 1));
            if (!key)
                goto err;
            if (PyList_Append(releases, key) < 0) {
                Py_DECREF(key);
                goto err;
            }
            Py_DECREF(key);
            continue;
        }
        if (res == Py_False)
            continue; /* store error, surfaced already */
        PyObject *rv_obj = PyTuple_GET_ITEM(res, 0);
        PyObject *new_obj = PyTuple_GET_ITEM(res, 1);
        n_ok++;
        PyObject *nm = PyDict_GetItemWithError(new_obj, s_metadata);
        if (!nm || !PyDict_Check(nm)) {
            if (PyErr_Occurred())
                goto err;
            continue;
        }
        PyObject *rvs = PyDict_GetItemWithError(nm, s_resourceVersion);
        if (!rvs) {
            if (PyErr_Occurred())
                goto err;
            rvs = Py_None;
        }
        Py_ssize_t row = PyLong_AsSsize_t(row_obj);
        if (row < 0 && PyErr_Occurred())
            goto err;
        /* written is row-indexed (list), like vals_cache */
        Py_INCREF(rvs);
        if (PyList_SetItem(written, row, rvs) < 0) /* steals */
            goto err;
        PyObject *old = PyList_GET_ITEM(objects, row);
        if (cache) {
            PyObject *ns = PyDict_GetItemWithError(nm, s_namespace);
            if (!ns || ns == Py_None) {
                if (PyErr_Occurred())
                    goto err;
                ns = s_empty;
            }
            PyObject *name = PyDict_GetItemWithError(nm, s_name);
            if (!name || name == Py_None) {
                if (PyErr_Occurred())
                    goto err;
                name = s_empty;
            }
            PyObject *key = PyTuple_Pack(2, ns, name);
            if (!key)
                goto err;
            /* only move forward: an informer-delivered event for a
             * NEWER write must not be clobbered by this older echo.
             * Pointer shortcut: in steady churn the cache entry IS the
             * row mirror we adopted last tick (we wrote both), so one
             * compare replaces the resourceVersion parse. */
            int write = 1;
            PyObject *curc = PyDict_GetItemWithError(cache, key);
            if (!curc && PyErr_Occurred()) {
                Py_DECREF(key);
                goto err;
            }
            if (curc && curc != old) {
                PyObject *cm = PyDict_GetItemWithError(curc, s_metadata);
                if (cm && PyDict_Check(cm)) {
                    PyObject *crv =
                        PyDict_GetItemWithError(cm, s_resourceVersion);
                    int ok = 0;
                    long long cur_rv = rv_to_ll(crv, &ok);
                    long long new_rv = PyLong_AsLongLong(rv_obj);
                    if (new_rv == -1 && PyErr_Occurred())
                        PyErr_Clear();
                    else if (ok && cur_rv > new_rv)
                        write = 0;
                }
                if (PyErr_Occurred()) {
                    Py_DECREF(key);
                    goto err;
                }
            }
            if (write && PyDict_SetItem(cache, key, new_obj) < 0) {
                Py_DECREF(key);
                goto err;
            }
            Py_DECREF(key);
        }
        if (old == new_obj)
            continue; /* in-place lane: the row mirror IS the store's */
        if (old == Py_None)
            continue;
        PyObject *om = PyDict_GetItemWithError(old, s_metadata);
        if (!om || !PyDict_Check(om)) {
            if (PyErr_Occurred())
                goto err;
            om = NULL;
        }
        int same = eq_field(PyDict_GetItemWithError(old, s_spec),
                            PyDict_GetItemWithError(new_obj, s_spec));
        if (same > 0 && om)
            same = eq_field(PyDict_GetItemWithError(om, s_labels),
                            PyDict_GetItemWithError(nm, s_labels));
        if (same > 0 && om)
            same = eq_field(PyDict_GetItemWithError(om, s_annotations),
                            PyDict_GetItemWithError(nm, s_annotations));
        if (same > 0 && om)
            same = eq_field(PyDict_GetItemWithError(om, s_ownerReferences),
                            PyDict_GetItemWithError(nm, s_ownerReferences));
        if (same > 0 && om)
            same = eq_field(PyDict_GetItemWithError(om, s_deletionTimestamp),
                            PyDict_GetItemWithError(nm, s_deletionTimestamp));
        if (same > 0 && om)
            same = eq_field(PyDict_GetItemWithError(om, s_finalizers),
                            PyDict_GetItemWithError(nm, s_finalizers));
        if (same < 0 || PyErr_Occurred())
            goto err;
        if (same && om) {
            Py_INCREF(new_obj);
            if (PyList_SetItem(objects, row, new_obj) < 0) { /* steals */
                Py_DECREF(new_obj);
                goto err;
            }
        } else {
            PyObject *idx = PyLong_FromSsize_t(i);
            if (!idx)
                goto err;
            if (PyList_Append(fallbacks, idx) < 0) {
                Py_DECREF(idx);
                goto err;
            }
            Py_DECREF(idx);
        }
    }
    return Py_BuildValue("(LNN)", n_ok, releases, fallbacks);
err:
    Py_XDECREF(releases);
    Py_XDECREF(fallbacks);
    return NULL;
}

/* ---------------------------------------------------------- cache_apply */

static PyObject *
py_cache_apply(PyObject *self, PyObject *args)
{
    PyObject *cache, *evs;
    if (!PyArg_ParseTuple(args, "OO", &cache, &evs))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(evs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *ev = PyList_GET_ITEM(evs, i);
        PyObject *type = PyObject_GetAttr(ev, s_type);
        if (!type)
            return NULL;
        PyObject *obj = PyObject_GetAttr(ev, s_object);
        if (!obj) {
            Py_DECREF(type);
            return NULL;
        }
        PyObject *meta = PyDict_GetItemWithError(obj, s_metadata);
        if (!meta || !PyDict_Check(meta)) {
            Py_DECREF(type);
            Py_DECREF(obj);
            if (PyErr_Occurred())
                return NULL;
            continue;
        }
        PyObject *ns = PyDict_GetItemWithError(meta, s_namespace);
        PyObject *name = PyDict_GetItemWithError(meta, s_name);
        if (!ns || ns == Py_None)
            ns = s_empty;
        if (!name || name == Py_None)
            name = s_empty;
        PyObject *key = PyTuple_Pack(2, ns, name);
        if (!key) {
            Py_DECREF(type);
            Py_DECREF(obj);
            return NULL;
        }
        int deleted = PyUnicode_Check(type) &&
                      PyUnicode_Compare(type, s_DELETED) == 0;
        int rc;
        if (deleted) {
            rc = PyDict_DelItem(cache, key);
            if (rc < 0 && PyErr_ExceptionMatches(PyExc_KeyError)) {
                PyErr_Clear();
                rc = 0;
            }
        } else {
            rc = PyDict_SetItem(cache, key, obj);
        }
        Py_DECREF(key);
        Py_DECREF(type);
        Py_DECREF(obj);
        if (rc < 0)
            return NULL;
    }
    Py_RETURN_NONE;
}

/* -------------------------------------------------------------- module */

static PyMethodDef Methods[] = {
    {"build", py_build, METH_VARARGS, "build(comp, vals) -> patch"},
    {"status_commit", py_status_commit, METH_VARARGS,
     "status_commit(objects, items, rv_start, namespaced, ev_cls)"},
    {"filter_stale", py_filter_stale, METH_VARARGS,
     "filter_stale(evs, rows, written) -> fresh events"},
    {"cache_apply", py_cache_apply, METH_VARARGS,
     "cache_apply(cache, evs) -> None"},
    {"fused_group", py_fused_group, METH_VARARGS,
     "fused_group(objects, keys, rows, s_idx, comp, bound, vals_cache, "
     "row_vals_cb, all_top_plain, top_plain, store_objects, rv_start, "
     "written) -> (n_ok, new_rv, slow_rows, release_rows, skipped)"},
    {"fast_group", py_fast_group, METH_VARARGS,
     "fast_group(objects, rows, s_idx, comp, bound, vals_cache, "
     "row_vals_cb, check_noop, has_null, all_top_plain, top_plain, "
     "merge_cb, fast_rows, fast_items) -> (noops, slow_rows)"},
    {"confirm_batch", py_confirm_batch, METH_VARARGS,
     "confirm_batch(results, rows, items, objects, written, cache) -> "
     "(n_ok, releases, fallback_idx)"},
    {"status_commit_inplace", py_status_commit_inplace, METH_VARARGS,
     "status_commit_inplace(objects, items, rv_start, namespaced) -> "
     "(results, last_rv)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "kwok_fastdrain", NULL, -1, Methods,
};

PyMODINIT_FUNC
PyInit_kwok_fastdrain(void)
{
    s_metadata = PyUnicode_InternFromString("metadata");
    s_namespace = PyUnicode_InternFromString("namespace");
    s_name = PyUnicode_InternFromString("name");
    s_resourceVersion = PyUnicode_InternFromString("resourceVersion");
    s_status = PyUnicode_InternFromString("status");
    s_MODIFIED = PyUnicode_InternFromString("MODIFIED");
    s_DELETED = PyUnicode_InternFromString("DELETED");
    s_default = PyUnicode_InternFromString("default");
    s_empty = PyUnicode_InternFromString("");
    s_type = PyUnicode_InternFromString("type");
    s_object = PyUnicode_InternFromString("object");
    s_spec = PyUnicode_InternFromString("spec");
    s_labels = PyUnicode_InternFromString("labels");
    s_annotations = PyUnicode_InternFromString("annotations");
    s_ownerReferences = PyUnicode_InternFromString("ownerReferences");
    s_deletionTimestamp = PyUnicode_InternFromString("deletionTimestamp");
    s_finalizers = PyUnicode_InternFromString("finalizers");
    if (PyType_Ready(&FastEventType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&moduledef);
    if (!m)
        return NULL;
    Py_INCREF(&FastEventType);
    if (PyModule_AddObject(m, "WatchEvent", (PyObject *)&FastEventType) < 0) {
        Py_DECREF(&FastEventType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
