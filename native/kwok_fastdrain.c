/* kwok_fastdrain — CPython extension for the device drain's per-row
 * hot loops (VERDICT r02 next-#1: C-backed substitution + columnar
 * store commit so per-op dicts/copies disappear).
 *
 * Everything here is a drop-in accelerator for a pure-Python
 * equivalent that stays in-tree (engine/render_plan.py,
 * cluster/store.py, controllers/device_player.py); when the toolchain
 * is missing the Python paths run instead.
 *
 * Functions:
 *   build(comp, vals)                -> patch        (render_plan._build)
 *   status_commit(objects, items, rv_start, namespaced, ev_cls)
 *                                    -> (results, evs, last_rv)
 *   filter_stale(evs, rows, written) -> [ev, ...]    (self-echo drop)
 *   cache_apply(cache, evs)          -> None         (informer mirror)
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdlib.h>

static PyObject *s_metadata, *s_namespace, *s_name, *s_resourceVersion,
    *s_status, *s_MODIFIED, *s_DELETED, *s_default, *s_empty, *s_type,
    *s_object;

/* ---------------------------------------------------------------- build */

static PyObject *
build_node(PyObject *comp, PyObject *vals)
{
    PyObject *kind = PyTuple_GET_ITEM(comp, 0);
    PyObject *orig = PyTuple_GET_ITEM(comp, 1);
    PyObject *items = PyTuple_GET_ITEM(comp, 2);
    const char *k = PyUnicode_AsUTF8(kind);
    if (!k)
        return NULL;
    switch (k[0]) {
    case 'x': { /* exact token: typed substitution */
        PyObject *v = PyDict_GetItemWithError(vals, orig);
        if (!v) {
            if (!PyErr_Occurred())
                PyErr_SetObject(PyExc_KeyError, orig);
            return NULL;
        }
        Py_INCREF(v);
        return v;
    }
    case 's': { /* string leaf with embedded tokens */
        PyObject *cur = orig;
        Py_INCREF(cur);
        Py_ssize_t n = PyList_GET_SIZE(items);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *tok = PyList_GET_ITEM(items, i);
            PyObject *v = PyDict_GetItemWithError(vals, tok);
            if (!v) {
                Py_DECREF(cur);
                if (!PyErr_Occurred())
                    PyErr_SetObject(PyExc_KeyError, tok);
                return NULL;
            }
            PyObject *vs = PyObject_Str(v);
            if (!vs) {
                Py_DECREF(cur);
                return NULL;
            }
            PyObject *next = PyUnicode_Replace(cur, tok, vs, -1);
            Py_DECREF(vs);
            Py_DECREF(cur);
            if (!next)
                return NULL;
            cur = next;
        }
        return cur;
    }
    case 'd': {
        PyObject *out = PyDict_Copy(orig);
        if (!out)
            return NULL;
        Py_ssize_t n = PyList_GET_SIZE(items);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *pair = PyList_GET_ITEM(items, i);
            PyObject *key = PyTuple_GET_ITEM(pair, 0);
            PyObject *child = PyTuple_GET_ITEM(pair, 1);
            PyObject *v = build_node(child, vals);
            if (!v || PyDict_SetItem(out, key, v) < 0) {
                Py_XDECREF(v);
                Py_DECREF(out);
                return NULL;
            }
            Py_DECREF(v);
        }
        return out;
    }
    case 'l': {
        PyObject *out = PySequence_List(orig);
        if (!out)
            return NULL;
        Py_ssize_t n = PyList_GET_SIZE(items);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *pair = PyList_GET_ITEM(items, i);
            Py_ssize_t idx = PyLong_AsSsize_t(PyTuple_GET_ITEM(pair, 0));
            PyObject *child = PyTuple_GET_ITEM(pair, 1);
            PyObject *v = build_node(child, vals);
            if (!v) {
                Py_DECREF(out);
                return NULL;
            }
            if (PyList_SetItem(out, idx, v) < 0) { /* steals v */
                Py_DECREF(out);
                return NULL;
            }
        }
        return out;
    }
    default:
        PyErr_SetString(PyExc_ValueError, "bad comp node kind");
        return NULL;
    }
}

static PyObject *
py_build(PyObject *self, PyObject *args)
{
    PyObject *comp, *vals;
    if (!PyArg_ParseTuple(args, "OO", &comp, &vals))
        return NULL;
    return build_node(comp, vals);
}

/* -------------------------------------------------------- status_commit */

static PyObject *
py_status_commit(PyObject *self, PyObject *args)
{
    PyObject *objects, *items, *ev_cls;
    long long rv;
    int namespaced;
    if (!PyArg_ParseTuple(args, "OOLpO", &objects, &items, &rv, &namespaced,
                          &ev_cls))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(items);
    PyObject *results = PyList_New(0);
    PyObject *evs = PyList_New(0);
    if (!results || !evs)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(items, i); /* (ns, name, status) */
        PyObject *ns = PyTuple_GET_ITEM(item, 0);
        PyObject *name = PyTuple_GET_ITEM(item, 1);
        PyObject *status = PyTuple_GET_ITEM(item, 2);
        PyObject *keyns;
        if (namespaced)
            keyns = (ns != Py_None && PyObject_IsTrue(ns)) ? ns : s_default;
        else
            keyns = s_empty;
        PyObject *key = PyTuple_Pack(2, keyns, name);
        if (!key)
            goto fail;
        PyObject *cur = PyDict_GetItemWithError(objects, key);
        if (!cur) {
            Py_DECREF(key);
            if (PyErr_Occurred())
                goto fail;
            if (PyList_Append(results, Py_None) < 0)
                goto fail;
            continue;
        }
        PyObject *newobj = PyDict_Copy(cur);
        if (!newobj) {
            Py_DECREF(key);
            goto fail;
        }
        if (PyDict_SetItem(newobj, s_status, status) < 0)
            goto fail_new;
        PyObject *meta = PyDict_GetItemWithError(cur, s_metadata);
        if (!meta) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_KeyError, "metadata");
            goto fail_new;
        }
        PyObject *nm = PyDict_Copy(meta);
        if (!nm)
            goto fail_new;
        rv += 1;
        PyObject *rvs = PyUnicode_FromFormat("%lld", rv);
        if (!rvs || PyDict_SetItem(nm, s_resourceVersion, rvs) < 0) {
            Py_XDECREF(rvs);
            Py_DECREF(nm);
            goto fail_new;
        }
        Py_DECREF(rvs);
        if (PyDict_SetItem(newobj, s_metadata, nm) < 0) {
            Py_DECREF(nm);
            goto fail_new;
        }
        Py_DECREF(nm);
        if (PyDict_SetItem(objects, key, newobj) < 0)
            goto fail_new;
        Py_DECREF(key);
        key = NULL;
        {
            PyObject *ev = PyObject_CallFunction(ev_cls, "OOL", s_MODIFIED,
                                                 newobj, rv);
            if (!ev)
                goto fail_new2;
            if (PyList_Append(evs, ev) < 0) {
                Py_DECREF(ev);
                goto fail_new2;
            }
            Py_DECREF(ev);
        }
        {
            PyObject *res = Py_BuildValue("(LO)", rv, newobj);
            if (!res)
                goto fail_new2;
            if (PyList_Append(results, res) < 0) {
                Py_DECREF(res);
                goto fail_new2;
            }
            Py_DECREF(res);
        }
        Py_DECREF(newobj);
        continue;
    fail_new:
        Py_DECREF(key);
    fail_new2:
        Py_DECREF(newobj);
        goto fail;
    }
    return Py_BuildValue("(NNL)", results, evs, rv);
fail:
    Py_XDECREF(results);
    Py_XDECREF(evs);
    return NULL;
}

/* --------------------------------------------------------- filter_stale */

/* parse a resourceVersion string to int; returns 0 and sets *ok=0 when
 * non-numeric */
static long long
rv_to_ll(PyObject *rvs, int *ok)
{
    *ok = 0;
    if (!rvs || !PyUnicode_Check(rvs))
        return 0;
    const char *sp = PyUnicode_AsUTF8(rvs);
    if (!sp || !*sp)
        return 0;
    char *end = NULL;
    long long v = strtoll(sp, &end, 10);
    if (end && *end == '\0')
        *ok = 1;
    return v;
}

static PyObject *
py_filter_stale(PyObject *self, PyObject *args)
{
    PyObject *evs, *rows, *written;
    if (!PyArg_ParseTuple(args, "OOO", &evs, &rows, &written))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(evs);
    PyObject *out = PyList_New(0);
    if (!out)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *ev = PyList_GET_ITEM(evs, i);
        int keep = 1;
        PyObject *type = PyObject_GetAttr(ev, s_type);
        if (!type)
            goto err;
        int is_mod = PyUnicode_Check(type) &&
                     PyUnicode_Compare(type, s_MODIFIED) == 0;
        Py_DECREF(type);
        if (is_mod) {
            PyObject *obj = PyObject_GetAttr(ev, s_object);
            if (!obj)
                goto err;
            PyObject *meta = PyDict_GetItemWithError(obj, s_metadata);
            if (meta && PyDict_Check(meta)) {
                PyObject *ns = PyDict_GetItemWithError(meta, s_namespace);
                PyObject *name = PyDict_GetItemWithError(meta, s_name);
                if (!ns || ns == Py_None)
                    ns = s_empty;
                if (!name || name == Py_None)
                    name = s_empty;
                PyObject *key = PyTuple_Pack(2, ns, name);
                if (!key) {
                    Py_DECREF(obj);
                    goto err;
                }
                PyObject *row = PyDict_GetItemWithError(rows, key);
                Py_DECREF(key);
                if (row) {
                    PyObject *last = PyDict_GetItemWithError(written, row);
                    if (last) {
                        PyObject *rvs =
                            PyDict_GetItemWithError(meta, s_resourceVersion);
                        if (rvs && PyUnicode_Check(rvs) &&
                            PyUnicode_Check(last)) {
                            if (PyUnicode_Compare(rvs, last) == 0) {
                                keep = 0;
                            } else {
                                int ok1, ok2;
                                long long a = rv_to_ll(rvs, &ok1);
                                long long b = rv_to_ll(last, &ok2);
                                if (ok1 && ok2 && a <= b)
                                    keep = 0;
                            }
                        }
                    }
                }
            }
            Py_DECREF(obj);
        }
        if (PyErr_Occurred())
            goto err;
        if (keep && PyList_Append(out, ev) < 0)
            goto err;
    }
    return out;
err:
    Py_DECREF(out);
    return NULL;
}

/* ---------------------------------------------------------- cache_apply */

static PyObject *
py_cache_apply(PyObject *self, PyObject *args)
{
    PyObject *cache, *evs;
    if (!PyArg_ParseTuple(args, "OO", &cache, &evs))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(evs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *ev = PyList_GET_ITEM(evs, i);
        PyObject *type = PyObject_GetAttr(ev, s_type);
        if (!type)
            return NULL;
        PyObject *obj = PyObject_GetAttr(ev, s_object);
        if (!obj) {
            Py_DECREF(type);
            return NULL;
        }
        PyObject *meta = PyDict_GetItemWithError(obj, s_metadata);
        if (!meta || !PyDict_Check(meta)) {
            Py_DECREF(type);
            Py_DECREF(obj);
            if (PyErr_Occurred())
                return NULL;
            continue;
        }
        PyObject *ns = PyDict_GetItemWithError(meta, s_namespace);
        PyObject *name = PyDict_GetItemWithError(meta, s_name);
        if (!ns || ns == Py_None)
            ns = s_empty;
        if (!name || name == Py_None)
            name = s_empty;
        PyObject *key = PyTuple_Pack(2, ns, name);
        if (!key) {
            Py_DECREF(type);
            Py_DECREF(obj);
            return NULL;
        }
        int deleted = PyUnicode_Check(type) &&
                      PyUnicode_Compare(type, s_DELETED) == 0;
        int rc;
        if (deleted) {
            rc = PyDict_DelItem(cache, key);
            if (rc < 0 && PyErr_ExceptionMatches(PyExc_KeyError)) {
                PyErr_Clear();
                rc = 0;
            }
        } else {
            rc = PyDict_SetItem(cache, key, obj);
        }
        Py_DECREF(key);
        Py_DECREF(type);
        Py_DECREF(obj);
        if (rc < 0)
            return NULL;
    }
    Py_RETURN_NONE;
}

/* -------------------------------------------------------------- module */

static PyMethodDef Methods[] = {
    {"build", py_build, METH_VARARGS, "build(comp, vals) -> patch"},
    {"status_commit", py_status_commit, METH_VARARGS,
     "status_commit(objects, items, rv_start, namespaced, ev_cls)"},
    {"filter_stale", py_filter_stale, METH_VARARGS,
     "filter_stale(evs, rows, written) -> fresh events"},
    {"cache_apply", py_cache_apply, METH_VARARGS,
     "cache_apply(cache, evs) -> None"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "kwok_fastdrain", NULL, -1, Methods,
};

PyMODINIT_FUNC
PyInit_kwok_fastdrain(void)
{
    s_metadata = PyUnicode_InternFromString("metadata");
    s_namespace = PyUnicode_InternFromString("namespace");
    s_name = PyUnicode_InternFromString("name");
    s_resourceVersion = PyUnicode_InternFromString("resourceVersion");
    s_status = PyUnicode_InternFromString("status");
    s_MODIFIED = PyUnicode_InternFromString("MODIFIED");
    s_DELETED = PyUnicode_InternFromString("DELETED");
    s_default = PyUnicode_InternFromString("default");
    s_empty = PyUnicode_InternFromString("");
    s_type = PyUnicode_InternFromString("type");
    s_object = PyUnicode_InternFromString("object");
    return PyModule_Create(&moduledef);
}
