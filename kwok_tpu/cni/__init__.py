"""CNI integration for simulated pod networking.

The reference optionally allocates *real* IPs for fake pods through
go-cni + a network namespace when ``--experimental-enable-cni`` is on
(reference pkg/kwok/cni/cni_linux.go:26+, gated linux-only); the
default path is the in-process per-node CIDR pool
(pod_controller.go:481-535).

This module mirrors that split, speaking the standard CNI *plugin
protocol* directly (CNI_COMMAND/CNI_CONTAINERID/CNI_NETNS env + network
config JSON on stdin, IPAM result JSON on stdout) rather than binding
to a Go library:

- :class:`SimulatedCNI` — the default: wraps the same IPPool allocator
  the pod controller uses; no privileges, works everywhere.
- :class:`HostCNI` — EXPERIMENTAL: invokes a real CNI plugin binary
  (e.g. host-local) per ADD/DEL.  Needs a plugin on disk; no netns is
  created (kwok pods have no processes), so CNI_NETNS is passed as the
  placeholder the plugin tolerates for pure-IPAM plugins.

Both expose ``add(pod) -> ip`` / ``delete(pod)``, the two verbs the
pod controller needs.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
from typing import Dict, Optional

from kwok_tpu.controllers.utils import IPPool

__all__ = ["SimulatedCNI", "HostCNI", "CNIError"]


class CNIError(RuntimeError):
    pass


class SimulatedCNI:
    """IPPool-backed CNI: the default simulated network.

    Mirrors the pool path's invariants (pod_controller.py pod_ip_for):
    allocation is serialized so concurrent plays for one pod cannot
    double-allocate, and an IP already present in ``status.podIP`` is
    re-reserved rather than re-issued (controller-restart safety)."""

    def __init__(self, cidr: str = "10.0.0.1/24"):
        self._pool = IPPool(cidr)
        self._ips: Dict[str, str] = {}
        self._mut = threading.Lock()

    def add(self, pod: dict) -> str:
        uid = (pod.get("metadata") or {}).get("uid") or ""
        existing = (pod.get("status") or {}).get("podIP")
        with self._mut:
            ip = self._ips.get(uid)
            if ip is None:
                if existing:
                    self._pool.use(existing)
                    ip = existing
                else:
                    ip = self._pool.get()
                self._ips[uid] = ip
            return ip

    def delete(self, pod: dict) -> None:
        uid = (pod.get("metadata") or {}).get("uid") or ""
        with self._mut:
            ip = self._ips.pop(uid, None)
            if ip is not None:
                self._pool.put(ip)


class HostCNI:
    """Drive a real CNI plugin binary per the CNI spec (ADD/DEL).

    ``plugin_path`` must point at a CNI plugin executable (the
    canonical pure-IPAM choice is ``host-local``).  The network config
    is the standard conflist member document."""

    def __init__(
        self,
        plugin_path: str,
        cidr: str = "10.244.0.0/16",
        ifname: str = "eth0",
        netns: str = "/var/run/netns/kwok-placeholder",
        extra_conf: Optional[dict] = None,
    ):
        if not os.path.exists(plugin_path):
            raise CNIError(f"CNI plugin not found: {plugin_path}")
        self.plugin_path = plugin_path
        self.ifname = ifname
        self.netns = netns
        self.conf = {
            "cniVersion": "0.4.0",
            "name": "kwok-net",
            "type": os.path.basename(plugin_path),
            "ipam": {
                "type": os.path.basename(plugin_path),
                "subnet": cidr,
            },
        }
        if extra_conf:
            self.conf.update(extra_conf)

    def _invoke(self, command: str, pod: dict) -> dict:
        uid = (pod.get("metadata") or {}).get("uid") or "no-uid"
        env = dict(os.environ)
        env.update(
            {
                "CNI_COMMAND": command,
                "CNI_CONTAINERID": uid,
                "CNI_NETNS": self.netns,
                "CNI_IFNAME": self.ifname,
                "CNI_PATH": os.path.dirname(self.plugin_path),
            }
        )
        try:
            proc = subprocess.run(
                [self.plugin_path],
                input=json.dumps(self.conf).encode(),
                capture_output=True,
                env=env,
                timeout=30,
            )
        except (OSError, subprocess.SubprocessError) as exc:
            raise CNIError(f"CNI {command} failed to execute: {exc}") from exc
        if proc.returncode != 0:
            raise CNIError(
                f"CNI {command} exited {proc.returncode}: "
                f"{proc.stdout.decode(errors='replace')[:500]}"
            )
        out = proc.stdout.decode(errors="replace")
        return json.loads(out) if out.strip() else {}

    def add(self, pod: dict) -> str:
        result = self._invoke("ADD", pod)
        for ip_entry in result.get("ips") or []:
            addr = (ip_entry.get("address") or "").split("/")[0]
            if addr:
                return addr
        raise CNIError(f"CNI ADD returned no IP: {result}")

    def delete(self, pod: dict) -> None:
        self._invoke("DEL", pod)
