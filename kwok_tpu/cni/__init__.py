"""CNI integration for simulated pod networking.

The reference optionally allocates *real* IPs for fake pods through
go-cni + a network namespace when ``--experimental-enable-cni`` is on
(reference pkg/kwok/cni/cni_linux.go:26+, gated linux-only); the
default path is the in-process per-node CIDR pool
(pod_controller.go:481-535).

This module mirrors that split, speaking the standard CNI *plugin
protocol* directly (CNI_COMMAND/CNI_CONTAINERID/CNI_NETNS env + network
config JSON on stdin, IPAM result JSON on stdout) rather than binding
to a Go library:

- :class:`SimulatedCNI` — the default: wraps the same IPPool allocator
  the pod controller uses; no privileges, works everywhere.
- :class:`HostCNI` — EXPERIMENTAL: invokes a real CNI plugin binary
  (e.g. host-local) per ADD/DEL.  Needs a plugin on disk.  When the
  process is privileged and ``ip netns`` is available, a REAL network
  namespace is created per pod and its path passed as CNI_NETNS —
  the reference's NewNS/UnmountNS flow (cni_linux.go:26+, NS helpers
  in pkg/kwok/cni) — and deleted on DEL; otherwise a placeholder path
  is passed, which pure-IPAM plugins tolerate.

Both expose ``add(pod) -> ip`` / ``delete(pod)``, the two verbs the
pod controller needs.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
from typing import Dict, Optional

from kwok_tpu.controllers.utils import IPPool

__all__ = ["SimulatedCNI", "HostCNI", "CNIError"]


class CNIError(RuntimeError):
    pass


class SimulatedCNI:
    """IPPool-backed CNI: the default simulated network.

    Mirrors the pool path's invariants (pod_controller.py pod_ip_for):
    allocation is serialized so concurrent plays for one pod cannot
    double-allocate, and an IP already present in ``status.podIP`` is
    re-reserved rather than re-issued (controller-restart safety)."""

    def __init__(self, cidr: str = "10.0.0.1/24"):
        self._pool = IPPool(cidr)
        self._ips: Dict[str, str] = {}
        self._mut = threading.Lock()

    def add(self, pod: dict) -> str:
        uid = (pod.get("metadata") or {}).get("uid") or ""
        existing = (pod.get("status") or {}).get("podIP")
        with self._mut:
            ip = self._ips.get(uid)
            if ip is None:
                if existing:
                    self._pool.use(existing)
                    ip = existing
                else:
                    ip = self._pool.get()
                self._ips[uid] = ip
            return ip

    def delete(self, pod: dict) -> None:
        uid = (pod.get("metadata") or {}).get("uid") or ""
        with self._mut:
            ip = self._ips.pop(uid, None)
            if ip is not None:
                self._pool.put(ip)


class HostCNI:
    """Drive a real CNI plugin binary per the CNI spec (ADD/DEL).

    ``plugin_path`` must point at a CNI plugin executable (the
    canonical pure-IPAM choice is ``host-local``).  The network config
    is the standard conflist member document."""

    def __init__(
        self,
        plugin_path: str,
        cidr: str = "10.244.0.0/16",
        ifname: str = "eth0",
        netns: str = "/var/run/netns/kwok-placeholder",
        extra_conf: Optional[dict] = None,
        create_netns: Optional[bool] = None,
    ):
        if not os.path.exists(plugin_path):
            raise CNIError(f"CNI plugin not found: {plugin_path}")
        self.plugin_path = plugin_path
        self.ifname = ifname
        self.netns = netns
        #: real per-pod namespaces (reference NewNS): auto-detected —
        #: root + the iproute2 tool present — but an EXPLICIT netns=
        #: argument always wins (the caller points at an existing
        #: namespace; creating our own would configure the wrong one)
        if create_netns is None:
            create_netns = (
                netns == "/var/run/netns/kwok-placeholder"
                and os.geteuid() == 0
                and _ip_netns_available()
            )
        self.create_netns = create_netns
        self.conf = {
            "cniVersion": "0.4.0",
            "name": "kwok-net",
            "type": os.path.basename(plugin_path),
            "ipam": {
                "type": os.path.basename(plugin_path),
                "subnet": cidr,
            },
        }
        if extra_conf:
            self.conf.update(extra_conf)

    @staticmethod
    def _uid(pod: dict) -> str:
        return (pod.get("metadata") or {}).get("uid") or "no-uid"

    @staticmethod
    def _netns_name(uid: str) -> str:
        """Unique, always-valid netns name: uids are caller-supplied
        strings (not necessarily UUIDs), so hash rather than truncate —
        truncation collided 32-char-prefix twins, and characters like
        '/' broke `ip netns add`.  A readable prefix of the uid rides
        along for debuggability."""
        import hashlib
        import re

        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", uid)[:16]
        digest = hashlib.sha1(uid.encode()).hexdigest()[:12]
        return f"kwok-{safe}-{digest}"

    def _netns_path(self, uid: str) -> str:
        return f"/var/run/netns/{self._netns_name(uid)}"

    def _ensure_netns(self, uid: str) -> str:
        """Create (idempotently) the pod's network namespace; returns
        its bind path (reference NewNS, pkg/kwok/cni)."""
        name = self._netns_name(uid)
        path = f"/var/run/netns/{name}"
        if not os.path.exists(path):
            try:
                proc = subprocess.run(
                    ["ip", "netns", "add", name],
                    capture_output=True,
                    timeout=10,
                )
            except subprocess.SubprocessError as exc:
                raise CNIError(f"netns create failed: {exc}") from exc
            if proc.returncode != 0 and not os.path.exists(path):
                raise CNIError(
                    f"netns create failed: {proc.stderr.decode(errors='replace')[:200]}"
                )
        return path

    def _delete_netns(self, uid: str) -> None:
        name = self._netns_name(uid)
        if os.path.exists(f"/var/run/netns/{name}"):
            try:
                subprocess.run(
                    ["ip", "netns", "delete", name],
                    capture_output=True,
                    timeout=10,
                )
            except subprocess.SubprocessError:
                pass  # best effort; the DEL error (if any) wins

    def _invoke(self, command: str, uid: str, netns: str) -> dict:
        env = dict(os.environ)
        env.update(
            {
                "CNI_COMMAND": command,
                "CNI_CONTAINERID": uid,
                "CNI_NETNS": netns,
                "CNI_IFNAME": self.ifname,
                "CNI_PATH": os.path.dirname(self.plugin_path),
            }
        )
        try:
            proc = subprocess.run(
                [self.plugin_path],
                input=json.dumps(self.conf).encode(),
                capture_output=True,
                env=env,
                timeout=30,
            )
        except (OSError, subprocess.SubprocessError) as exc:
            raise CNIError(f"CNI {command} failed to execute: {exc}") from exc
        if proc.returncode != 0:
            raise CNIError(
                f"CNI {command} exited {proc.returncode}: "
                f"{proc.stdout.decode(errors='replace')[:500]}"
            )
        out = proc.stdout.decode(errors="replace")
        return json.loads(out) if out.strip() else {}

    def add(self, pod: dict) -> str:
        uid = self._uid(pod)
        netns = self._ensure_netns(uid) if self.create_netns else self.netns
        try:
            result = self._invoke("ADD", uid, netns)
            for ip_entry in result.get("ips") or []:
                addr = (ip_entry.get("address") or "").split("/")[0]
                if addr:
                    return addr
            raise CNIError(f"CNI ADD returned no IP: {result}")
        except CNIError:
            # a failed setup must not leak the namespace it pre-created
            # (the reference unmounts the NS on Setup error too)
            if self.create_netns:
                self._delete_netns(uid)
            raise

    def delete(self, pod: dict) -> None:
        uid = self._uid(pod)
        netns = self._netns_path(uid) if self.create_netns else self.netns
        try:
            self._invoke("DEL", uid, netns)
        finally:
            if self.create_netns:
                self._delete_netns(uid)


def _ip_netns_available() -> bool:
    import shutil

    return shutil.which("ip") is not None and os.path.isdir("/var/run")
