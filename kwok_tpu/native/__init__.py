"""ctypes bindings for the C++ runtime core (native/kwok_native.cpp).

The shared library is built on demand with g++ the first time it is
needed (and cached beside this package); when no toolchain is present
everything falls back to the pure-Python implementations, so the
native layer is a transparent accelerator, never a hard dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_LIB_NAME = "libkwok_native.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _source_dir() -> str:
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo_root, "native")


def _build(target: str) -> bool:
    src = os.path.join(_source_dir(), "kwok_native.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", target, src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it if necessary; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        here = os.path.dirname(os.path.abspath(__file__))
        cached = os.path.join(here, _LIB_NAME)
        # the compile runs under _lock on purpose: build-once semantics —
        # concurrent first callers must block until the library exists
        # rather than race duplicate compiler invocations
        if not os.path.exists(cached) and not _build(cached):  # kwoklint: disable=lock-discipline
            return None
        try:
            lib = ctypes.CDLL(cached)
        except OSError:
            return None
        lib.kn_heap_new.restype = ctypes.c_void_p
        lib.kn_heap_free.argtypes = [ctypes.c_void_p]
        lib.kn_heap_add.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_double,
        ]
        lib.kn_heap_cancel.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kn_heap_cancel.restype = ctypes.c_int
        lib.kn_heap_promote.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.kn_heap_pop_ready.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int,
        ]
        lib.kn_heap_pop_ready.restype = ctypes.c_int
        lib.kn_heap_next_deadline.argtypes = [ctypes.c_void_p]
        lib.kn_heap_next_deadline.restype = ctypes.c_double
        lib.kn_heap_ready_count.argtypes = [ctypes.c_void_p]
        lib.kn_heap_ready_count.restype = ctypes.c_int
        lib.kn_heap_size.argtypes = [ctypes.c_void_p]
        lib.kn_heap_size.restype = ctypes.c_int
        lib.kn_fnv1a64_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


class NativeDelayHeap:
    """Python face of the C++ delay/weight heap.

    Schedules opaque int64 ids: :meth:`add` (re-add reschedules),
    :meth:`cancel`, :meth:`promote` (move due entries to their weight
    buckets), :meth:`pop_ready` (lowest weight first, FIFO within a
    weight), :meth:`next_deadline`."""

    __slots__ = ("_h", "_lib", "_buf")

    _POP_BATCH = 1024

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError("kwok_native library unavailable")
        self._lib = lib
        self._h = lib.kn_heap_new()
        self._buf = (ctypes.c_int64 * self._POP_BATCH)()

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.kn_heap_free(h)

    def add(self, id_: int, weight: int, deadline: float) -> None:
        self._lib.kn_heap_add(self._h, id_, weight, deadline)

    def cancel(self, id_: int) -> bool:
        return bool(self._lib.kn_heap_cancel(self._h, id_))

    def promote(self, now: float) -> None:
        self._lib.kn_heap_promote(self._h, now)

    def pop_ready(self, max_items: Optional[int] = None):
        out = []
        budget = max_items if max_items is not None else 1 << 31
        while budget > 0:
            n = self._lib.kn_heap_pop_ready(
                self._h, self._buf, min(budget, self._POP_BATCH)
            )
            if n <= 0:
                break
            out.extend(self._buf[:n])
            budget -= n
        return out

    def next_deadline(self) -> Optional[float]:
        d = self._lib.kn_heap_next_deadline(self._h)
        return None if d < 0 else d

    @property
    def ready_count(self) -> int:
        return self._lib.kn_heap_ready_count(self._h)

    def __len__(self) -> int:
        return self._lib.kn_heap_size(self._h)


def fnv1a64(values) -> list:
    """Batch FNV-1a 64 over a list of str/bytes."""
    lib = load()
    enc = [v.encode() if isinstance(v, str) else bytes(v) for v in values]
    if lib is None:
        out = []
        for b in enc:
            h = 0xCBF29CE484222325
            for byte in b:
                h = ((h ^ byte) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
            out.append(h)
        return out
    buf = b"".join(enc)
    n = len(enc)
    offs = (ctypes.c_int64 * n)()
    lens = (ctypes.c_int64 * n)()
    pos = 0
    for i, b in enumerate(enc):
        offs[i] = pos
        lens[i] = len(b)
        pos += len(b)
    out = (ctypes.c_uint64 * n)()
    lib.kn_fnv1a64_batch(buf, offs, lens, n, out)
    return list(out)
