"""Loader for the kwok_fastdrain CPython extension.

The accelerator exists because "only dirty rows cross the boundary"
(SURVEY.md:373) leaves the drain's dict-building as the host
bottleneck; the reference has no native analog (CGO is disabled,
hack/releases.sh:186).  Unlike the ctypes-based delay heap
(kwok_tpu/native/__init__.py), the
drain accelerator manipulates Python dicts directly, so it is a real
extension module compiled against Python.h and imported from its build
path.  ``KWOK_TPU_NATIVE=0`` or a missing toolchain falls back to the
pure-Python implementations everywhere it is used.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import subprocess
import sysconfig
import threading
from typing import Optional

_LIB_NAME = "kwok_fastdrain.so"
_lock = threading.Lock()
_mod = None
_tried = False


def _source_path() -> str:
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo_root, "native", "kwok_fastdrain.c")


def _build(target: str) -> bool:
    src = _source_path()
    if not os.path.exists(src):
        return False
    include = sysconfig.get_paths().get("include")
    if not include:
        return False
    try:
        subprocess.run(
            [
                "g++",
                "-O2",
                "-shared",
                "-fPIC",
                f"-I{include}",
                "-o",
                target,
                "-x",
                "c",
                src,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load():
    """The extension module, building it if necessary; None if
    unavailable or disabled via KWOK_TPU_NATIVE=0."""
    global _mod, _tried
    if os.environ.get("KWOK_TPU_NATIVE", "1") == "0":
        return None
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        here = os.path.dirname(os.path.abspath(__file__))
        cached = os.path.join(here, _LIB_NAME)
        src = _source_path()
        stale = (
            not os.path.exists(cached)
            or (
                os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(cached)
            )
        )
        # the compile runs under the lock on purpose: build-once
        # semantics — concurrent first callers must block until the
        # extension exists rather than race duplicate compiles
        if stale and not _build(cached):  # kwoklint: disable=lock-discipline
            return None
        try:
            loader = importlib.machinery.ExtensionFileLoader(
                "kwok_fastdrain", cached
            )
            spec = importlib.util.spec_from_file_location(
                "kwok_fastdrain", cached, loader=loader
            )
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
        except (ImportError, OSError):
            return None
        _mod = mod
        return _mod
