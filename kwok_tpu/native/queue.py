"""WeightDelayingQueue on the C++ delay heap.

Same surface and scheduling semantics as the pure-Python
:class:`kwok_tpu.utils.queue.WeightDelayingQueue` (itself mirroring
reference weight_delaying_queue.go:29-163): ``add_weight_after``
schedules, due items promote into weight buckets (lower weight served
first), ``cancel`` removes pending items.  The deadline bookkeeping —
the O(log n) hot path at 100k+ in-flight timers — lives in native code;
Python only keeps the id↔item table and the blocking FIFO face.

Cancellation matches the controllers' usage pattern (one scheduled
entry per object key, cancelled by the same item instance — reference
delayQueueMapping, pod_controller.go:205-214): cancel removes every
pending entry scheduled for an item that compares equal (hashable
items) or identical (unhashable).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple, TypeVar

from kwok_tpu.native import NativeDelayHeap, available
from kwok_tpu.utils.clock import Clock, RealClock
from kwok_tpu.utils.queue import WeightQueue

T = TypeVar("T")

__all__ = ["NativeWeightDelayingQueue", "native_available"]


def native_available() -> bool:
    return available()


def _key(item) -> object:
    try:
        hash(item)
        return item
    except TypeError:
        return id(item)


class NativeWeightDelayingQueue(WeightQueue[T]):
    """Drop-in WeightDelayingQueue backed by the C++ heap."""

    def __init__(self, clock: Optional[Clock] = None):
        super().__init__()
        self._clock = clock or RealClock()
        self._heap = NativeDelayHeap()
        self._entries: Dict[int, Tuple[T, int]] = {}  # id -> (item, weight)
        self._ids_by_item: Dict[object, List[int]] = {}
        self._next_id = 0
        self._hmut = threading.Lock()
        self._hsignal = threading.Event()
        self._clock.subscribe(self._hsignal)
        self._stopped = False
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    # ----------------------------------------------------------- scheduling

    def add_weight_after(self, item: T, weight: int, delay: float) -> None:
        if delay <= 0:
            self.add_weight(item, weight)
            return
        deadline = self._clock.now() + delay
        with self._hmut:
            self._next_id += 1
            eid = self._next_id
            self._entries[eid] = (item, weight)
            self._ids_by_item.setdefault(_key(item), []).append(eid)
            self._heap.add(eid, weight, deadline)
        self._hsignal.set()

    def add_after(self, item: T, delay: float) -> None:
        self.add_weight_after(item, 0, delay)

    def cancel(self, item: T) -> bool:
        with self._hmut:
            removed = False
            for eid in self._ids_by_item.pop(_key(item), []):
                if self._entries.pop(eid, None) is not None:
                    self._heap.cancel(eid)
                    removed = True
        return self.remove(item) or removed

    # --------------------------------------------------------------- worker

    def _drop_entry(self, eid: int) -> Optional[Tuple[T, int]]:
        entry = self._entries.pop(eid, None)
        if entry is None:
            return None
        key = _key(entry[0])
        ids = self._ids_by_item.get(key)
        if ids is not None:
            try:
                ids.remove(eid)
            except ValueError:
                pass
            if not ids:
                del self._ids_by_item[key]
        return entry

    def _loop(self) -> None:
        while not self._stopped:
            now = self._clock.now()
            promoted: List[Tuple[T, int]] = []
            with self._hmut:
                self._heap.promote(now)
                for eid in self._heap.pop_ready():
                    entry = self._drop_entry(eid)
                    if entry is not None:
                        promoted.append(entry)
                nxt = self._heap.next_deadline()
            for item, weight in promoted:
                self.add_weight(item, weight)
            if promoted:
                continue
            wait = 10.0 if nxt is None else min(max(nxt - now, 0.0), 10.0)
            self._clock.wait_signal(self._hsignal, wait)
            self._hsignal.clear()

    def stop(self) -> None:
        self._stopped = True
        self._hsignal.set()

    # __len__ deliberately inherits WeightQueue's (promoted items only,
    # excluding not-yet-due delayed entries) to match the pure-Python
    # WeightDelayingQueue exactly; pending_count exposes the rest.

    @property
    def pending_count(self) -> int:
        """Scheduled-but-not-yet-due entries (native heap residents)."""
        with self._hmut:
            return len(self._entries)
