"""In-process tracer: spans, W3C context propagation, OTLP export.

The reference delegates tracing to the ecosystem: kwokctl launches a
Jaeger all-in-one (reference pkg/kwokctl/components/jaeger.go:42) and
configures kube-apiserver's OTLP exporter at full sampling
(reference pkg/kwokctl/k8s/kube_apiserver_tracing_config.go:34-47);
kwok itself only exposes pprof.  This rebuild has no external binaries
to lean on, so the tracer is built in:

- :class:`Tracer` — cheap spans (trace/span ids, wall ns, attributes,
  status), thread-local current-span context, bounded in-memory buffer
  flushed by a background exporter thread;
- W3C ``traceparent`` header helpers so a trace crosses the
  client→apiserver process boundary the way OTLP ecosystems expect;
- OTLP/HTTP JSON export (``resourceSpans`` shape) to a collector URL —
  the bundled collector (cmd/tracing.py, the Jaeger seat) or any real
  OTLP endpoint.

Disabled (no endpoint) the tracer is a few dict lookups per span; the
device tick's inner loop is never traced per-row — spans wrap whole
batched operations, keeping observability off the hot path.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional

__all__ = [
    "Span",
    "Tracer",
    "get_tracer",
    "peek_global",
    "set_global",
    "traceparent",
    "from_traceparent",
]


class Span:
    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "name",
        "start_ns",
        "end_ns",
        "attributes",
        "status_ok",
        "_tracer",
        "_token",
    )

    def __init__(self, tracer, name, trace_id, span_id, parent_id):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attributes: Dict[str, Any] = {}
        self.status_ok = True
        self._token = None

    def set(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def error(self, message: str) -> "Span":
        self.status_ok = False
        self.attributes["error.message"] = message
        return self

    def end(self) -> None:
        self.end_ns = time.time_ns()
        self._tracer._finish(self)

    # context-manager sugar: `with tracer.span("x") as sp:`
    def __enter__(self) -> "Span":
        self._token = self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.error(str(exc))
        self._tracer._pop(self._token)
        self.end()


class Tracer:
    """One per process/component; export is best-effort and bounded."""

    MAX_BUFFER = 8192
    FLUSH_EVERY = 2.0

    def __init__(
        self,
        service: str,
        endpoint: Optional[str] = None,
        resource: Optional[Dict[str, Any]] = None,
    ):
        self.service = service
        self.endpoint = endpoint  # e.g. http://127.0.0.1:4318/v1/traces
        self.resource = dict(resource or {})
        self._local = threading.local()
        self._buf: List[Span] = []
        self._mut = threading.Lock()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.dropped = 0
        self.exported = 0
        #: True while the collector is unreachable — the log-once gate:
        #: the first failed flush of an outage logs a warning (with the
        #: running drop count), the first successful one logs recovery;
        #: everything in between drops silently-but-counted
        self._outage = False
        #: separate edge for buffer overpressure (spans produced faster
        #: than FLUSH_EVERY drains them, collector possibly healthy):
        #: logged once per overpressure episode, cleared only after a
        #: full flush cycle with zero drops — never recycled per batch,
        #: and never conflated with collector reachability
        self._buf_logged = False
        self._dropped_since_flush = 0
        if endpoint:
            self._thread = threading.Thread(
                target=self._flush_loop, daemon=True, name=f"trace-{service}"
            )
            self._thread.start()

    @property
    def enabled(self) -> bool:
        return self.endpoint is not None

    # ----------------------------------------------------------------- spans

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def span(
        self,
        name: str,
        parent: Optional[Span] = None,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
    ) -> Span:
        """New span.  Parent defaults to the thread's current span;
        pass trace_id/parent_id (e.g. from a traceparent header) to
        continue a remote trace."""
        if parent is None and trace_id is None:
            parent = self.current()
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        if trace_id is None:
            trace_id = secrets.token_hex(16)
        return Span(self, name, trace_id, secrets.token_hex(8), parent_id)

    def _push(self, span: Span):
        st = self._stack()
        st.append(span)
        return len(st) - 1

    def _pop(self, token) -> None:
        st = self._stack()
        if token is not None and token < len(st):
            del st[token:]

    def _finish(self, span: Span) -> None:
        if not self.enabled:
            return
        log_edge = False
        with self._mut:
            if len(self._buf) >= self.MAX_BUFFER:
                self.dropped += 1
                self._dropped_since_flush += 1
                # edge check-and-set under the mutex: two threads
                # overflowing concurrently must produce ONE warning,
                # not a race on the log-once flag
                if not self._buf_logged:
                    self._buf_logged = True
                    log_edge = True
            else:
                self._buf.append(span)
        if log_edge:
            # a full buffer with a healthy exporter means spans arrive
            # faster than FLUSH_EVERY drains them — say so once per
            # overpressure episode instead of silently shedding forever
            self._log_drop("span buffer full; dropping spans")

    # ---------------------------------------------------------------- export

    def _flush_loop(self) -> None:
        while not self._done.wait(self.FLUSH_EVERY):
            self.flush()
        self.flush()

    def flush(self) -> None:
        with self._mut:
            batch, self._buf = self._buf, []
            # a full flush cycle with zero drops ends the overpressure
            # episode: the NEXT buffer-full is a new edge worth a line.
            # Sustained overpressure (drops every cycle) keeps the edge
            # set, so the warn stays once-per-episode, never per batch.
            if self._dropped_since_flush == 0:
                self._buf_logged = False
            self._dropped_since_flush = 0
        if not batch or not self.endpoint:
            return
        try:
            payload = json.dumps(self._otlp(batch)).encode()
            req = urllib.request.Request(
                self.endpoint,
                data=payload,
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=5).read()
            with self._mut:
                self.exported += len(batch)
                recovered = self._outage
                self._outage = False
            if recovered:
                self._log_drop(
                    "collector reachable again; resuming span export",
                    recovered=True,
                )
        except Exception as exc:  # noqa: BLE001 — a dead collector must
            # not break the traced component; spans from this batch are
            # lost, counted, and the outage is logged ONCE (edge
            # check-and-set under the mutex, like _finish's)
            with self._mut:
                self.dropped += len(batch)
                log_edge = not self._outage
                self._outage = True
            if log_edge:
                self._log_drop(f"collector unreachable: {exc}")

    def _log_drop(self, message: str, recovered: bool = False) -> None:
        """One line per outage edge (never per batch — a dead collector
        at FLUSH_EVERY cadence would otherwise spam forever)."""
        from kwok_tpu.utils.log import get_logger

        log = get_logger("tracer")
        if recovered:
            log.info(message, service=self.service, dropped_total=self.dropped)
        else:
            log.warn(
                message,
                service=self.service,
                endpoint=self.endpoint,
                dropped_total=self.dropped,
            )

    def stats(self) -> dict:
        """Exporter health counters (scraped into /metrics as
        ``kwok_tracer_dropped_spans_total`` etc.)."""
        with self._mut:
            return {
                "dropped": self.dropped,
                "exported": self.exported,
                "buffered": len(self._buf),
                "outage": self._outage,
            }

    def _otlp(self, batch: List[Span]) -> dict:
        def attr(k, v):
            if isinstance(v, bool):
                return {"key": k, "value": {"boolValue": v}}
            if isinstance(v, int):
                return {"key": k, "value": {"intValue": str(v)}}
            if isinstance(v, float):
                return {"key": k, "value": {"doubleValue": v}}
            return {"key": k, "value": {"stringValue": str(v)}}

        res_attrs = [attr("service.name", self.service)] + [
            attr(k, v) for k, v in self.resource.items()
        ]
        spans = []
        for s in batch:
            spans.append(
                {
                    "traceId": s.trace_id,
                    "spanId": s.span_id,
                    "parentSpanId": s.parent_id or "",
                    "name": s.name,
                    "kind": 1,
                    "startTimeUnixNano": str(s.start_ns),
                    "endTimeUnixNano": str(s.end_ns),
                    "attributes": [attr(k, v) for k, v in s.attributes.items()],
                    "status": {"code": 1 if s.status_ok else 2},
                }
            )
        return {
            "resourceSpans": [
                {
                    "resource": {"attributes": res_attrs},
                    "scopeSpans": [
                        {"scope": {"name": "kwok-tpu"}, "spans": spans}
                    ],
                }
            ]
        }

    def stop(self) -> None:
        self._done.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ------------------------------------------------------------- propagation


def traceparent(span: Optional[Span]) -> Optional[str]:
    """W3C traceparent header for outgoing requests."""
    if span is None:
        return None
    return f"00-{span.trace_id}-{span.span_id}-01"


def from_traceparent(header: Optional[str]):
    """(trace_id, parent_span_id) out of an incoming header, or
    (None, None)."""
    if not header:
        return None, None
    parts = header.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None, None
    return parts[1], parts[2]


# ------------------------------------------------------------ global tracer

_global: Optional[Tracer] = None
_global_mut = threading.Lock()


def set_global(tracer: Optional[Tracer]) -> None:
    """Install (or with None, reset) the process-global tracer."""
    global _global
    with _global_mut:
        _global = tracer


def peek_global() -> Optional[Tracer]:
    """The installed global tracer, or None — without creating one
    (metrics exposition reads drop counters from whatever the process
    already configured; it must not instantiate a tracer as a side
    effect of a scrape)."""
    with _global_mut:
        return _global


def get_tracer(service: str = "kwok") -> Tracer:
    """Process-wide tracer; configured from ``KWOK_TRACE_ENDPOINT`` on
    first use (how kwokctl components inherit the collector address)."""
    global _global
    with _global_mut:
        if _global is None:
            _global = Tracer(
                service=os.environ.get("KWOK_TRACE_SERVICE", service),
                endpoint=os.environ.get("KWOK_TRACE_ENDPOINT") or None,
            )
        return _global
