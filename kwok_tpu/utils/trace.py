"""In-process tracer: spans, W3C context propagation, OTLP export.

The reference delegates tracing to the ecosystem: kwokctl launches a
Jaeger all-in-one (reference pkg/kwokctl/components/jaeger.go:42) and
configures kube-apiserver's OTLP exporter at full sampling
(reference pkg/kwokctl/k8s/kube_apiserver_tracing_config.go:34-47);
kwok itself only exposes pprof.  This rebuild has no external binaries
to lean on, so the tracer is built in:

- :class:`Tracer` — cheap spans (trace/span ids, wall ns, attributes,
  status), thread-local current-span context, bounded in-memory buffer
  flushed by a background exporter thread;
- W3C ``traceparent`` header helpers so a trace crosses the
  client→apiserver process boundary the way OTLP ecosystems expect;
- OTLP span **links** + ``context_of``/``current_context`` helpers —
  the rv→span stitch across the watch boundary rides these (the store
  stamps each commit with the writing thread's context; watch-driven
  consumers continue/link it);
- OTLP/HTTP JSON export (``resourceSpans`` shape) to a collector URL —
  the bundled collector (cmd/tracing.py, the Jaeger seat) or any real
  OTLP endpoint;
- journey/critical-path analysis over collector-format spans
  (``build_journey`` / ``critical_path``) shared by the collector's
  ``/api/journey``+``/api/critical-path`` endpoints and the
  ``python -m kwok_tpu.utils.trace --critical-path`` CLI.

Disabled (no endpoint) the tracer is a few dict lookups per span; the
device tick's inner loop is never traced per-row — spans wrap whole
batched operations, keeping observability off the hot path.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional

__all__ = [
    "Span",
    "Tracer",
    "context_of",
    "current_context",
    "get_tracer",
    "peek_global",
    "set_global",
    "traceparent",
    "from_traceparent",
]


class Span:
    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "name",
        "start_ns",
        "end_ns",
        "attributes",
        "links",
        "status_ok",
        "_tracer",
        "_token",
    )

    def __init__(self, tracer, name, trace_id, span_id, parent_id):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attributes: Dict[str, Any] = {}
        #: OTLP span links — causal references to spans in OTHER traces
        #: (or other branches of this one): the watch-boundary stitch
        #: records the causing write's context here when the reconcile
        #: span cannot simply continue that trace
        self.links: List[tuple] = []
        self.status_ok = True
        self._token = None

    def set(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def add_link(self, trace_id: Optional[str], span_id: Optional[str]) -> "Span":
        """Record a causal link to another span context (OTLP link).
        None components are ignored, so callers can pass a possibly-
        missing watch-event ctx without guarding."""
        if trace_id and span_id:
            self.links.append((trace_id, span_id))
        return self

    def error(self, message: str) -> "Span":
        self.status_ok = False
        self.attributes["error.message"] = message
        return self

    def end(self) -> None:
        self.end_ns = time.time_ns()
        self._tracer._finish(self)

    # context-manager sugar: `with tracer.span("x") as sp:`
    def __enter__(self) -> "Span":
        self._token = self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.error(str(exc))
        self._tracer._pop(self._token)
        self.end()


class Tracer:
    """One per process/component; export is best-effort and bounded."""

    MAX_BUFFER = 8192
    FLUSH_EVERY = 2.0

    def __init__(
        self,
        service: str,
        endpoint: Optional[str] = None,
        resource: Optional[Dict[str, Any]] = None,
    ):
        self.service = service
        self.endpoint = endpoint  # e.g. http://127.0.0.1:4318/v1/traces
        self.resource = dict(resource or {})
        self._local = threading.local()
        self._buf: List[Span] = []
        self._mut = threading.Lock()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.dropped = 0
        self.exported = 0
        #: True while the collector is unreachable — the log-once gate:
        #: the first failed flush of an outage logs a warning (with the
        #: running drop count), the first successful one logs recovery;
        #: everything in between drops silently-but-counted
        self._outage = False
        #: separate edge for buffer overpressure (spans produced faster
        #: than FLUSH_EVERY drains them, collector possibly healthy):
        #: logged once per overpressure episode, cleared only after a
        #: full flush cycle with zero drops — never recycled per batch,
        #: and never conflated with collector reachability
        self._buf_logged = False
        self._dropped_since_flush = 0
        if endpoint:
            self._thread = threading.Thread(
                target=self._flush_loop, daemon=True, name=f"trace-{service}"
            )
            self._thread.start()

    @property
    def enabled(self) -> bool:
        return self.endpoint is not None

    # ----------------------------------------------------------------- spans

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def span(
        self,
        name: str,
        parent: Optional[Span] = None,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
    ) -> Span:
        """New span.  Parent defaults to the thread's current span;
        pass trace_id/parent_id (e.g. from a traceparent header) to
        continue a remote trace."""
        if parent is None and trace_id is None:
            parent = self.current()
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        if trace_id is None:
            trace_id = secrets.token_hex(16)
        return Span(self, name, trace_id, secrets.token_hex(8), parent_id)

    def _push(self, span: Span):
        st = self._stack()
        st.append(span)
        return len(st) - 1

    def _pop(self, token) -> None:
        st = self._stack()
        if token is not None and token < len(st):
            del st[token:]

    def _finish(self, span: Span) -> None:
        if not self.enabled:
            return
        log_edge = False
        with self._mut:
            if len(self._buf) >= self.MAX_BUFFER:
                self.dropped += 1
                self._dropped_since_flush += 1
                # edge check-and-set under the mutex: two threads
                # overflowing concurrently must produce ONE warning,
                # not a race on the log-once flag
                if not self._buf_logged:
                    self._buf_logged = True
                    log_edge = True
            else:
                self._buf.append(span)
        if log_edge:
            # a full buffer with a healthy exporter means spans arrive
            # faster than FLUSH_EVERY drains them — say so once per
            # overpressure episode instead of silently shedding forever
            self._log_drop("span buffer full; dropping spans")

    # ---------------------------------------------------------------- export

    def _flush_loop(self) -> None:
        while not self._done.wait(self.FLUSH_EVERY):
            self.flush()
        self.flush()

    def flush(self) -> None:
        with self._mut:
            batch, self._buf = self._buf, []
            # a full flush cycle with zero drops ends the overpressure
            # episode: the NEXT buffer-full is a new edge worth a line.
            # Sustained overpressure (drops every cycle) keeps the edge
            # set, so the warn stays once-per-episode, never per batch.
            if self._dropped_since_flush == 0:
                self._buf_logged = False
            self._dropped_since_flush = 0
        if not batch or not self.endpoint:
            return
        try:
            payload = json.dumps(self._otlp(batch)).encode()
            req = urllib.request.Request(
                self.endpoint,
                data=payload,
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=5).read()
            with self._mut:
                self.exported += len(batch)
                recovered = self._outage
                self._outage = False
            if recovered:
                self._log_drop(
                    "collector reachable again; resuming span export",
                    recovered=True,
                )
        except Exception as exc:  # noqa: BLE001 — a dead collector must
            # not break the traced component; spans from this batch are
            # lost, counted, and the outage is logged ONCE (edge
            # check-and-set under the mutex, like _finish's)
            with self._mut:
                self.dropped += len(batch)
                log_edge = not self._outage
                self._outage = True
            if log_edge:
                self._log_drop(f"collector unreachable: {exc}")

    def _log_drop(self, message: str, recovered: bool = False) -> None:
        """One line per outage edge (never per batch — a dead collector
        at FLUSH_EVERY cadence would otherwise spam forever)."""
        from kwok_tpu.utils.log import get_logger

        log = get_logger("tracer")
        if recovered:
            log.info(message, service=self.service, dropped_total=self.dropped)
        else:
            log.warn(
                message,
                service=self.service,
                endpoint=self.endpoint,
                dropped_total=self.dropped,
            )

    def stats(self) -> dict:
        """Exporter health counters (scraped into /metrics as
        ``kwok_tracer_dropped_spans_total`` etc.)."""
        with self._mut:
            return {
                "dropped": self.dropped,
                "exported": self.exported,
                "buffered": len(self._buf),
                "outage": self._outage,
            }

    def _otlp(self, batch: List[Span]) -> dict:
        def attr(k, v):
            if isinstance(v, bool):
                return {"key": k, "value": {"boolValue": v}}
            if isinstance(v, int):
                return {"key": k, "value": {"intValue": str(v)}}
            if isinstance(v, float):
                return {"key": k, "value": {"doubleValue": v}}
            return {"key": k, "value": {"stringValue": str(v)}}

        res_attrs = [attr("service.name", self.service)] + [
            attr(k, v) for k, v in self.resource.items()
        ]
        spans = []
        for s in batch:
            rec = {
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "parentSpanId": s.parent_id or "",
                "name": s.name,
                "kind": 1,
                "startTimeUnixNano": str(s.start_ns),
                "endTimeUnixNano": str(s.end_ns),
                "attributes": [attr(k, v) for k, v in s.attributes.items()],
                "status": {"code": 1 if s.status_ok else 2},
            }
            if s.links:
                rec["links"] = [
                    {"traceId": t, "spanId": p} for t, p in s.links
                ]
            spans.append(rec)
        return {
            "resourceSpans": [
                {
                    "resource": {"attributes": res_attrs},
                    "scopeSpans": [
                        {"scope": {"name": "kwok-tpu"}, "spans": spans}
                    ],
                }
            ]
        }

    def stop(self) -> None:
        self._done.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ------------------------------------------------------------- propagation


def traceparent(span: Optional[Span]) -> Optional[str]:
    """W3C traceparent header for outgoing requests."""
    if span is None:
        return None
    return f"00-{span.trace_id}-{span.span_id}-01"


def from_traceparent(header: Optional[str]):
    """(trace_id, parent_span_id) out of an incoming header, or
    (None, None)."""
    if not header:
        return None, None
    parts = header.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None, None
    return parts[1], parts[2]


def context_of(span: Optional[Span]) -> Optional[tuple]:
    """``(trace_id, span_id)`` of a span, or None — the side-channel
    shape the store's commit ring carries per rv."""
    if span is None:
        return None
    return (span.trace_id, span.span_id)


def current_context() -> Optional[tuple]:
    """The calling thread's live span context on the process-global
    tracer, or None (tracer unset, disabled, or no span open).  The
    store's commit path reads this to stamp each rv with the committing
    write's context — pure observation, nothing control-flow."""
    tr = peek_global()
    if tr is None or not tr.enabled:
        return None
    return context_of(tr.current())


# ------------------------------------------------------------ global tracer

_global: Optional[Tracer] = None
_global_mut = threading.Lock()


def set_global(tracer: Optional[Tracer]) -> None:
    """Install (or with None, reset) the process-global tracer."""
    global _global
    with _global_mut:
        _global = tracer


def peek_global() -> Optional[Tracer]:
    """The installed global tracer, or None — without creating one
    (metrics exposition reads drop counters from whatever the process
    already configured; it must not instantiate a tracer as a side
    effect of a scrape)."""
    with _global_mut:
        return _global


def get_tracer(service: str = "kwok") -> Tracer:
    """Process-wide tracer; configured from ``KWOK_TRACE_ENDPOINT`` on
    first use (how kwokctl components inherit the collector address)."""
    global _global
    with _global_mut:
        if _global is None:
            _global = Tracer(
                service=os.environ.get("KWOK_TRACE_SERVICE", service),
                endpoint=os.environ.get("KWOK_TRACE_ENDPOINT") or None,
            )
        return _global


# ------------------------------------------------- journey / critical path
#
# Pure analysis over collector-format span dicts (cmd/tracing.py's
# storage shape): stitch one object's causally-linked spans into an
# ordered journey and attribute its end-to-end latency to the
# control-plane stages the PR 12 histograms only report in aggregate.
# Shared by the collector's /api/journey and /api/critical-path
# endpoints and the ``python -m kwok_tpu.utils.trace`` CLI.

#: span-name prefix -> critical-path stage bucket.  BOUNDED by
#: construction: every traced hot path names its spans from this
#: vocabulary, and anything else folds into "other".
_STAGE_PREFIXES = (
    ("client.", "client"),
    ("apiserver.", "commit"),
    ("schedule.", "sched"),
    ("gang.", "sched"),
    ("play.", "stage"),
)

#: attribution categories in waterfall order
STAGES = ("client", "queue", "commit", "watch", "sched", "stage", "other")


def classify_span(name: str) -> str:
    for prefix, stage in _STAGE_PREFIXES:
        if name.startswith(prefix):
            return stage
    return "other"


def span_attr(span: dict, key: str):
    """One attribute value out of a collector-format span, or None."""
    for a in span.get("attributes") or []:
        if a.get("key") == key:
            vals = a.get("value") or {}
            for v in vals.values():
                return v
    return None


def _span_ns(span: dict, field: str) -> int:
    try:
        return int(span.get(field) or 0)
    except (TypeError, ValueError):
        return 0


def linked_trace_ids(spans: List[dict]) -> set:
    """Every trace id reachable from these spans through OTLP links
    (one hop — links carry the causing write's context, so one
    expansion covers the watch-boundary stitch)."""
    out = set()
    for s in spans:
        for ln in s.get("links") or []:
            tid = ln.get("traceId")
            if tid:
                out.add(tid)
    return out


#: attribution priority when spans overlap: the innermost work wins
#: the instant (an apiserver PATCH nested inside a bind span is commit
#: work; the remainder of the bind is scheduling work)
_ATTRIBUTION_PRIORITY = ("commit", "sched", "stage", "client", "other")


def build_journey(spans: List[dict]) -> dict:
    """Order one object's causally-linked spans into a waterfall.

    Returns ``{"hops", "breakdown_s", "total_s", "t0_ns"}`` where each
    hop is ``{name, service, stage, start_s, duration_s, trace_id,
    span_id, parent_id}`` (start relative to the journey's first span)
    and ``breakdown_s`` partitions the total extent — every instant is
    attributed to exactly ONE stage, so the breakdown sums to
    ``total_s``: ``queue`` is the APF admission wait (apiserver spans'
    ``apf.wait_s`` attribute, carved out of ``commit``), ``commit`` the
    apiserver handling, ``watch`` the uncovered gaps (rv-commit ->
    consumer-pickup: delivery lag plus consumer queueing and stage
    delays), ``sched``/``stage``/``client`` the respective spans' own
    busy time with nested-span instants going to the innermost work
    (priority commit > sched > stage > client)."""
    spans = [s for s in spans if _span_ns(s, "startTimeUnixNano") > 0]
    spans.sort(key=lambda s: _span_ns(s, "startTimeUnixNano"))
    if not spans:
        return {"hops": [], "breakdown_s": {}, "total_s": 0.0, "t0_ns": 0}
    t0 = _span_ns(spans[0], "startTimeUnixNano")
    t_end = max(_span_ns(s, "endTimeUnixNano") for s in spans)
    hops = []
    intervals: List[tuple] = []  # (start_ns, end_ns, stage)
    queue_s = 0.0
    for s in spans:
        start = _span_ns(s, "startTimeUnixNano")
        end = max(_span_ns(s, "endTimeUnixNano"), start)
        stage = classify_span(str(s.get("name") or ""))
        hops.append(
            {
                "name": str(s.get("name") or ""),
                "service": str(s.get("service") or ""),
                "stage": stage,
                "start_s": round((start - t0) / 1e9, 6),
                "duration_s": round((end - start) / 1e9, 6),
                "trace_id": str(s.get("traceId") or ""),
                "span_id": str(s.get("spanId") or ""),
                "parent_id": str(s.get("parentSpanId") or ""),
            }
        )
        intervals.append((start, end, stage))
        if stage == "commit":
            try:
                queue_s += float(span_attr(s, "apf.wait_s") or 0.0)
            except (TypeError, ValueError):
                pass

    # boundary sweep: between each pair of adjacent span boundaries
    # exactly one stage wins the segment (innermost-work priority), and
    # segments no span covers are the watch-boundary gaps — so the
    # breakdown PARTITIONS the extent and sums to total_s
    rank = {st: i for i, st in enumerate(_ATTRIBUTION_PRIORITY)}
    bounds = sorted({b for a, e, _ in intervals for b in (a, e)})
    breakdown = {st: 0.0 for st in STAGES}
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        active = [st for (s0, s1, st) in intervals if s0 <= a and b <= s1]
        seg = (b - a) / 1e9
        if active:
            breakdown[min(active, key=lambda st: rank.get(st, 99))] += seg
        else:
            breakdown["watch"] += seg
    total_s = (t_end - t0) / 1e9
    queue_s = min(queue_s, breakdown["commit"])
    breakdown["queue"] = round(queue_s, 6)
    breakdown["commit"] = round(breakdown["commit"] - queue_s, 6)
    for st in breakdown:
        breakdown[st] = round(breakdown[st], 6)
    return {
        "hops": hops,
        "breakdown_s": breakdown,
        "total_s": round(total_s, 6),
        "t0_ns": t0,
    }


def critical_path(journeys: List[dict]) -> dict:
    """Aggregate N journeys (``build_journey`` outputs) into a
    time-to-running budget: per-stage mean/max seconds plus each
    stage's share of the summed extent — ROADMAP item 1's ``host_build``
    wall generalized into an attributed breakdown."""
    n = len(journeys)
    if n == 0:
        return {"journeys": 0, "stages": {}, "total_s": {"mean": 0.0, "max": 0.0}}
    sums = {st: 0.0 for st in STAGES}
    maxes = {st: 0.0 for st in STAGES}
    totals = [float(j.get("total_s") or 0.0) for j in journeys]
    for j in journeys:
        for st in STAGES:
            v = float((j.get("breakdown_s") or {}).get(st) or 0.0)
            sums[st] += v
            maxes[st] = max(maxes[st], v)
    grand = sum(totals) or 1.0
    stages = {
        st: {
            "mean_s": round(sums[st] / n, 6),
            "max_s": round(maxes[st], 6),
            "share": round(sums[st] / grand, 4),
        }
        for st in STAGES
        if sums[st] > 0.0 or st in ("commit", "watch")
    }
    return {
        "journeys": n,
        "stages": stages,
        "total_s": {
            "mean": round(sum(totals) / n, 6),
            "max": round(max(totals), 6),
        },
    }


def _cli_main(argv=None) -> int:
    """``python -m kwok_tpu.utils.trace --critical-path`` — query the
    collector's journey surface and render the time-to-running budget
    (the offline twin of ``GET /api/critical-path``)."""
    import argparse
    import json as _json

    p = argparse.ArgumentParser(
        prog="kwok-tpu-trace",
        description="critical-path attribution over collected journeys",
    )
    p.add_argument(
        "--critical-path",
        action="store_true",
        help="aggregate recent journeys into a per-stage latency budget",
    )
    p.add_argument(
        "--collector",
        default=os.environ.get("KWOK_TRACE_ENDPOINT", "http://127.0.0.1:4318"),
        help="collector base URL (KWOK_TRACE_ENDPOINT also accepted)",
    )
    p.add_argument("--limit", type=int, default=50, help="journeys to aggregate")
    p.add_argument("--json", action="store_true", help="raw JSON output")
    args = p.parse_args(argv)
    if not args.critical_path:
        p.error("nothing to do: pass --critical-path")
    base = args.collector.split("/v1/traces")[0].rstrip("/")
    url = f"{base}/api/critical-path?limit={args.limit}"
    try:
        data = _json.loads(urllib.request.urlopen(url, timeout=10).read())
    except OSError as exc:
        print(f"collector unreachable at {base}: {exc}")
        return 1
    if args.json:
        print(_json.dumps(data, indent=2))
        return 0
    n = data.get("journeys", 0)
    tot = data.get("total_s") or {}
    print(
        f"critical path over {n} journeys "
        f"(time-to-running mean {tot.get('mean', 0):.3f}s, "
        f"max {tot.get('max', 0):.3f}s)"
    )
    stages = data.get("stages") or {}
    for st in STAGES:
        row = stages.get(st)
        if row is None:
            continue
        bar = "#" * int(40 * float(row.get("share") or 0.0))
        print(
            f"  {st:<7} {row.get('mean_s', 0):>9.4f}s mean  "
            f"{row.get('max_s', 0):>9.4f}s max  "
            f"{100 * float(row.get('share') or 0):>5.1f}%  {bar}"
        )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_cli_main())
