"""CEL expression engine for Metric / ResourceUsage evaluation.

The reference evaluates Metric and ResourceUsage expressions with cel-go
(``pkg/utils/cel/environment.go:39`` ``NewEnvironment``, program cache at
``environment.go:98-114``, ``AsFloat64:117``), exposing vars ``node``/``pod``/
``container``, funcs ``Now``/``Rand``/``SinceSecond``/``UnixSecond``/``Quantity``
(``pkg/utils/cel/default.go:77-84``, ``funcs.go:27-45``) and a ``Quantity``
wrapper with full arithmetic traits (``pkg/utils/cel/quantity.go``).

This is a from-scratch implementation of the CEL subset those configs use
(see ``charts/metrics-usage/templates/*.yaml``): literals, field selection,
indexing, ``in``, function/method calls, unary ``!``/``-``, the full binary
operator ladder, and the ternary conditional.  Programs compile to Python
closures for the host path, and the AST is exposed (``Program.ast``) so the
metrics layer can lower row-local arithmetic onto the device SoA instead of
looping objects — the TPU-side equivalent of kwok's per-object cel-go calls.
"""

from __future__ import annotations

import math
import random
import re
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CELError",
    "Quantity",
    "parse_quantity",
    "Environment",
    "EnvironmentConfig",
    "Program",
    "as_float64",
    "as_string",
    "parse",
]


class CELError(ValueError):
    """Raised for lexing, parsing, or evaluation failures."""


# ---------------------------------------------------------------------------
# Quantity — k8s resource.Quantity semantics (suffix parse/format, arithmetic)
# ---------------------------------------------------------------------------

_BINARY_SUFFIXES = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
_DECIMAL_SUFFIXES = {
    "n": 10**-9,
    "u": 10**-6,
    "m": 10**-3,
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QUANTITY_RE = re.compile(
    r"^([+-]?[0-9]+(?:\.[0-9]*)?|[+-]?\.[0-9]+)"
    r"(Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E|"
    r"[eE][+-]?[0-9]+)?$"
)


def parse_quantity(s: str) -> float:
    """Parse a k8s quantity string (``100m``, ``1Gi``, ``12e6``) to a float."""
    s = s.strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise CELError(f"invalid quantity: {s!r}")
    number, suffix = m.group(1), m.group(2) or ""
    value = float(number)
    if suffix in _BINARY_SUFFIXES:
        return value * _BINARY_SUFFIXES[suffix]
    if suffix in _DECIMAL_SUFFIXES:
        return value * _DECIMAL_SUFFIXES[suffix]
    # exponent form 12e6 / 3E2
    return float(number + suffix)


class Quantity:
    """k8s ``resource.Quantity`` with nano-scaled integer arithmetic.

    Mirrors the adder/comparer/divider/multiplier/negator/subtractor traits of
    the reference's CEL wrapper (``pkg/utils/cel/quantity.go:30-38``): internal
    representation is an int64 count of nano-units so ``100m + 100m == 200m``
    exactly, with float conversion via :meth:`as_float` (``AsApproximateFloat64``).
    """

    __slots__ = ("nano", "_text")

    def __init__(self, value: Any = 0, _text: Optional[str] = None):
        if isinstance(value, Quantity):
            self.nano = value.nano
            self._text = value._text
        elif isinstance(value, str):
            self.nano = round(parse_quantity(value) * 10**9)
            self._text = value.strip()
        elif isinstance(value, bool):
            raise CELError("cannot make a Quantity from bool")
        elif isinstance(value, (int, float)):
            self.nano = round(float(value) * 10**9)
            self._text = _text
        else:
            raise CELError(f"cannot make a Quantity from {type(value).__name__}")

    def as_float(self) -> float:
        return self.nano / 10**9

    def __repr__(self) -> str:
        return f"Quantity({self.format()!r})"

    def format(self) -> str:
        """Canonical-ish formatting: keep original text when untouched."""
        if self._text is not None:
            return self._text
        nano = self.nano
        if nano == 0:
            return "0"
        if nano % 10**9 == 0:
            return str(nano // 10**9)
        if nano % 10**6 == 0:
            return f"{nano // 10**6}m"
        if nano % 10**3 == 0:
            return f"{nano // 10**3}u"
        return f"{nano}n"

    # arithmetic traits ----------------------------------------------------
    def _coerce(self, other: Any) -> "Quantity":
        if isinstance(other, Quantity):
            return other
        if isinstance(other, (int, float)) and not isinstance(other, bool):
            return Quantity(other)
        raise CELError(f"no such overload: Quantity and {type(other).__name__}")

    def __add__(self, other):
        q = Quantity(0)
        q.nano = self.nano + self._coerce(other).nano
        return q

    __radd__ = __add__

    def __sub__(self, other):
        q = Quantity(0)
        q.nano = self.nano - self._coerce(other).nano
        return q

    def __rsub__(self, other):
        return self._coerce(other).__sub__(self)

    def __mul__(self, other):
        if isinstance(other, Quantity):
            other = other.as_float()
        elif not _is_number(other):
            raise CELError(f"no such overload: Quantity * {type(other).__name__}")
        q = Quantity(0)
        q.nano = round(self.nano * float(other))
        return q

    __rmul__ = __mul__

    def __truediv__(self, other):
        if isinstance(other, Quantity):
            if other.nano == 0:
                raise CELError("quantity division by zero")
            return self.nano / other.nano  # ratio → double
        if not _is_number(other):
            raise CELError(f"no such overload: Quantity / {type(other).__name__}")
        if float(other) == 0:
            raise CELError("quantity division by zero")
        q = Quantity(0)
        q.nano = round(self.nano / float(other))
        return q

    def __neg__(self):
        q = Quantity(0)
        q.nano = -self.nano
        return q

    def __eq__(self, other):
        # Only Quantity==Quantity at the Python level so hash stays consistent
        # with eq; CEL's number-coercing `==` lives in Environment._equals.
        if not isinstance(other, Quantity):
            return NotImplemented
        return self.nano == other.nano

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq

    def __lt__(self, other):
        return self.nano < self._coerce(other).nano

    def __le__(self, other):
        return self.nano <= self._coerce(other).nano

    def __gt__(self, other):
        return self.nano > self._coerce(other).nano

    def __ge__(self, other):
        return self.nano >= self._coerce(other).nano

    def __hash__(self):
        return hash(self.nano)


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>//[^\n]*)
  | (?P<float>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+[eE][+-]?\d+)
  | (?P<int>0x[0-9a-fA-F]+|\d+)
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><=|>=|==|!=|&&|\|\||[-+*/%!<>?:.,()\[\]{}])
    """,
    re.VERBOSE,
)

_ESCAPES = {
    "n": "\n",
    "t": "\t",
    "r": "\r",
    "\\": "\\",
    '"': '"',
    "'": "'",
    "a": "\a",
    "b": "\b",
    "f": "\f",
    "v": "\v",
    "0": "\0",
}


@dataclass
class _Tok:
    kind: str
    text: str
    pos: int


def _lex(src: str) -> List[_Tok]:
    toks: List[_Tok] = []
    pos = 0
    n = len(src)
    while pos < n:
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise CELError(f"unexpected character {src[pos]!r} at {pos}")
        kind = m.lastgroup or ""
        if kind not in ("ws", "comment"):
            toks.append(_Tok(kind, m.group(), pos))
        pos = m.end()
    toks.append(_Tok("eof", "", n))
    return toks


def _unquote(text: str) -> str:
    body = text[1:-1]
    out: List[str] = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            nxt = body[i + 1]
            if nxt == "u" and i + 5 < len(body):
                out.append(chr(int(body[i + 2 : i + 6], 16)))
                i += 6
                continue
            out.append(_ESCAPES.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Lit:
    value: Any


@dataclass(frozen=True)
class Ident:
    name: str


@dataclass(frozen=True)
class Select:
    operand: Any
    field: str


@dataclass(frozen=True)
class Index:
    operand: Any
    index: Any


@dataclass(frozen=True)
class Call:
    target: Optional[Any]  # None for global function
    name: str
    args: Tuple[Any, ...]


@dataclass(frozen=True)
class Unary:
    op: str
    operand: Any


@dataclass(frozen=True)
class Binary:
    op: str
    left: Any
    right: Any


@dataclass(frozen=True)
class Ternary:
    cond: Any
    then: Any
    other: Any


@dataclass(frozen=True)
class ListLit:
    items: Tuple[Any, ...]


@dataclass(frozen=True)
class MapLit:
    entries: Tuple[Tuple[Any, Any], ...]


class _Parser:
    def __init__(self, toks: List[_Tok]):
        self.toks = toks
        self.i = 0

    def peek(self) -> _Tok:
        return self.toks[self.i]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> _Tok:
        t = self.next()
        if t.text != text:
            raise CELError(f"expected {text!r}, got {t.text!r} at {t.pos}")
        return t

    # CEL precedence: ternary < || < && < relational < additive <
    # multiplicative < unary < member/index/call < primary
    def parse(self):
        e = self.ternary()
        t = self.peek()
        if t.kind != "eof":
            raise CELError(f"trailing input at {t.pos}: {t.text!r}")
        return e

    def ternary(self):
        cond = self.logical_or()
        if self.peek().text == "?":
            self.next()
            then = self.ternary()
            self.expect(":")
            other = self.ternary()
            return Ternary(cond, then, other)
        return cond

    def logical_or(self):
        e = self.logical_and()
        while self.peek().text == "||":
            self.next()
            e = Binary("||", e, self.logical_and())
        return e

    def logical_and(self):
        e = self.relation()
        while self.peek().text == "&&":
            self.next()
            e = Binary("&&", e, self.relation())
        return e

    def relation(self):
        e = self.additive()
        while True:
            t = self.peek()
            if t.text in ("<", "<=", ">", ">=", "==", "!=") or (
                t.kind == "ident" and t.text == "in"
            ):
                self.next()
                e = Binary(t.text, e, self.additive())
            else:
                return e

    def additive(self):
        e = self.multiplicative()
        while self.peek().text in ("+", "-"):
            op = self.next().text
            e = Binary(op, e, self.multiplicative())
        return e

    def multiplicative(self):
        e = self.unary()
        while self.peek().text in ("*", "/", "%"):
            op = self.next().text
            e = Binary(op, e, self.unary())
        return e

    def unary(self):
        t = self.peek()
        if t.text in ("!", "-"):
            self.next()
            return Unary(t.text, self.unary())
        return self.member()

    def member(self):
        e = self.primary()
        while True:
            t = self.peek()
            if t.text == ".":
                self.next()
                name = self.next()
                if name.kind != "ident":
                    raise CELError(f"expected field name at {name.pos}")
                if self.peek().text == "(":
                    e = Call(e, name.text, self.call_args())
                else:
                    e = Select(e, name.text)
            elif t.text == "[":
                self.next()
                idx = self.ternary()
                self.expect("]")
                e = Index(e, idx)
            else:
                return e

    def call_args(self) -> Tuple[Any, ...]:
        self.expect("(")
        args: List[Any] = []
        if self.peek().text != ")":
            args.append(self.ternary())
            while self.peek().text == ",":
                self.next()
                args.append(self.ternary())
        self.expect(")")
        return tuple(args)

    def primary(self):
        t = self.next()
        if t.kind == "int":
            return Lit(int(t.text, 0))
        if t.kind == "float":
            return Lit(float(t.text))
        if t.kind == "string":
            return Lit(_unquote(t.text))
        if t.kind == "ident":
            if t.text == "true":
                return Lit(True)
            if t.text == "false":
                return Lit(False)
            if t.text == "null":
                return Lit(None)
            if self.peek().text == "(":
                return Call(None, t.text, self.call_args())
            return Ident(t.text)
        if t.text == "(":
            e = self.ternary()
            self.expect(")")
            return e
        if t.text == "[":
            items: List[Any] = []
            if self.peek().text != "]":
                items.append(self.ternary())
                while self.peek().text == ",":
                    self.next()
                    items.append(self.ternary())
            self.expect("]")
            return ListLit(tuple(items))
        if t.text == "{":
            entries: List[Tuple[Any, Any]] = []
            if self.peek().text != "}":
                while True:
                    k = self.ternary()
                    self.expect(":")
                    v = self.ternary()
                    entries.append((k, v))
                    if self.peek().text != ",":
                        break
                    self.next()
            self.expect("}")
            return MapLit(tuple(entries))
        raise CELError(f"unexpected token {t.text!r} at {t.pos}")


def parse(src: str):
    """Parse a CEL expression into its AST."""
    return _Parser(_lex(src)).parse()


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


class _Obj:
    """Typed wrapper for node/pod/container vars.

    The reference dispatches CEL methods by Go type (``corev1.Node`` vs
    ``corev1.Pod`` — ``pkg/kwok/metrics/evaluator.go:75-121``); here the
    wrapper carries the k8s ``role`` so Usage/CumulativeUsage overloads can
    resolve, while plain field selection falls through to the dict.
    """

    __slots__ = ("role", "obj")

    def __init__(self, role: str, obj: dict):
        self.role = role
        self.obj = obj or {}


def _is_number(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _truthy(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    raise CELError(f"condition is not a bool: {type(v).__name__}")


_STRING_METHODS = {
    "startsWith": lambda s, p: s.startswith(p),
    "endsWith": lambda s, p: s.endswith(p),
    "contains": lambda s, p: p in s,
    "matches": lambda s, p: re.search(p, s) is not None,
    "size": lambda s: len(s),
}


@dataclass
class Program:
    """A compiled CEL program: ``eval`` it with var bindings."""

    source: str
    ast: Any
    env: "Environment"

    def eval(self, bindings: Optional[Dict[str, Any]] = None) -> Any:
        return self.env._eval(self.ast, bindings or {})


@dataclass
class EnvironmentConfig:
    """Hooks mirroring the reference's ``EnvironmentConfig``
    (``pkg/kwok/metrics/evaluator.go:35-49``)."""

    now: Optional[Callable[[], float]] = None
    rand: Optional[Callable[[], float]] = None
    started_containers_total: Optional[Callable[[str], float]] = None
    container_resource_usage: Optional[Callable[[str, str, str, str], float]] = None
    pod_resource_usage: Optional[Callable[[str, str, str], float]] = None
    node_resource_usage: Optional[Callable[[str, str], float]] = None
    container_resource_cumulative_usage: Optional[
        Callable[[str, str, str, str], float]
    ] = None
    pod_resource_cumulative_usage: Optional[Callable[[str, str, str], float]] = None
    node_resource_cumulative_usage: Optional[Callable[[str, str], float]] = None
    funcs: Dict[str, Callable] = field(default_factory=dict)


def _rfc3339_to_unix(s: str) -> float:
    from kwok_tpu.utils.expression import parse_rfc3339

    t = parse_rfc3339(s)
    if t is None:
        raise CELError(f"invalid RFC3339 timestamp: {s!r}")
    return t.timestamp()


class Environment:
    """CEL evaluation environment with a program cache.

    Equivalent of ``pkg/utils/cel/environment.go:39`` ``NewEnvironment`` +
    ``pkg/kwok/metrics/evaluator.go:52`` with vars ``node``/``pod``/``container``.
    """

    def __init__(self, conf: Optional[EnvironmentConfig] = None):
        self.conf = conf or EnvironmentConfig()
        self._cache: Dict[str, Program] = {}
        self._lock = threading.Lock()

    # -- compilation -------------------------------------------------------
    def compile(self, src: str) -> Program:
        with self._lock:
            prog = self._cache.get(src)
            if prog is None:
                prog = Program(src, parse(src), self)
                self._cache[src] = prog
            return prog

    # -- vars --------------------------------------------------------------
    @staticmethod
    def node_var(node: dict) -> _Obj:
        return _Obj("node", node)

    @staticmethod
    def pod_var(pod: dict) -> _Obj:
        return _Obj("pod", pod)

    @staticmethod
    def container_var(container: dict) -> _Obj:
        return _Obj("container", container)

    # -- evaluation --------------------------------------------------------
    def _eval(self, node: Any, env: Dict[str, Any]) -> Any:
        ev = self._eval
        if isinstance(node, Lit):
            return node.value
        if isinstance(node, Ident):
            if node.name in env:
                return env[node.name]
            raise CELError(f"undeclared reference: {node.name}")
        if isinstance(node, Select):
            operand = ev(node.operand, env)
            return self._select(operand, node.field)
        if isinstance(node, Index):
            operand = ev(node.operand, env)
            idx = ev(node.index, env)
            return self._index(operand, idx)
        if isinstance(node, Call):
            return self._call(node, env)
        if isinstance(node, Unary):
            v = ev(node.operand, env)
            if node.op == "!":
                return not _truthy(v)
            if node.op == "-":
                if isinstance(v, Quantity) or _is_number(v):
                    return -v
                raise CELError(f"no such overload: -{type(v).__name__}")
        if isinstance(node, Binary):
            return self._binary(node, env)
        if isinstance(node, Ternary):
            if _truthy(ev(node.cond, env)):
                return ev(node.then, env)
            return ev(node.other, env)
        if isinstance(node, ListLit):
            return [ev(i, env) for i in node.items]
        if isinstance(node, MapLit):
            return {ev(k, env): ev(v, env) for k, v in node.entries}
        raise CELError(f"unknown AST node: {node!r}")

    @staticmethod
    def _select(operand: Any, fld: str) -> Any:
        if isinstance(operand, _Obj):
            operand = operand.obj
        if isinstance(operand, dict):
            if fld in operand:
                return operand[fld]
            return None
        raise CELError(f"cannot select {fld!r} from {type(operand).__name__}")

    @staticmethod
    def _index(operand: Any, idx: Any) -> Any:
        if isinstance(operand, _Obj):
            operand = operand.obj
        if isinstance(operand, dict):
            if idx in operand:
                return operand[idx]
            raise CELError(f"no such key: {idx!r}")
        if isinstance(operand, (list, str)):
            if not isinstance(idx, int) or isinstance(idx, bool):
                raise CELError("index must be an int")
            if not 0 <= idx < len(operand):
                raise CELError(f"index out of range: {idx}")
            return operand[idx]
        raise CELError(f"cannot index {type(operand).__name__}")

    def _binary(self, node: Binary, env: Dict[str, Any]) -> Any:
        op = node.op
        if op == "&&":
            return _truthy(self._eval(node.left, env)) and _truthy(
                self._eval(node.right, env)
            )
        if op == "||":
            return _truthy(self._eval(node.left, env)) or _truthy(
                self._eval(node.right, env)
            )
        left = self._eval(node.left, env)
        right = self._eval(node.right, env)
        if op == "in":
            if isinstance(right, _Obj):
                right = right.obj
            if right is None:
                # absent map/list field: cel-go over typed k8s objects
                # yields an empty map there (e.g. `"k" in
                # pod.metadata.annotations` on a pod with no
                # annotations), so membership is simply false
                return False
            if isinstance(right, dict):
                return left in right
            if isinstance(right, (list, str)):
                return left in right
            raise CELError(f"cannot apply 'in' to {type(right).__name__}")
        if op == "==":
            return self._equals(left, right)
        if op == "!=":
            return not self._equals(left, right)
        if op in ("<", "<=", ">", ">="):
            self._check_comparable(left, right, op)
            if op == "<":
                return left < right
            if op == "<=":
                return left <= right
            if op == ">":
                return left > right
            return left >= right
        # arithmetic
        if op == "+":
            if isinstance(left, str) and isinstance(right, str):
                return left + right
            if isinstance(left, list) and isinstance(right, list):
                return left + right
            return self._arith(left, right, op)
        if op in ("-", "*", "/", "%"):
            return self._arith(left, right, op)
        raise CELError(f"unknown operator {op!r}")

    @staticmethod
    def _equals(left: Any, right: Any) -> bool:
        if isinstance(left, Quantity) or isinstance(right, Quantity):
            try:
                lq = left if isinstance(left, Quantity) else Quantity(left)
                rq = right if isinstance(right, Quantity) else Quantity(right)
                return lq.nano == rq.nano
            except CELError:
                return False
        return bool(left == right)

    @staticmethod
    def _check_comparable(left: Any, right: Any, op: str) -> None:
        ok = (
            (_is_number(left) and _is_number(right))
            or (isinstance(left, str) and isinstance(right, str))
            or (isinstance(left, bool) and isinstance(right, bool))
            or isinstance(left, Quantity)
            or isinstance(right, Quantity)
        )
        if not ok:
            raise CELError(
                f"no such overload: {type(left).__name__} {op} {type(right).__name__}"
            )

    @staticmethod
    def _arith(left: Any, right: Any, op: str) -> Any:
        if isinstance(left, Quantity) or isinstance(right, Quantity):
            if op == "+":
                return left + right
            if op == "-":
                return left - right
            if op == "*":
                return left * right
            if op == "/":
                return left / right
            raise CELError(f"no such overload: Quantity {op} Quantity")
        if not (_is_number(left) and _is_number(right)):
            raise CELError(
                f"no such overload: {type(left).__name__} {op} {type(right).__name__}"
            )
        if op == "+":
            return left + right
        if op == "-":
            return left - right
        if op == "*":
            return left * right
        if op == "/":
            if isinstance(left, int) and isinstance(right, int):
                if right == 0:
                    raise CELError("division by zero")
                q = abs(left) // abs(right)  # CEL int division truncates
                return -q if (left < 0) != (right < 0) else q
            if right == 0:
                raise CELError("division by zero")
            return left / right
        if op == "%":
            if not (isinstance(left, int) and isinstance(right, int)):
                raise CELError("modulo requires ints")
            if right == 0:
                raise CELError("modulo by zero")
            r = abs(left) % abs(right)  # Go-style truncated modulo
            return -r if left < 0 else r
        raise CELError(f"unknown arithmetic op {op!r}")

    # -- calls -------------------------------------------------------------
    def _call(self, node: Call, env: Dict[str, Any]) -> Any:
        args = [self._eval(a, env) for a in node.args]
        name = node.name
        if node.target is None:
            return self._global_call(name, args)
        target = self._eval(node.target, env)
        return self._method_call(target, name, args)

    def _now(self) -> float:
        return self.conf.now() if self.conf.now else _time.time()

    def _global_call(self, name: str, args: List[Any]) -> Any:
        conf = self.conf
        if name in conf.funcs:
            return conf.funcs[name](*args)
        if name in ("Now", "now") and not args:
            return self._now()
        if name == "Rand" and not args:
            return conf.rand() if conf.rand else random.random()
        if name == "UnixSecond" and len(args) == 1:
            return self._unix_second(args[0])
        if name == "SinceSecond" and len(args) == 1:
            return self._since_second(args[0])
        if name == "Quantity" and len(args) == 1:
            return Quantity(args[0])
        if name in ("StartedContainersTotal", "startedContainersTotal") and len(args) == 1:
            return self._started_containers_total(args[0])
        if name == "size" and len(args) == 1:
            if isinstance(args[0], (str, list, dict, bytes)):
                return len(args[0])
            raise CELError(f"size: unsupported type {type(args[0]).__name__}")
        if name == "string" and len(args) == 1:
            return self._to_string(args[0])
        if name == "int" and len(args) == 1:
            v = args[0]
            if isinstance(v, str):
                try:
                    return int(v, 0)
                except ValueError as exc:
                    raise CELError(f"int: cannot parse {v!r}") from exc
            return int(as_float64(v))
        if name == "double" and len(args) == 1:
            v = args[0]
            if isinstance(v, str):
                try:
                    return float(v)
                except ValueError as exc:
                    raise CELError(f"double: cannot parse {v!r}") from exc
            return as_float64(v)
        if name == "bool" and len(args) == 1:
            v = args[0]
            if isinstance(v, bool):
                return v
            if isinstance(v, str):  # CEL bool(string) parses the literal
                if v.lower() in ("true", "1", "t"):
                    return True
                if v.lower() in ("false", "0", "f"):
                    return False
                raise CELError(f"bool: cannot parse {v!r}")
            raise CELError(f"bool: unsupported type {type(v).__name__}")
        if name in ("min", "max") and args:
            vals = args[0] if len(args) == 1 and isinstance(args[0], list) else args
            if not vals:
                raise CELError(f"{name}: empty argument list")
            try:
                return (min if name == "min" else max)(vals)
            except TypeError as exc:
                raise CELError(f"{name}: incomparable arguments") from exc
        if name in ("ceil", "floor") and len(args) == 1:
            f = as_float64(args[0])  # numbers, bools, Quantity
            return math.ceil(f) if name == "ceil" else math.floor(f)
        raise CELError(f"undeclared function: {name}/{len(args)}")

    @staticmethod
    def _to_string(v: Any) -> str:
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, Quantity):
            return v.format()
        if isinstance(v, float) and v.is_integer():
            return str(int(v))
        return str(v)

    def _unix_second(self, v: Any) -> float:
        if _is_number(v):
            return float(v)
        if isinstance(v, str):
            return _rfc3339_to_unix(v)
        raise CELError(f"UnixSecond: unsupported type {type(v).__name__}")

    def _since_second(self, v: Any) -> float:
        # reference: time.Since(creationTimestamp) — funcs.go:34-40
        obj = v.obj if isinstance(v, _Obj) else v
        if not isinstance(obj, dict):
            raise CELError("SinceSecond expects a resource object")
        ts = (obj.get("metadata") or {}).get("creationTimestamp")
        if not ts:
            return 0.0
        return self._now() - _rfc3339_to_unix(ts)

    def _started_containers_total(self, v: Any) -> float:
        hook = self.conf.started_containers_total
        if hook is None:
            raise CELError("StartedContainersTotal is not configured")
        if isinstance(v, _Obj):
            name = ((v.obj.get("metadata") or {}).get("name")) or ""
            return float(hook(name))
        if isinstance(v, str):
            return float(hook(v))
        raise CELError("StartedContainersTotal expects a node or node name")

    def _method_call(self, target: Any, name: str, args: List[Any]) -> Any:
        conf = self.conf
        if isinstance(target, str) and name in _STRING_METHODS:
            return _STRING_METHODS[name](target, *args)
        if name == "size" and not args:
            if isinstance(target, _Obj):
                target = target.obj
            return len(target)
        if name in ("SinceSecond",) and not args:
            return self._since_second(target)
        if name in ("UnixSecond",) and not args:
            return self._unix_second(target)
        if name in ("StartedContainersTotal", "startedContainersTotal") and not args:
            return self._started_containers_total(target)
        if isinstance(target, _Obj):
            meta = target.obj.get("metadata") or {}
            ns = meta.get("namespace") or ""
            obj_name = meta.get("name") or ""
            if name == "Usage":
                if target.role == "pod" and len(args) == 2:
                    if conf.container_resource_usage is None:
                        raise CELError("container Usage is not configured")
                    return conf.container_resource_usage(args[0], ns, obj_name, args[1])
                if target.role == "pod" and len(args) == 1:
                    if conf.pod_resource_usage is None:
                        raise CELError("pod Usage is not configured")
                    return conf.pod_resource_usage(args[0], ns, obj_name)
                if target.role == "node" and len(args) == 1:
                    if conf.node_resource_usage is None:
                        raise CELError("node Usage is not configured")
                    return conf.node_resource_usage(args[0], obj_name)
            if name == "CumulativeUsage":
                if target.role == "pod" and len(args) == 2:
                    if conf.container_resource_cumulative_usage is None:
                        raise CELError("container CumulativeUsage is not configured")
                    return conf.container_resource_cumulative_usage(
                        args[0], ns, obj_name, args[1]
                    )
                if target.role == "pod" and len(args) == 1:
                    if conf.pod_resource_cumulative_usage is None:
                        raise CELError("pod CumulativeUsage is not configured")
                    return conf.pod_resource_cumulative_usage(args[0], ns, obj_name)
                if target.role == "node" and len(args) == 1:
                    if conf.node_resource_cumulative_usage is None:
                        raise CELError("node CumulativeUsage is not configured")
                    return conf.node_resource_cumulative_usage(args[0], obj_name)
        raise CELError(
            f"no such method: {type(target).__name__}.{name}/{len(args)}"
        )


# ---------------------------------------------------------------------------
# Result conversion — reference environment.go:117 AsFloat64 / :139 AsString
# ---------------------------------------------------------------------------


def as_float64(v: Any) -> float:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, Quantity):
        return v.as_float()
    raise CELError(f"unsupported type for AsFloat64: {type(v).__name__}")


def as_string(v: Any) -> str:
    if isinstance(v, str):
        return v
    raise CELError(f"unsupported type for AsString: {type(v).__name__}")
