"""Shared TLS posture for the cluster's serving surfaces.

One place for the server-side SSLContext so the apiserver
(cluster/apiserver.py) and the kubelet surface (server/server.py)
cannot drift: TLS-server protocol, the serving cert pair, and an
optional client CA with OPTIONAL verification (the kubelet's
client-auth posture; reference server.go:446-533).
"""

from __future__ import annotations

import ssl
from typing import Optional


def build_server_ssl_context(
    cert_file: str, key_file: str, client_ca: Optional[str] = None
) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file)
    if client_ca:
        ctx.load_verify_locations(client_ca)
        ctx.verify_mode = ssl.CERT_OPTIONAL
    return ctx
