"""Structured logging (the pkg/log equivalent).

The reference wraps slog with levels, key=value attributes, context
carrying, and ``KObj`` object references (reference pkg/log/logger.go;
SURVEY.md:356 records the role).
This is the same shape on stdlib logging: one process-wide root with
``key=value`` formatting, ``with_values`` child loggers, a ``kobj``
helper rendering ``ns/name`` refs, and a ``-v`` flag mapping
(0=info, 1=debug, 2+=everything including third-party)."""

from __future__ import annotations

import logging
import sys
import threading
import time
from typing import Any, Optional

__all__ = ["Logger", "get_logger", "setup", "kobj"]

_setup_done = False
_setup_mut = threading.Lock()


def kobj(obj: Optional[dict]) -> str:
    """Render an object reference as ``ns/name`` (pkg/log KObj)."""
    if not obj:
        return "<nil>"
    meta = obj.get("metadata") or {}
    ns = meta.get("namespace") or ""
    name = meta.get("name") or ""
    return f"{ns}/{name}" if ns else name


class _KVFormatter(logging.Formatter):
    """``HH:MM:SS.mmm LEVEL component message key=value ...``"""

    def format(self, record: logging.LogRecord) -> str:
        t = time.strftime("%H:%M:%S", time.localtime(record.created))
        ms = int(record.msecs)
        parts = [
            f"{t}.{ms:03d}",
            record.levelname,
            record.name,
            record.getMessage(),
        ]
        kvs = getattr(record, "kwok_kvs", None)
        if kvs:
            parts.extend(f"{k}={_render(v)}" for k, v in kvs.items())
        if record.exc_info:
            parts.append("\n" + self.formatException(record.exc_info))
        return " ".join(parts)


def _render(v: Any) -> str:
    if isinstance(v, dict) and "metadata" in v:
        return kobj(v)
    s = str(v)
    return f'"{s}"' if " " in s else s


class Logger:
    """Level methods carry trailing ``key=value`` attributes:
    ``log.info("played stage", pod=obj, stage=name)``."""

    def __init__(self, base: logging.Logger, values: Optional[dict] = None):
        self._base = base
        self._values = dict(values or {})

    def with_values(self, **kvs: Any) -> "Logger":
        merged = dict(self._values)
        merged.update(kvs)
        return Logger(self._base, merged)

    def _log(self, level: int, msg: str, kvs: dict, exc_info=None) -> None:
        if not self._base.isEnabledFor(level):
            return
        merged = dict(self._values)
        merged.update(kvs)
        self._base.log(level, msg, extra={"kwok_kvs": merged}, exc_info=exc_info)

    def debug(self, msg: str, **kvs: Any) -> None:
        self._log(logging.DEBUG, msg, kvs)

    def info(self, msg: str, **kvs: Any) -> None:
        self._log(logging.INFO, msg, kvs)

    def warn(self, msg: str, **kvs: Any) -> None:
        self._log(logging.WARNING, msg, kvs)

    def error(self, msg: str, exc_info=None, **kvs: Any) -> None:
        self._log(logging.ERROR, msg, kvs, exc_info=exc_info)


def setup(verbosity: int = 0, stream=None) -> None:
    """Install the kv formatter on the kwok root (idempotent).
    -v mapping mirrors the reference's klog-style levels."""
    global _setup_done
    with _setup_mut:
        root = logging.getLogger("kwok")
        if not _setup_done:
            handler = logging.StreamHandler(stream or sys.stderr)
            handler.setFormatter(_KVFormatter())
            root.addHandler(handler)
            root.propagate = False
            _setup_done = True
        root.setLevel(logging.DEBUG if verbosity >= 1 else logging.INFO)


def get_logger(component: str) -> Logger:
    return Logger(logging.getLogger(f"kwok.{component}"))
