"""SPDY/3.1 client for the kubelet streaming endpoints — the test and
tooling counterpart of ``kwok_tpu.server.spdy`` (what client-go's
``spdy.RoundTripper`` + remotecommand do for kubectl ≤1.28; reference
serves it via debugging_exec.go:148-165).

``connect()`` performs the HTTP Upgrade handshake and returns the
framed session; the kubelet conventions are then one ``open_stream``
per channel with a ``streamType`` header (exec/attach) or
``data``/``error`` pairs keyed by ``port``/``requestID``
(port-forward).
"""

from __future__ import annotations

import socket
from typing import Dict, Optional, Tuple
from urllib.parse import urlsplit

from kwok_tpu.utils.spdyproto import SpdySession


class SpdyUpgradeError(ConnectionError):
    """The server did not complete the SPDY/3.1 upgrade."""


def connect(
    url: str,
    protocols: Tuple[str, ...] = ("v4.channel.k8s.io",),
    timeout: float = 10.0,
    method: str = "POST",
    headers: Optional[Dict[str, str]] = None,
) -> Tuple[SpdySession, str]:
    """Upgrade ``url`` (http://host:port/path?query) to an SPDY/3.1
    session; returns (session, negotiated_protocol)."""
    parts = urlsplit(url)
    host = parts.hostname or "127.0.0.1"
    port = parts.port or 80
    path = parts.path + (f"?{parts.query}" if parts.query else "")
    sock = socket.create_connection((host, port), timeout=timeout)
    req = [
        f"{method} {path} HTTP/1.1",
        f"Host: {host}:{port}",
        "Connection: Upgrade",
        "Upgrade: SPDY/3.1",
    ]
    if protocols:
        req.append(f"X-Stream-Protocol-Version: {', '.join(protocols)}")
    for k, v in (headers or {}).items():
        req.append(f"{k}: {v}")
    req.append("Content-Length: 0")
    sock.sendall(("\r\n".join(req) + "\r\n\r\n").encode())

    # read the 101 response head
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            sock.close()
            raise SpdyUpgradeError("connection closed during upgrade")
        buf += chunk
        if len(buf) > 65536:
            sock.close()
            raise SpdyUpgradeError("oversized upgrade response")
    head, rest = buf.split(b"\r\n\r\n", 1)
    lines = head.decode("latin-1").split("\r\n")
    status = lines[0].split(" ", 2)
    if len(status) < 2 or status[1] != "101":
        sock.close()
        raise SpdyUpgradeError(f"upgrade refused: {lines[0]}")
    resp_headers = {}
    for ln in lines[1:]:
        if ":" in ln:
            k, v = ln.split(":", 1)
            resp_headers[k.strip().lower()] = v.strip()
    chosen = resp_headers.get("x-stream-protocol-version", "")
    # the handshake timeout must not apply to the framed session: the
    # reader treats a socket timeout as connection death, and streams
    # legitimately sit silent (a command producing no output)
    sock.settimeout(None)
    session = SpdySession(sock, client=True)
    if rest:
        # frames that arrived glued to the 101: hand them to the reader
        # by replaying through a shim — in practice servers never write
        # before the client opens a stream, so reject loudly instead of
        # silently dropping bytes
        session.close()
        raise SpdyUpgradeError("unexpected data before first stream")
    return session, chosen
