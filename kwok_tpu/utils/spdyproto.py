"""SPDY/3.1 framing: the transport both halves of the kubelet
streaming stack share.

The session/stream machinery here is symmetric — the server side
(``kwok_tpu.server.spdy``) accepts upgrades and adapts streams for the
exec/attach handlers, the client side (``kwok_tpu.utils.spdyclient``)
initiates them — so the protocol lives at the utils layer, below both
(the layering rule in ``kwok_tpu.analysis.layering``; the reference
keeps the equivalent split between k8s.io/apimachinery httpstream/spdy
and moby/spdystream, wired in via debugging_exec.go:148-165 under
pkg/kwok/server/).  Implemented here:

- SPDY/3.1 control and data frames: SYN_STREAM / SYN_REPLY /
  RST_STREAM / SETTINGS / PING / GOAWAY / HEADERS / WINDOW_UPDATE,
- the SPDY/3 zlib header compression (per-direction persistent
  compressors with the draft-3 dictionary; each block ends with a
  SYNC flush),
- per-stream + per-session flow control (64 KiB initial windows,
  WINDOW_UPDATE credits), and
- stream plumbing: one stream per channel with a ``streamtype``
  header (error/stdin/stdout/stderr/resize — the kubelet
  remote-command convention) or data/error pairs keyed by
  ``port``/``requestid`` (port forward).

The header dictionary below is the SPDY draft-3 constant
(reconstructed from the spec, §2.6.10.1).  Both directions of this
implementation use it symmetrically; byte-exactness only governs
interop with foreign implementations (client-go), which cannot be
exercised in this environment (no kubectl binary, no egress).
"""

from __future__ import annotations

import struct
import threading
import zlib
from typing import Dict, List, Optional

# --------------------------------------------------------------- dictionary

_WORDS = [
    "options", "head", "post", "put", "delete", "trace", "accept",
    "accept-charset", "accept-encoding", "accept-language",
    "accept-ranges", "age", "allow", "authorization", "cache-control",
    "connection", "content-base", "content-encoding",
    "content-language", "content-length", "content-location",
    "content-md5", "content-range", "content-type", "date", "etag",
    "expect", "expires", "from", "host", "if-match",
    "if-modified-since", "if-none-match", "if-range",
    "if-unmodified-since", "last-modified", "location", "max-forwards",
    "pragma", "proxy-authenticate", "proxy-authorization", "range",
    "referer", "retry-after", "server", "te", "trailer",
    "transfer-encoding", "upgrade", "user-agent", "vary", "via",
    "warning", "www-authenticate", "method", "get", "status", "200 OK",
    "version", "HTTP/1.1", "url", "public", "set-cookie", "keep-alive",
    "origin",
]
_TAIL = (
    "100101201202205206300302303304305306307402405406407408409410411412"
    "413414415416417502504505"
    "203 Non-Authoritative Information204 No Content301 Moved Permanently"
    "400 Bad Request401 Unauthorized403 Forbidden404 Not Found"
    "500 Internal Server Error501 Not Implemented503 Service Unavailable"
    "Jan Feb Mar Apr May Jun Jul Aug Sept Oct Nov Dec"
    " 00:00:00"
    " Mon, Tue, Wed, Thu, Fri, Sat, Sun, GMT"
    "chunked,text/html,image/png,image/jpg,image/gif,"
    "application/xml,application/xhtml+xml,text/plain,text/javascript,"
    "publicprivatemax-age=gzip,deflate,sdchcharset=utf-8charset=iso-8859-1"
    ",utf-,*,enq=0.7,q=0.8,q=0.9,q=1.0,q=0.1,q=0.001,q=0.002,q=0.5,en-gb"
    "chunkedtext/htmlimage/pngimage/jpgimage/gifapplication/xml"
    "application/xhtml+xmltext/plaintext/javascriptpublicprivate"
    "max-age=gzip,deflate,sdchcharset=utf-8charset=iso-8859-1,utf-,*,en"
)
SPDY_DICT = (
    b"".join(struct.pack(">I", len(w)) + w.encode() for w in _WORDS)
    + _TAIL.encode()
    + b"\x00"
)

SPDY_VERSION = 3

# control frame types
SYN_STREAM = 1
SYN_REPLY = 2
RST_STREAM = 3
SETTINGS = 4
PING = 6
GOAWAY = 7
HEADERS = 8
WINDOW_UPDATE = 9

FLAG_FIN = 0x01

#: per-stream / per-session initial flow-control window (SPDY/3.1)
INITIAL_WINDOW = 64 * 1024

#: the remote-command sub-protocols answered for SPDY clients
#: (reference remotecommand supports v1-v4 over SPDY; v4 carries the
#: JSON Status error channel the server emits)
REMOTE_COMMAND_PROTOCOLS = ("v4.channel.k8s.io",)
PORT_FORWARD_PROTOCOLS = ("portforward.k8s.io",)


def _encode_headers(pairs: Dict[str, str], deflater) -> bytes:
    out = [struct.pack(">I", len(pairs))]
    for k, v in pairs.items():
        kb = k.lower().encode()
        vb = v.encode()
        out.append(struct.pack(">I", len(kb)) + kb)
        out.append(struct.pack(">I", len(vb)) + vb)
    raw = b"".join(out)
    return deflater.compress(raw) + deflater.flush(zlib.Z_SYNC_FLUSH)


def _decode_headers(block: bytes, inflater) -> Dict[str, str]:
    raw = inflater.decompress(block)
    n = struct.unpack_from(">I", raw, 0)[0]
    i = 4
    out: Dict[str, str] = {}
    for _ in range(n):
        klen = struct.unpack_from(">I", raw, i)[0]
        i += 4
        k = raw[i : i + klen].decode("latin-1")
        i += klen
        vlen = struct.unpack_from(">I", raw, i)[0]
        i += 4
        v = raw[i : i + vlen].decode("latin-1")
        i += vlen
        out[k.lower()] = v
    return out


class SpdyStream:
    """One SPDY stream: an inbound byte queue plus framed writes."""

    def __init__(self, session: "SpdySession", stream_id: int, headers: Dict[str, str]):
        self.session = session
        self.stream_id = stream_id
        self.headers = headers
        self._chunks: List[Optional[bytes]] = []
        self._cv = threading.Condition()
        self._closed_remote = False
        self._closed_local = False
        self._send_window = INITIAL_WINDOW

    @property
    def stream_type(self) -> str:
        return self.headers.get("streamtype", "")

    # called by the session reader
    def _feed(self, data: bytes, fin: bool) -> None:
        with self._cv:
            if data:
                self._chunks.append(data)
            if fin:
                self._closed_remote = True
            self._cv.notify_all()

    def _credit(self, delta: int) -> None:
        with self._cv:
            self._send_window += delta
            self._cv.notify_all()

    def read(self, timeout: Optional[float] = None) -> Optional[bytes]:
        """Next inbound chunk; None at remote FIN / session end."""
        with self._cv:
            while not self._chunks:
                if self._closed_remote or self.session.closed:
                    return None
                if not self._cv.wait(timeout):
                    raise TimeoutError("spdy stream read timeout")
            return self._chunks.pop(0)

    def write(self, data: bytes) -> bool:
        # respect the peer's flow-control window (64 KiB initial; the
        # peer credits us back with WINDOW_UPDATE as it consumes)
        view = memoryview(data)
        while view:
            with self._cv:
                while self._send_window <= 0:
                    if self._closed_local or self.session.closed:
                        return False
                    self._cv.wait(1.0)
                n = min(len(view), self._send_window, 1 << 20)
                self._send_window -= n
            if not self.session._send_data(self.stream_id, bytes(view[:n]), 0):
                return False
            view = view[n:]
        return True

    def close(self) -> None:
        """Half-close our side (FIN)."""
        if not self._closed_local:
            self._closed_local = True
            self.session._send_data(self.stream_id, b"", FLAG_FIN)
        self.session._maybe_reap(self)


class SpdySession:
    """One side of an SPDY/3.1 connection (server by default; pass
    ``client=True`` for odd client stream ids + open_stream)."""

    def __init__(self, sock, client: bool = False):
        self.sock = sock
        self.closed = False
        self._next_id = 1 if client else 2
        self._wlock = threading.Lock()
        self._deflate = zlib.compressobj(6, zlib.DEFLATED, 15, 8,
                                         zlib.Z_DEFAULT_STRATEGY, SPDY_DICT)
        self._inflate = zlib.decompressobj(zdict=SPDY_DICT)
        self.streams: Dict[int, SpdyStream] = {}
        self._accept_q: List[SpdyStream] = []
        self._cv = threading.Condition()
        self._recv_window = INITIAL_WINDOW
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    # ------------------------------------------------------------- send side

    def _send(self, frame: bytes) -> bool:
        with self._wlock:
            # _wlock is the socket write serializer: frames must hit
            # the wire whole and in order
            return self._send_locked(frame)  # kwoklint: disable=lock-discipline

    def _send_locked(self, frame: bytes) -> bool:
        """Write with ``_wlock`` already held.  Header-bearing frames
        MUST compress and send under one continuous hold: the deflate
        stream is stateful, so the order blocks pass through
        ``self._deflate`` must equal the order they hit the wire, or a
        concurrent ``open_stream``/``syn_reply`` desyncs the peer's
        shared inflater (ADVICE r5 #2)."""
        try:
            self.sock.sendall(frame)
            return True
        except OSError:
            self._mark_closed()
            return False

    def _control(self, ftype: int, flags: int, payload: bytes) -> bytes:
        head = struct.pack(
            ">HHBBH",
            0x8000 | SPDY_VERSION,
            ftype,
            flags,
            (len(payload) >> 16) & 0xFF,
            len(payload) & 0xFFFF,
        )
        return head + payload

    def _send_data(self, stream_id: int, data: bytes, flags: int) -> bool:
        head = struct.pack(
            ">IBBH",
            stream_id & 0x7FFFFFFF,
            flags,
            (len(data) >> 16) & 0xFF,
            len(data) & 0xFFFF,
        )
        return self._send(head + data)

    def syn_reply(self, stream_id: int, headers: Dict[str, str]) -> bool:
        with self._wlock:
            # sanctioned blocking-under-lock (see _send_locked): the
            # stateful deflater pins compress+send order to wire order
            block = _encode_headers(headers, self._deflate)
            payload = struct.pack(">I", stream_id & 0x7FFFFFFF) + block
            return self._send_locked(self._control(SYN_REPLY, 0, payload))  # kwoklint: disable=lock-discipline — stateful deflater pins compress+send to wire order

    def rst_stream(self, stream_id: int, status: int = 1) -> bool:
        payload = struct.pack(">II", stream_id & 0x7FFFFFFF, status)
        return self._send(self._control(RST_STREAM, 0, payload))

    def _window_update(self, stream_id: int, delta: int) -> None:
        payload = struct.pack(">II", stream_id & 0x7FFFFFFF, delta)
        self._send(self._control(WINDOW_UPDATE, 0, payload))

    def goaway(self) -> None:
        self._send(self._control(GOAWAY, 0, struct.pack(">II", 0, 0)))

    def open_stream(
        self, headers: Dict[str, str], fin: bool = False
    ) -> SpdyStream:
        """Initiate a stream (SYN_STREAM) — the client side of the
        kubelet streaming protocols (one stream per channel)."""
        with self._cv:
            sid = self._next_id
            self._next_id += 2
        stream = SpdyStream(self, sid, {k.lower(): v for k, v in headers.items()})
        self.streams[sid] = stream
        with self._wlock:
            # sanctioned blocking-under-lock (see _send_locked): the
            # stateful deflater pins compress+send order to wire order
            block = _encode_headers(headers, self._deflate)
            payload = (
                struct.pack(">II", sid & 0x7FFFFFFF, 0) + b"\x00\x00" + block
            )
            self._send_locked(  # kwoklint: disable=lock-discipline — stateful deflater pins compress+send to wire order
                self._control(SYN_STREAM, FLAG_FIN if fin else 0, payload)
            )
        return stream

    # ------------------------------------------------------------- recv side

    def _read_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _read_loop(self) -> None:
        while not self.closed:
            head = self._read_exact(8)
            if head is None:
                break
            first, = struct.unpack_from(">I", head, 0)
            flags = head[4]
            length = (head[5] << 16) | (head[6] << 8) | head[7]
            payload = self._read_exact(length) if length else b""
            if payload is None:
                break
            if first & 0x80000000:  # control frame
                ftype = first & 0xFFFF
                self._on_control(ftype, flags, payload)
            else:
                self._on_data(first & 0x7FFFFFFF, flags, payload)
        self._mark_closed()

    def _on_control(self, ftype: int, flags: int, payload: bytes) -> None:
        if ftype == SYN_STREAM:
            stream_id = struct.unpack_from(">I", payload, 0)[0] & 0x7FFFFFFF
            headers = _decode_headers(payload[10:], self._inflate)
            stream = SpdyStream(self, stream_id, headers)
            with self._cv:
                self.streams[stream_id] = stream
                self._accept_q.append(stream)
                self._cv.notify_all()
            self.syn_reply(stream_id, {})
            if flags & FLAG_FIN:
                stream._feed(b"", fin=True)
        elif ftype == PING:
            # echo every ping (the spec echoes only peer-initiated ids;
            # a server never pings here, so everything is peer-initiated)
            self._send(self._control(PING, 0, payload))
        elif ftype == WINDOW_UPDATE:
            stream_id, delta = struct.unpack_from(">II", payload, 0)
            stream_id &= 0x7FFFFFFF
            delta &= 0x7FFFFFFF
            if stream_id:
                st = self.streams.get(stream_id)
                if st is not None:
                    st._credit(delta)
        elif ftype == SYN_REPLY:
            pass  # our SYN_STREAM acknowledged; headers carry nothing we use
        elif ftype == RST_STREAM:
            stream_id = struct.unpack_from(">I", payload, 0)[0] & 0x7FFFFFFF
            st = self.streams.pop(stream_id, None)
            if st is not None:
                st._feed(b"", fin=True)
        elif ftype == GOAWAY:
            self._mark_closed()
        # SETTINGS / HEADERS: accepted and ignored (no server behavior
        # depends on them for the kubelet streaming protocols)

    def _maybe_reap(self, st: SpdyStream) -> None:
        """Forget a stream once both sides closed — a port-forward
        session held open for hours must not accumulate per-connection
        stream objects."""
        if st._closed_local and st._closed_remote:
            self.streams.pop(st.stream_id, None)

    def _on_data(self, stream_id: int, flags: int, data: bytes) -> None:
        st = self.streams.get(stream_id)
        if st is None:
            self.rst_stream(stream_id, 2)  # INVALID_STREAM
            return
        st._feed(data, fin=bool(flags & FLAG_FIN))
        if flags & FLAG_FIN:
            self._maybe_reap(st)
        if data:
            # credit the peer back immediately: stream + session windows
            # (SPDY/3.1 session-level flow control rides stream id 0)
            self._window_update(stream_id, len(data))
            self._window_update(0, len(data))

    # -------------------------------------------------------------- accept

    def accept_stream(self, timeout: Optional[float] = None) -> Optional[SpdyStream]:
        """Next client-opened stream (None on session close/timeout)."""
        with self._cv:
            while not self._accept_q:
                if self.closed:
                    return None
                if not self._cv.wait(timeout):
                    return None
            return self._accept_q.pop(0)

    def _mark_closed(self) -> None:
        if self.closed:
            return
        self.closed = True
        with self._cv:
            self._cv.notify_all()
        for st in list(self.streams.values()):
            st._feed(b"", fin=True)

    def close(self) -> None:
        if not self.closed:
            self.goaway()
        self._mark_closed()
        try:
            self.sock.close()
        except OSError:
            pass
