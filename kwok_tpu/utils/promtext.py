"""Prometheus text-format parsing, shared by every scrape consumer
(`kwokctl kubectl top` and the metrics.k8s.io facade both read the
kubelet's resource-metrics endpoint, whose values the reference
computes in pkg/kwok/server/metrics_resource_usage.go:36-264; one
parser keeps them from drifting).  Handles quoted label values containing commas and escaped
quotes, which naive ``split(",")`` parsers mis-split."""

from __future__ import annotations

import re
from typing import Dict, Iterator, Tuple

__all__ = ["iter_samples"]

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_ESCAPES = {"\\": "\\", '"': '"', "n": "\n"}


def _unescape(value: str) -> str:
    """Left-to-right escape scan — sequential whole-string replaces
    would corrupt an escaped backslash followed by 'n' into a
    newline."""
    if "\\" not in value:
        return value
    out = []
    i = 0
    n = len(value)
    while i < n:
        c = value[i]
        if c == "\\" and i + 1 < n:
            nxt = value[i + 1]
            out.append(_ESCAPES.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def iter_samples(text: str) -> Iterator[Tuple[str, Dict[str, str], float]]:
    """Yield (metric_name, labels, value) for each sample line."""
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        series, _, val = line.rpartition(" ")
        if not series:
            continue
        try:
            fval = float(val)
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        name = series
        if "{" in series:
            name, _, lbl = series.partition("{")
            labels = {k: _unescape(v) for k, v in _LABEL_RE.findall(lbl)}
        yield name.strip(), labels, fval
