"""Kubernetes WebSocket streaming vocabulary, shared by both halves.

One source of truth for the channel bytes, sub-protocol names and
handshake key derivation of the kubelet streaming protocols — the
server (``kwok_tpu.server.websocket``) and the client
(``kwok_tpu.utils.wsclient``) both import from here, so the
vocabulary cannot drift between them and the client stays below the
server in the layer map.  The conventions mirror what
k8s.io/apiserver's upgrade-aware handlers negotiate (reference
pkg/kwok/server/debugging.go:36-102):

- remote command (``v4.channel.k8s.io``/``v5.channel.k8s.io``):
  binary frames whose first byte selects the stream — 0 stdin,
  1 stdout, 2 stderr, 3 an error/status JSON trailer, 4 terminal
  resize;
- port forward (``portforward.k8s.io``/``v2.portforward.k8s.io``):
  two channels per requested port (2i data, 2i+1 error), each
  opening with a little-endian uint16 port frame.
"""

from __future__ import annotations

import base64
import hashlib

#: RFC 6455 §1.3 magic GUID for Sec-WebSocket-Accept
_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

#: newest first — the server picks the first supported protocol the
#: client offered, like k8s.io/apiserver's negotiation
REMOTE_COMMAND_PROTOCOLS = ["v5.channel.k8s.io", "v4.channel.k8s.io"]
PORT_FORWARD_PROTOCOLS = ["v2.portforward.k8s.io", "portforward.k8s.io"]

CHAN_STDIN = 0
CHAN_STDOUT = 1
CHAN_STDERR = 2
CHAN_ERROR = 3
CHAN_RESIZE = 4

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def _accept_key(key: str) -> str:
    digest = hashlib.sha1((key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()
