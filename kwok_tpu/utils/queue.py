"""Thread-safe FIFO / weighted / delaying queues.

Semantics mirror the reference scheduling structures
(reference: pkg/utils/queue/{queue,weight_queue,delaying_queue,
weight_delaying_queue}.go):

- ``Queue``: FIFO with blocking get (queue.go:25-113).
- ``WeightQueue``: weight 0 is the main (highest-priority) queue;
  weights 1..n live in buckets that are drained into the main queue on
  demand, ``weight`` items per step, highest numeric weight first
  (weight_queue.go:84-110).
- ``DelayingQueue``: heap of (deadline, item) + a timer worker that
  promotes due items (delaying_queue.go:59-125).
- ``WeightDelayingQueue`` — the controllers' scheduling structure:
  ``add_weight_after(item, weight, delay)``; due items promote into
  the weight buckets; ``cancel`` removes not-yet-due items
  (weight_delaying_queue.go:29-163).

These back the *host* (slow/fallback) stage path and the lease
controller; the device path replaces them with the fire_at column in
the tick kernel (SURVEY.md §2.9).
"""

from __future__ import annotations

import heapq
import itertools
import threading
from collections import deque
from typing import Dict, Generic, List, Optional, Tuple, TypeVar

from kwok_tpu.utils.clock import Clock, RealClock

T = TypeVar("T")


class Queue(Generic[T]):
    """FIFO queue with blocking get."""

    def __init__(self):
        self._items: deque = deque()
        self._mut = threading.Lock()
        self._signal = threading.Event()

    def add(self, item: T) -> None:
        with self._mut:
            self._items.append(item)
        self._signal.set()

    def extend(self, items) -> None:
        """Enqueue many items with one lock hold and one wakeup (the
        informer's batched event delivery)."""
        if not items:
            return
        with self._mut:
            self._items.extend(items)
        self._signal.set()

    def get(self) -> Tuple[Optional[T], bool]:
        with self._mut:
            if self._items:
                return self._items.popleft(), True
        return None, False

    def drain(self) -> List[T]:
        """Pop everything queued under one lock hold."""
        with self._mut:
            if not self._items:
                return []
            items = list(self._items)
            self._items.clear()
        return items

    def remove(self, item: T) -> bool:
        """Remove a not-yet-consumed item from the FIFO."""
        with self._mut:
            try:
                self._items.remove(item)
                return True
            except ValueError:
                return False

    def get_or_wait(self, timeout: Optional[float] = None, done: Optional[threading.Event] = None) -> Tuple[Optional[T], bool]:
        """Block until an item is available, ``done`` is set, or timeout."""
        while True:
            item, ok = self.get()
            if ok:
                return item, True
            if done is not None and done.is_set():
                return None, False
            self._signal.clear()
            # re-check after clear to avoid a lost wakeup
            item, ok = self.get()
            if ok:
                return item, True
            if not self._signal.wait(timeout if timeout is not None else 0.5):
                if timeout is not None:
                    return None, False

    def __len__(self) -> int:
        with self._mut:
            return len(self._items)


class WeightQueue(Queue[T]):
    """Weight-bucketed queue (weight_queue.go).

    Weight 0 goes straight to the main FIFO (highest priority); weights
    1..n are drained ``weight`` items at a time, highest weight first.
    """

    def __init__(self):
        super().__init__()
        self._buckets: Dict[int, deque] = {}

    def add_weight(self, item: T, weight: int) -> None:
        if weight <= 0:
            self.add(item)
            return
        with self._mut:
            self._buckets.setdefault(weight, deque()).append(item)
        self._signal.set()

    def remove(self, item: T) -> bool:
        """Remove from the main FIFO or any weight bucket."""
        with self._mut:
            try:
                self._items.remove(item)
                return True
            except ValueError:
                pass
            for bucket in self._buckets.values():
                try:
                    bucket.remove(item)
                    return True
                except ValueError:
                    continue
        return False

    def _step(self) -> bool:
        """Drain buckets into the main queue; returns True if anything moved."""
        added = False
        for weight in sorted(self._buckets, reverse=True):
            bucket = self._buckets[weight]
            for _ in range(weight):
                if not bucket:
                    break
                self._items.append(bucket.popleft())
                added = True
        return added

    def get(self) -> Tuple[Optional[T], bool]:
        with self._mut:
            if self._items:
                return self._items.popleft(), True
            if self._step():
                return self._items.popleft(), True
        return None, False

    def __len__(self) -> int:
        with self._mut:
            return len(self._items) + sum(len(b) for b in self._buckets.values())


class _Heap(Generic[T]):
    """Deadline heap keyed by (deadline, insertion-seq). ``remove`` is an
    O(n) scan + heapify — cancels are rare (reference heap.Heap pays the
    same), the hot path is push/peek/pop."""

    def __init__(self):
        self._heap: List[Tuple[float, int, T]] = []

    def push(self, deadline: float, item: T) -> None:
        heapq.heappush(self._heap, (deadline, next(_seq), item))

    def peek(self) -> Tuple[float, Optional[T], bool]:
        if not self._heap:
            return 0.0, None, False
        deadline, _, item = self._heap[0]
        return deadline, item, True

    def pop(self) -> Tuple[float, Optional[T], bool]:
        if not self._heap:
            return 0.0, None, False
        deadline, _, item = heapq.heappop(self._heap)
        return deadline, item, True

    def remove(self, item: T) -> bool:
        for i, (_, _, it) in enumerate(self._heap):
            if it == item:
                self._heap[i] = self._heap[-1]
                self._heap.pop()
                heapq.heapify(self._heap)
                return True
        return False

    def __len__(self) -> int:
        return len(self._heap)


_seq = itertools.count()


class DelayingQueue(Queue[T]):
    """FIFO + add_after(item, delay_seconds) via a timer worker."""

    def __init__(self, clock: Optional[Clock] = None):
        super().__init__()
        self._clock = clock or RealClock()
        self._heap: _Heap[T] = _Heap()
        self._hmut = threading.Lock()
        self._hsignal = threading.Event()
        self._clock.subscribe(self._hsignal)
        self._stopped = False
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def add_after(self, item: T, delay: float) -> None:
        if delay <= 0:
            self._promote(item, 0)
            return
        deadline = self._clock.now() + delay
        with self._hmut:
            self._heap.push(deadline, item)
        self._hsignal.set()

    def cancel(self, item: T) -> bool:
        """Remove an item whether still delayed or already promoted."""
        with self._hmut:
            removed = self._heap.remove(item)
        return self.remove(item) or removed

    def _promote(self, item: T, weight: int) -> None:
        self.add(item)

    def _next(self) -> Tuple[Optional[T], int, bool, Optional[float]]:
        now = self._clock.now()
        with self._hmut:
            deadline, item, ok = self._heap.peek()
            if not ok:
                return None, 0, False, None
            if deadline <= now:
                self._heap.pop()
                return item, 0, True, None
            return None, 0, False, deadline - now

    def _loop(self) -> None:
        while not self._stopped:
            item, weight, ok, wait = self._next()
            if ok:
                self._promote(item, weight)
                continue
            delay = 10.0 if wait is None else min(wait, 10.0)
            self._clock.wait_signal(self._hsignal, delay)
            self._hsignal.clear()

    def stop(self) -> None:
        self._stopped = True
        self._hsignal.set()


def new_weight_delaying_queue(clock: Optional[Clock] = None) -> "WeightDelayingQueue":
    """Preferred constructor: the C++-backed queue when the native
    library is available (KWOK_TPU_NATIVE=0 forces pure Python), else
    the pure-Python implementation. Both present the same surface."""
    import os

    if os.environ.get("KWOK_TPU_NATIVE", "1") != "0":
        try:
            from kwok_tpu.native.queue import (
                NativeWeightDelayingQueue,
                native_available,
            )

            if native_available():
                return NativeWeightDelayingQueue(clock)  # type: ignore[return-value]
        except Exception:  # noqa: BLE001 — toolchain missing: fall back
            pass
    return WeightDelayingQueue(clock)


class WeightDelayingQueue(WeightQueue[T]):
    """add_weight_after: the controllers' retry/delay scheduler.

    Items become due on their deadline and enter the weight bucket they
    were scheduled with (weight 0 = fresh work, served before retries at
    weight 1 — reference pod_controller.go:660-671).

    Not built on DelayingQueue: the WeightQueue/DelayingQueue diamond
    would let cooperative ``super().__init__`` start the timer worker
    before this class's state exists. Owns its own heaps + worker.
    """

    def __init__(self, clock: Optional[Clock] = None):
        super().__init__()
        self._clock = clock or RealClock()
        self._heap: _Heap[T] = _Heap()
        self._wheaps: Dict[int, _Heap[T]] = {}
        self._hmut = threading.Lock()
        self._hsignal = threading.Event()
        self._clock.subscribe(self._hsignal)
        self._stopped = False
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _loop(self) -> None:
        while not self._stopped:
            item, weight, ok, wait = self._next()
            if ok:
                self.add_weight(item, weight)
                continue
            delay = 10.0 if wait is None else min(wait, 10.0)
            self._clock.wait_signal(self._hsignal, delay)
            self._hsignal.clear()

    def stop(self) -> None:
        self._stopped = True
        self._hsignal.set()

    def add_weight_after(self, item: T, weight: int, delay: float) -> None:
        if delay <= 0:
            self.add_weight(item, weight)
            return
        deadline = self._clock.now() + delay
        with self._hmut:
            if weight <= 0:
                self._heap.push(deadline, item)
            else:
                self._wheaps.setdefault(weight, _Heap()).push(deadline, item)
        self._hsignal.set()

    def add_after(self, item: T, delay: float) -> None:
        self.add_weight_after(item, 0, delay)

    def cancel(self, item: T) -> bool:
        """Remove an item whether still delayed or already promoted."""
        with self._hmut:
            removed = self._heap.remove(item)
            for h in self._wheaps.values():
                if h.remove(item):
                    removed = True
        return self.remove(item) or removed

    def _next(self) -> Tuple[Optional[T], int, bool, Optional[float]]:
        now = self._clock.now()
        wait: Optional[float] = None
        with self._hmut:
            deadline, item, ok = self._heap.peek()
            if ok:
                if deadline <= now:
                    self._heap.pop()
                    return item, 0, True, None
                wait = deadline - now
            for weight in sorted(self._wheaps, reverse=True):
                h = self._wheaps[weight]
                deadline, item, ok = h.peek()
                if not ok:
                    continue
                if deadline <= now:
                    h.pop()
                    return item, weight, True, None
                if wait is None or deadline - now < wait:
                    wait = deadline - now
        return None, 0, False, wait
