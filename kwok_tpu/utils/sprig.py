"""Sprig-at-large template functions for the gotpl engine.

The reference template env pulls in the whole sprig library
(reference pkg/utils/gotpl/funcs.go:42-117 ``sprig.TxtFuncMap()``), so
wild user stages may call any of it.  This module implements the sprig
v3 surface stages realistically use — strings, math, lists, dicts,
encodings, regex, dates, type/kind introspection, paths, semver —
with sprig's exact argument orders (collection/subject LAST, so
pipelines read naturally: ``{{ .v | b64enc }}``,
``{{ trimPrefix "p-" .name }}``).

Known divergences (documented, small): ``must*`` variants alias their
plain forms (the engine already surfaces errors), the crypto subset is
the checksum trio, and network/OS escape hatches (``getHostByName``)
return zero values instead of doing I/O.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import json
import math
import os
import posixpath
import random
import re
import uuid
from typing import Any, Callable, Dict, List

import yaml

#: randomness seam for the rand*/shuffle template funcs: an explicit
#: instance (never the module-global ``random`` state) so seeded runs
#: — chaos plans, the DST harness (kwok_tpu.dst) — fully determine
#: template randomness.  Default is an unseeded instance, matching
#: sprig's process-global behavior for ordinary use.
_RNG = random.Random()


def set_default_rng(rng: random.Random) -> "random.Random":
    """Seed the template-function randomness (one rng per process; the
    DST harness calls this per simulation run).  Returns the previous
    rng so a scoped caller can restore it afterwards."""
    global _RNG
    prev, _RNG = _RNG, rng
    return prev


# ---------------------------------------------------------------- helpers


def _to_str(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)


def _to_int(v: Any) -> int:
    if v is None or v == "":
        return 0
    if isinstance(v, bool):
        return int(v)
    try:
        return int(v)
    except (TypeError, ValueError):
        try:
            return int(float(v))
        except (TypeError, ValueError):
            return 0


def _to_float(v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def _is_empty(v: Any) -> bool:
    if v is None or v is False:
        return True
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v == 0
    if isinstance(v, (str, list, dict, tuple)):
        return len(v) == 0
    return False


_GO_LAYOUT = [
    # longest tokens first: Go reference time -> strftime
    ("2006", "%Y"), ("01", "%m"), ("02", "%d"), ("15", "%H"),
    ("04", "%M"), ("05", "%S"), ("Jan", "%b"), ("January", "%B"),
    ("Mon", "%a"), ("Monday", "%A"), ("Z07:00", "%:z"), ("-07:00", "%:z"),
    ("Z0700", "%z"), ("-0700", "%z"), ("PM", "%p"), ("pm", "%p"),
    ("03", "%I"), ("06", "%y"),
]


def _go_layout_to_strftime(layout: str) -> str:
    out = layout
    for go, st in sorted(_GO_LAYOUT, key=lambda p: -len(p[0])):
        out = out.replace(go, st)
    return out


def _as_datetime(t: Any) -> datetime.datetime:
    if isinstance(t, datetime.datetime):
        return t
    if isinstance(t, (int, float)) and not isinstance(t, bool):
        return datetime.datetime.fromtimestamp(t, datetime.timezone.utc)
    if isinstance(t, str):
        s = t.replace("Z", "+00:00")
        try:
            return datetime.datetime.fromisoformat(s)
        except ValueError:
            pass
        raise ValueError(f"cannot parse time {t!r}")
    raise ValueError(f"cannot interpret {type(t).__name__} as a time")


def _fmt_date(layout: str, t: Any) -> str:
    st = _go_layout_to_strftime(layout)
    dt = _as_datetime(t)
    out = dt.strftime(st.replace("%:z", "%z"))
    if "%:z" in st:  # Go's Z07:00 / colon zone form
        z = dt.strftime("%z") or "+0000"
        colon = f"{z[:3]}:{z[3:]}"
        out = dt.strftime(st.replace("%:z", "\x00")).replace(
            "\x00", "Z" if z in ("+0000", "") else colon
        )
    return out


_SEMVER_RE = re.compile(
    r"^v?(\d+)\.(\d+)\.(\d+)(?:-([0-9A-Za-z.-]+))?(?:\+[0-9A-Za-z.-]+)?$"
)


def _semver_tuple(v: str):
    m = _SEMVER_RE.match(v.strip())
    if not m:
        raise ValueError(f"invalid semver {v!r}")
    return (int(m.group(1)), int(m.group(2)), int(m.group(3)), m.group(4))


def _semver_cmp(a: str, b: str) -> int:
    ta, tb = _semver_tuple(a), _semver_tuple(b)
    if ta[:3] != tb[:3]:
        return -1 if ta[:3] < tb[:3] else 1
    # a pre-release sorts before the release
    pa, pb = ta[3], tb[3]
    if pa == pb:
        return 0
    if pa is None:
        return 1
    if pb is None:
        return -1
    return -1 if pa < pb else 1


def _semver_compare(constraint: str, version: str) -> bool:
    constraint = constraint.strip()
    for part in constraint.split(","):
        part = part.strip()
        if not part:
            continue
        if part in ("*", "x", "X"):
            _semver_tuple(version)  # still validates the version
            continue
        m = re.match(r"^(>=|<=|!=|>|<|=|\^|~)?\s*(.+)$", part)
        op, ref = m.group(1) or "=", m.group(2)
        # wildcard ranges: 1.x / 1.2.x act like ~ on the fixed prefix
        wild = re.fullmatch(r"v?(\d+)(?:\.(\d+))?\.[xX*]", ref)
        if wild:
            vt = _semver_tuple(version)
            if int(wild.group(1)) != vt[0]:
                return False
            if wild.group(2) is not None and int(wild.group(2)) != vt[1]:
                return False
            continue
        c = _semver_cmp(version, ref)  # invalid syntax raises (sprig
        # surfaces constraint errors rather than silently failing)
        if op == "!=":
            if c == 0:
                return False
            continue
        if op == "=" and c != 0:
            return False
        if op == ">" and c <= 0:
            return False
        if op == "<" and c >= 0:
            return False
        if op == ">=" and c < 0:
            return False
        if op == "<=" and c > 0:
            return False
        if op == "^":  # same major, >= ref
            if c < 0 or _semver_tuple(version)[0] != _semver_tuple(ref)[0]:
                return False
        if op == "~":  # same major.minor, >= ref
            if c < 0 or _semver_tuple(version)[:2] != _semver_tuple(ref)[:2]:
                return False
    return True


def _kind_of(v: Any) -> str:
    if v is None:
        return "invalid"
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, float):
        return "float64"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (list, tuple)):
        return "slice"
    if isinstance(v, dict):
        return "map"
    return type(v).__name__


def _deep_merge(dst: dict, src: dict) -> dict:
    for k, v in src.items():
        if k in dst and isinstance(dst[k], dict) and isinstance(v, dict):
            _deep_merge(dst[k], v)
        elif k not in dst:  # sprig merge: dst wins on conflicts
            dst[k] = v
    return dst


def _words(s: str) -> List[str]:
    s = re.sub(r"([a-z0-9])([A-Z])", r"\1 \2", s)
    return [w for w in re.split(r"[^A-Za-z0-9]+", s) if w]


def _regex_split(pattern: str, s: str, n: int = -1) -> List[str]:
    parts = re.split(pattern, s)
    if n >= 0:
        # Go's Split(n): at most n substrings (remainder unsplit)
        out = []
        rest = s
        for _ in range(n - 1):
            m = re.search(pattern, rest)
            if not m:
                break
            out.append(rest[: m.start()])
            rest = rest[m.end() :]
        out.append(rest)
        return out
    return parts


# ----------------------------------------------------------------- table


def sprig_funcs() -> Dict[str, Callable]:
    """The function table, merged under the engine's own builtins."""
    funcs: Dict[str, Callable] = {
        # strings -------------------------------------------------------
        "upper": lambda s: _to_str(s).upper(),
        "lower": lambda s: _to_str(s).lower(),
        "title": lambda s: _to_str(s).title(),
        "untitle": lambda s: _to_str(s)[:1].lower() + _to_str(s)[1:],
        "trim": lambda s: _to_str(s).strip(),
        "trimAll": lambda cut, s: _to_str(s).strip(cut),
        "trimPrefix": lambda p, s: _to_str(s).removeprefix(p),
        "trimSuffix": lambda p, s: _to_str(s).removesuffix(p),
        "repeat": lambda n, s: _to_str(s) * _to_int(n),
        "substr": lambda a, b, s: _to_str(s)[
            _to_int(a) : (len(_to_str(s)) if _to_int(b) < 0 else _to_int(b))
        ],
        "trunc": lambda n, s: (
            _to_str(s)[: _to_int(n)]
            if _to_int(n) >= 0
            else _to_str(s)[_to_int(n) :]
        ),
        "abbrev": lambda n, s: (
            _to_str(s)
            if len(_to_str(s)) <= _to_int(n)
            else _to_str(s)[: max(_to_int(n) - 3, 0)] + "..."
        ),
        "initials": lambda s: "".join(w[0] for w in _to_str(s).split()),
        "contains": lambda sub, s: sub in _to_str(s),
        "hasPrefix": lambda p, s: _to_str(s).startswith(p),
        "hasSuffix": lambda p, s: _to_str(s).endswith(p),
        "replace": lambda old, new, s: _to_str(s).replace(old, new),
        "snakecase": lambda s: "_".join(w.lower() for w in _words(_to_str(s))),
        "kebabcase": lambda s: "-".join(w.lower() for w in _words(_to_str(s))),
        "camelcase": lambda s: "".join(
            w.capitalize() for w in _words(_to_str(s))
        ),
        "nospace": lambda s: re.sub(r"\s", "", _to_str(s)),
        "swapcase": lambda s: _to_str(s).swapcase(),
        "shuffle": lambda s: "".join(
            _RNG.sample(_to_str(s), len(_to_str(s)))
        ),
        "wrap": lambda n, s: "\n".join(
            _to_str(s)[i : i + _to_int(n)]
            for i in range(0, len(_to_str(s)), max(_to_int(n), 1))
        ),
        "cat": lambda *a: " ".join(_to_str(x) for x in a),
        "indent": lambda n, s: "\n".join(
            " " * _to_int(n) + line for line in _to_str(s).split("\n")
        ),
        "nindent": lambda n, s: "\n" + "\n".join(
            " " * _to_int(n) + line for line in _to_str(s).split("\n")
        ),
        "squote": lambda *a: " ".join(f"'{_to_str(x)}'" for x in a),
        "quote": lambda *a: " ".join(json.dumps(_to_str(x)) for x in a),
        "splitList": lambda sep, s: _to_str(s).split(sep),
        "split": lambda sep, s: {
            f"_{i}": part for i, part in enumerate(_to_str(s).split(sep))
        },
        "splitn": lambda sep, n, s: {
            f"_{i}": part
            for i, part in enumerate(_to_str(s).split(sep, _to_int(n) - 1))
        },
        "join": lambda sep, l: sep.join(
            _to_str(x) for x in (l if isinstance(l, (list, tuple)) else [l])
        ),
        "sortAlpha": lambda l: sorted(_to_str(x) for x in l),
        "toString": _to_str,
        "toStrings": lambda l: [_to_str(x) for x in l],
        "randAlphaNum": lambda n: "".join(
            _RNG.choices(
                "0123456789abcdefghijklmnopqrstuvwxyz"
                "ABCDEFGHIJKLMNOPQRSTUVWXYZ",
                k=_to_int(n),
            )
        ),
        "randAlpha": lambda n: "".join(
            _RNG.choices(
                "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ",
                k=_to_int(n),
            )
        ),
        "randNumeric": lambda n: "".join(
            _RNG.choices("0123456789", k=_to_int(n))
        ),
        # math ----------------------------------------------------------
        "add": lambda *a: sum(_to_int(x) for x in a),
        "add1": lambda v: _to_int(v) + 1,
        "sub": lambda a, b: _to_int(a) - _to_int(b),
        "mul": lambda *a: math.prod(_to_int(x) for x in a),
        # Go integer semantics: truncate toward zero, remainder takes
        # the dividend's sign (Python's // floors instead)
        "div": lambda a, b: int(_to_int(a) / _to_int(b)),
        "mod": lambda a, b: _to_int(a) - int(_to_int(a) / _to_int(b)) * _to_int(b),
        "max": lambda *a: max(_to_int(x) for x in a),
        "min": lambda *a: min(_to_int(x) for x in a),
        "addf": lambda *a: sum(_to_float(x) for x in a),
        "subf": lambda a, b: _to_float(a) - _to_float(b),
        "mulf": lambda *a: math.prod(_to_float(x) for x in a),
        "divf": lambda a, b: _to_float(a) / _to_float(b),
        "maxf": lambda *a: max(_to_float(x) for x in a),
        "minf": lambda *a: min(_to_float(x) for x in a),
        "floor": lambda v: float(math.floor(_to_float(v))),
        "ceil": lambda v: float(math.ceil(_to_float(v))),
        "round": lambda v, p=0: round(_to_float(v), _to_int(p)),
        "seq": lambda *a: " ".join(str(i) for i in _seq_range(*a)),
        "until": lambda n: list(range(_to_int(n))),
        "untilStep": lambda start, stop, step: list(
            range(_to_int(start), _to_int(stop), _to_int(step) or 1)
        ),
        "atoi": _to_int,
        "int": _to_int,
        "int64": _to_int,
        "float64": _to_float,
        "toDecimal": lambda v: int(_to_str(v), 8),
        # lists ---------------------------------------------------------
        "list": lambda *a: list(a),
        "tuple": lambda *a: list(a),
        "first": lambda l: l[0] if l else None,
        "rest": lambda l: list(l[1:]),
        "last": lambda l: l[-1] if l else None,
        "initial": lambda l: list(l[:-1]),
        "append": lambda l, v: list(l or []) + [v],
        "prepend": lambda l, v: [v] + list(l or []),
        "concat": lambda *ls: [x for l in ls for x in (l or [])],
        "reverse": lambda l: list(reversed(l)),
        "uniq": _uniq,
        "without": lambda l, *vs: [x for x in l if x not in vs],
        "has": lambda v, l: v in (l or []),
        "compact": lambda l: [x for x in l if not _is_empty(x)],
        "slice": lambda l, *ab: list(
            l[_to_int(ab[0]) if ab else 0 : _to_int(ab[1]) if len(ab) > 1 else len(l)]
        ),
        "chunk": lambda n, l: [
            list(l[i : i + _to_int(n)]) for i in range(0, len(l), max(_to_int(n), 1))
        ],
        # dicts ---------------------------------------------------------
        "get": lambda d, k: (d or {}).get(k, ""),
        "set": _dict_set,
        "unset": _dict_unset,
        "hasKey": lambda d, k: k in (d or {}),
        "keys": lambda *ds: [k for d in ds for k in (d or {})],
        "values": lambda *ds: [v for d in ds for v in (d or {}).values()],
        "pluck": lambda k, *ds: [d[k] for d in ds if isinstance(d, dict) and k in d],
        "pick": lambda d, *ks: {k: d[k] for k in ks if k in (d or {})},
        "omit": lambda d, *ks: {k: v for k, v in (d or {}).items() if k not in ks},
        "merge": lambda dst, *srcs: _merge_all(dst, srcs),
        "mergeOverwrite": lambda dst, *srcs: _merge_overwrite(dst, srcs),
        "deepCopy": lambda v: json.loads(json.dumps(v)),
        "dig": _dig,
        # encodings -----------------------------------------------------
        "b64enc": lambda s: base64.b64encode(_to_str(s).encode()).decode(),
        "b64dec": lambda s: base64.b64decode(_to_str(s).encode()).decode(),
        "b32enc": lambda s: base64.b32encode(_to_str(s).encode()).decode(),
        "b32dec": lambda s: base64.b32decode(_to_str(s).encode()).decode(),
        "toJson": lambda v: json.dumps(v, separators=(",", ":")),
        "toRawJson": lambda v: json.dumps(v, separators=(",", ":")),
        "toPrettyJson": lambda v: json.dumps(v, indent=2),
        "fromJson": lambda s: json.loads(s),
        "toYaml": lambda v: yaml.safe_dump(v, default_flow_style=False).rstrip("\n"),
        "fromYaml": lambda s: yaml.safe_load(s),
        "sha256sum": lambda s: hashlib.sha256(_to_str(s).encode()).hexdigest(),
        "sha1sum": lambda s: hashlib.sha1(_to_str(s).encode()).hexdigest(),
        "md5sum": lambda s: hashlib.md5(_to_str(s).encode()).hexdigest(),
        "uuidv4": lambda: str(uuid.uuid4()),
        # flow / defaults ----------------------------------------------
        "empty": _is_empty,
        "coalesce": lambda *a: next((x for x in a if not _is_empty(x)), None),
        "ternary": lambda t, f, cond: t if cond else f,
        "fail": _fail,
        # regex ---------------------------------------------------------
        "regexMatch": lambda pat, s: re.search(pat, _to_str(s)) is not None,
        "regexFind": lambda pat, s: (
            (re.search(pat, _to_str(s)) or _EMPTY_MATCH).group(0)
        ),
        "regexFindAll": lambda pat, s, n: (
            [m.group(0) for m in re.finditer(pat, _to_str(s))][
                : None if _to_int(n) < 0 else _to_int(n)
            ]
        ),
        "regexReplaceAll": lambda pat, s, repl: re.sub(
            pat, _go_repl(repl), _to_str(s)
        ),
        "regexSplit": lambda pat, s, n: _regex_split(pat, _to_str(s), _to_int(n)),
        # dates ---------------------------------------------------------
        "now": lambda: datetime.datetime.now(datetime.timezone.utc),
        "date": _fmt_date,
        "dateInZone": _date_in_zone,
        "unixEpoch": lambda t: int(_as_datetime(t).timestamp()),
        "toDate": _to_date,
        "duration": lambda secs: f"{_to_int(secs)}s",
        "htmlDate": lambda t: _fmt_date("2006-01-02", t),
        # type introspection -------------------------------------------
        "kindOf": _kind_of,
        "kindIs": lambda k, v: _kind_of(v) == k,
        "typeOf": _kind_of,
        "typeIs": lambda k, v: _kind_of(v) == k,
        "deepEqual": _deep_equal,
        # paths ---------------------------------------------------------
        "base": posixpath.basename,
        "dir": posixpath.dirname,
        "clean": posixpath.normpath,
        "ext": lambda p: posixpath.splitext(p)[1],
        "isAbs": posixpath.isabs,
        # os (sprig exposes these; harmless reads) ----------------------
        "env": lambda name: os.environ.get(name, ""),
        "expandenv": os.path.expandvars,
        "getHostByName": lambda name: "",  # no network I/O by design
        # semver --------------------------------------------------------
        "semverCompare": _semver_compare,
        "semver": lambda v: dict(
            zip(
                ("Major", "Minor", "Patch", "Prerelease"),
                _semver_tuple(v),
            )
        ),
    }
    # sprig's must* variants surface errors; the engine already raises,
    # so they alias the plain forms
    for name in (
        "fromJson", "toDate", "uuidv4", "regexMatch", "regexFind",
        "regexFindAll", "regexReplaceAll", "regexSplit", "merge",
        "mergeOverwrite", "deepCopy", "first", "rest", "last", "initial",
        "append", "prepend", "reverse", "uniq", "without", "has",
        "compact", "slice", "chunk", "fromYaml", "toJson", "toYaml",
    ):
        funcs["must" + name[0].upper() + name[1:]] = funcs[name]
    return funcs


class _EmptyMatch:
    @staticmethod
    def group(_i: int) -> str:
        return ""


_EMPTY_MATCH = _EmptyMatch()


def _go_repl(repl: str) -> str:
    """Go regexp replacement syntax ($1) -> Python (\\1)."""
    return re.sub(r"\$(\d+)", r"\\\1", re.sub(r"\$\{(\d+)\}", r"\\\1", repl))


def _seq_range(*a) -> range:
    a = [_to_int(x) for x in a]
    if len(a) == 1:
        return range(1, a[0] + 1) if a[0] >= 1 else range(1, a[0] - 1, -1)
    if len(a) == 2:
        step = 1 if a[1] >= a[0] else -1
        return range(a[0], a[1] + step, step)
    if len(a) == 3:
        start, step, stop = a  # bash seq order: FIRST INCREMENT LAST
        if step == 0:
            return range(0)
        return range(start, stop + (1 if step > 0 else -1), step)
    return range(0)


def _uniq(l):
    out = []
    for x in l:
        if x not in out:
            out.append(x)
    return out


def _dict_set(d: dict, k: str, v: Any) -> dict:
    d[k] = v
    return d


def _dict_unset(d: dict, k: str) -> dict:
    d.pop(k, None)
    return d


def _merge_all(dst: dict, srcs) -> dict:
    for src in srcs:
        _deep_merge(dst, src or {})
    return dst


def _merge_overwrite(dst: dict, srcs) -> dict:
    for src in srcs:
        for k, v in (src or {}).items():
            if isinstance(dst.get(k), dict) and isinstance(v, dict):
                _merge_overwrite(dst[k], [v])
            else:
                dst[k] = v
    return dst


def _dig(*args):
    """dig key1 key2 ... default dict (sprig arg order)."""
    *keys, default, d = args
    cur = d
    for k in keys:
        if not isinstance(cur, dict) or k not in cur:
            return default
        cur = cur[k]
    return cur


def _fail(msg: str):
    raise ValueError(f"template fail: {msg}")


def _to_date(layout: str, s: str) -> datetime.datetime:
    """sprig toDate: parse with the Go layout (strict, errors surface)."""
    st = _go_layout_to_strftime(layout).replace("%:z", "%z")
    try:
        return datetime.datetime.strptime(_to_str(s), st)
    except ValueError:
        return _as_datetime(s)  # ISO fallback; raises when unparseable


def _date_in_zone(layout: str, t: Any, zone: str) -> str:
    import zoneinfo

    dt = _as_datetime(t)
    if zone and zone.upper() != "UTC":
        try:
            dt = dt.astimezone(zoneinfo.ZoneInfo(zone))
        except (KeyError, zoneinfo.ZoneInfoNotFoundError):
            raise ValueError(f"unknown time zone {zone!r}")
    return _fmt_date(layout, dt)


def _deep_equal(a: Any, b: Any) -> bool:
    """Go reflect.DeepEqual semantics: bools never equal ints (the
    engine's own eq uses the same guard)."""
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_deep_equal(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(
            _deep_equal(x, y) for x, y in zip(a, b)
        )
    return a == b
