"""Clock abstraction: real and fake (virtual) time.

The reference injects ``clock.Clock`` everywhere for testability
(reference: pkg/kwok/controllers/controller.go:102, queue Clock iface
pkg/utils/queue/delaying_queue.go:27-31). Here the same seam also
carries the record/replay speed scaling (reference: pkg/kwokctl/
recording/speed.go:24-62): a ``ScaledClock`` over the real clock plays
time faster/slower, and ``FakeClock`` drives deterministic tests and
the device tick's virtual-time column.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import List, Optional


def wall_age(rfc3339: Optional[str]) -> Optional[float]:
    """Seconds elapsed since an RFC3339 wall-clock timestamp (the
    Lease ``renewTime`` display format written by
    ``cluster.election`` / ``controllers.node_lease_controller``),
    clamped at 0; None for absent/unparseable values.  Display-only —
    lease *expiry* decisions use locally-observed monotonic time, never
    this (see MonotonicClock)."""
    if not rfc3339:
        return None
    import datetime

    try:
        t = datetime.datetime.fromisoformat(
            str(rfc3339).replace("Z", "+00:00")
        )
    except ValueError:
        return None
    if t.tzinfo is None:
        t = t.replace(tzinfo=datetime.timezone.utc)
    now = datetime.datetime.now(datetime.timezone.utc)
    return max(0.0, (now - t).total_seconds())


class Clock:
    """Monotonic-ish wall clock in float seconds."""

    def now(self) -> float:
        raise NotImplementedError

    def wait_signal(self, signal: threading.Event, timeout: Optional[float]) -> None:
        """Block until ``signal`` is set or ``timeout`` *clock* seconds
        elapse (the Go ``select { <-After(d); <-signal }``)."""
        raise NotImplementedError

    def subscribe(self, signal: threading.Event) -> None:
        """Register a signal to be pinged when virtual time advances
        (no-op for real clocks)."""


class RealClock(Clock):
    def now(self) -> float:
        return time.time()

    def wait_signal(self, signal: threading.Event, timeout: Optional[float]) -> None:
        signal.wait(timeout)

    def subscribe(self, signal: threading.Event) -> None:
        pass


class MonotonicClock(Clock):
    """``time.monotonic``-based clock for deadline/lease arithmetic.

    Leader-election and lease-expiry math must be immune to wall-clock
    skew (NTP steps, suspend/resume): client-go measures lease expiry
    from a *locally observed* monotonic timestamp, never from the
    renewTime written in the record (leaderelection.go:61-73 "is
    susceptible to clock skew" caveat).  The kwoklint
    ``wallclock-deadline`` rule points offenders here."""

    def now(self) -> float:
        return time.monotonic()

    def wait_signal(self, signal: threading.Event, timeout: Optional[float]) -> None:
        signal.wait(timeout)

    def subscribe(self, signal: threading.Event) -> None:
        pass


class ScaledClock(Clock):
    """Real time scaled by a live-adjustable factor (replay speed).

    ``now`` advances at ``speed`` × real rate from the moment the speed
    was last changed; ``speed=0`` pauses (reference: recording/handle.go
    pause/speed keyboard control).
    """

    def __init__(self, speed: float = 1.0, base: Optional[Clock] = None):
        self._base = base or RealClock()
        self._speed = speed
        self._origin_real = self._base.now()
        self._origin_virtual = 0.0
        self._mut = threading.Lock()

    @property
    def speed(self) -> float:
        with self._mut:
            return self._speed

    def set_speed(self, speed: float) -> None:
        with self._mut:
            now = self._now_locked()
            self._origin_virtual = now
            self._origin_real = self._base.now()
            self._speed = max(0.0, speed)

    def _now_locked(self) -> float:
        return self._origin_virtual + (self._base.now() - self._origin_real) * self._speed

    def now(self) -> float:
        with self._mut:
            return self._now_locked()

    def wait_signal(self, signal: threading.Event, timeout: Optional[float]) -> None:
        if timeout is None:
            signal.wait(None)
            return
        with self._mut:
            speed = self._speed
        # virtual timeout -> real timeout; when paused, poll slowly
        real = timeout / speed if speed > 0 else 0.5
        signal.wait(min(real, 10.0))

    def subscribe(self, signal: threading.Event) -> None:
        pass


class FakeClock(Clock):
    """Manually advanced virtual clock for deterministic tests."""

    def __init__(self, start: float = 0.0):
        self._now = start
        self._mut = threading.Lock()
        self._subscribers: List[threading.Event] = []

    def now(self) -> float:
        with self._mut:
            return self._now

    def subscribe(self, signal: threading.Event) -> None:
        with self._mut:
            self._subscribers.append(signal)

    def advance(self, dt: float) -> None:
        with self._mut:
            self._now += dt
            subs = list(self._subscribers)
        for s in subs:
            s.set()

    def set(self, t: float) -> None:
        with self._mut:
            self._now = max(self._now, t)
            subs = list(self._subscribers)
        for s in subs:
            s.set()

    def wait_signal(self, signal: threading.Event, timeout: Optional[float]) -> None:
        # Virtual timeouts only elapse via advance(); advance pings all
        # subscribed signals, so just wait on the signal (bounded so a
        # missing advance in a test cannot hang forever).
        signal.wait(5.0)


class VirtualClock(FakeClock):
    """Deterministic-simulation clock (the DST harness,
    :mod:`kwok_tpu.dst`): FakeClock plus a registry of parked timeout
    deadlines, so the simulation scheduler can see the earliest instant
    any waiter is due to wake (:meth:`next_deadline`) and advance
    virtual time exactly there.  Time moves only when the simulation
    steps — a thread parked in :meth:`wait_signal` wakes when its
    signal fires or its *virtual* deadline passes, never because wall
    time elapsed.

    ``poll_s`` bounds the real-time wait per wakeup check: ``advance``
    pings every subscribed signal, and an un-advanced clock must never
    hang a waiter forever (the FakeClock posture, kept here).
    """

    def __init__(self, start: float = 0.0, poll_s: float = 0.02):
        super().__init__(start)
        self.poll_s = poll_s
        #: min-heap of virtual instants some waiter is due to wake at
        self._deadlines: List[float] = []

    #: real-seconds bound on one wait: a clock nobody advances anymore
    #: must not hang a waiter forever (the FakeClock 5s posture)
    REAL_WAIT_CAP_S = 5.0

    def wait_signal(self, signal: threading.Event, timeout: Optional[float]) -> None:
        if timeout is None:
            # no virtual deadline to honor: wake on advance() pings
            signal.wait(self.poll_s)
            return
        with self._mut:
            deadline = self._now + timeout
            heapq.heappush(self._deadlines, deadline)
        give_up = time.monotonic() + self.REAL_WAIT_CAP_S
        while (
            not signal.is_set()
            and self.now() < deadline
            and time.monotonic() < give_up
        ):
            signal.wait(self.poll_s)

    def next_deadline(self) -> Optional[float]:
        """Earliest still-pending parked deadline, or None.  Deadlines
        at/below the current instant are expired and dropped."""
        with self._mut:
            while self._deadlines and self._deadlines[0] <= self._now:
                heapq.heappop(self._deadlines)
            return self._deadlines[0] if self._deadlines else None

    def advance_to_next(self, limit: Optional[float] = None) -> bool:
        """Advance to the earliest parked deadline (bounded by
        ``limit``); returns False when there is none (or it lies past
        the limit).  The step-the-world primitive for tests migrating
        off wall-clock sleeps."""
        nxt = self.next_deadline()
        if nxt is None or (limit is not None and nxt > limit):
            return False
        self.set(nxt)
        return True
