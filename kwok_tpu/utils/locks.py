"""Runtime deadlock sentinel: named locks that learn the process-wide
acquisition order and fail loudly on an inversion.

The static half of the concurrency gate
(``kwok_tpu/analysis/lock_order.py``) derives the
may-hold-while-acquiring graph lexically; this is the dynamic
complement for the holds a lexical view cannot see — locks carried
across context-manager boundaries (``cluster/store.py`` ``_LaneGrant``
holds the store mutex from ``__enter__`` to ``__exit__``), receivers
too dynamic to type, and whatever the sharded-store refactor
(ROADMAP.md:53-82) wires up at runtime.  Modeled on what the reference
gets from ``go test -race`` in CI (PARITY.md:175): every chaos/DST run
doubles as a deadlock detector.

Usage: the shared-state lock sites (store, flowcontrol, election,
informer) create their mutexes through :func:`make_lock` /
:func:`make_rlock` instead of calling ``threading`` directly.  With
``KWOK_LOCK_SENTINEL`` unset the factories return the plain
``threading`` primitive — zero wrapping, zero overhead, byte-identical
behavior.  With ``KWOK_LOCK_SENTINEL=1`` they return instrumented
wrappers that record, per thread, which named lock classes were held
at each blocking acquire, merge those orders into one process-global
order graph, and raise :class:`LockInversion` at the acquire that
would close a cycle — BEFORE blocking on it, so the report fires
instead of the hang.

Determinism contract: the sentinel reads no clock and no RNG and emits
nothing into any trace, so DST runs produce byte-identical trace
digests sentinel-on vs sentinel-off (tests/test_locks.py pins this) —
which is what lets ``tools/check.sh`` keep its DST stage permanently
armed.

Lock identity is the NAME (the ``module.Class.attr`` lock class, same
granularity as the static analyzer), not the instance: holding
instance A of a class while acquiring instance B of the same class is
re-entrancy by name and records no edge, exactly like the static
rule's RLock self-edge exemption.

``KWOK_RACE_SENTINEL=1`` arms the second detector on the same
held-stack bookkeeping: an Eraser-style lockset checker.  The static
``guarded-by`` rule (kwok_tpu/analysis/guarded_by.py) proves lock
coverage lexically; :func:`guarded` is its runtime twin — a class
declares "this attribute is protected by that lock class" at
construction, and every subsequent get/set of the attribute is checked
against the accessing thread's held-set.  The per-attribute state
machine follows Eraser's ownership refinement: *fresh* (declared,
untouched) → *exclusive* (single owner thread — no lock required, so
single-threaded DST runs are violation-free by construction) →
*shared* (a second thread touched it — from then on EVERY access must
hold the declared lock or :class:`RaceWitness` fires with both access
sites).  Like the order sentinel it reads no clock and no RNG, so DST
trace digests stay byte-identical armed vs disarmed.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LockInversion",
    "RaceWitness",
    "guarded",
    "make_lock",
    "make_rlock",
    "make_condition",
    "sentinel_enabled",
    "race_sentinel_enabled",
    "reset_sentinel",
    "sentinel_order_graph",
]


class LockInversion(RuntimeError):
    """Two threads acquired the same lock classes in opposite orders.

    Raised in the acquiring thread before it blocks — the process gets
    a traceback naming both orders instead of a silent deadlock."""


class RaceWitness(RuntimeError):
    """A declared-guarded attribute was touched by multiple threads
    without the declared lock held.

    Raised in the accessing thread at the unguarded access — the
    report names the attribute, the missing lock class, this access
    site and the previous one, instead of silent corruption."""


def sentinel_enabled() -> bool:
    return os.environ.get("KWOK_LOCK_SENTINEL", "") == "1"


def race_sentinel_enabled() -> bool:
    return os.environ.get("KWOK_RACE_SENTINEL", "") == "1"


class _Registry:
    """Process-global acquisition-order graph.

    ``_edges[held][acquired]`` exists when some thread blocked on
    ``acquired`` while holding ``held``; the value is the first
    witness (thread name, held-stack snapshot).  A cycle can only
    appear at the instant its final edge is inserted, so the (locked)
    path check runs on NEW edges only — repeat acquisitions take the
    lock-free dict-hit fast path."""

    def __init__(self) -> None:
        self._mut = threading.Lock()
        self._edges: Dict[str, Dict[str, Tuple[str, Tuple[str, ...]]]] = {}
        self._local = threading.local()

    # ------------------------------------------------------- held stack

    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = []
            self._local.stack = st
        return st

    def push(self, name: str) -> None:
        self._stack().append(name)

    def pop(self, name: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                return
        # release of a lock this thread never tracked (cross-thread
        # release): nothing to unwind

    def holds(self, name: str) -> bool:
        """True when the CURRENT thread holds a lock of class ``name``
        (the race sentinel's lockset membership test)."""
        return name in self._stack()

    # ------------------------------------------------------ order graph

    def before_blocking_acquire(self, name: str) -> None:
        st = self._stack()
        if not st or name in st:
            # nothing held, or re-entrancy by name: no ordering fact
            return
        held = []
        seen = set()
        for h in st:
            if h not in seen:
                seen.add(h)
                held.append(h)
        snapshot = tuple(st)
        tname = threading.current_thread().name
        for h in held:
            bucket = self._edges.get(h)
            if bucket is not None and name in bucket:
                continue  # known-good order, lock-free fast path
            with self._mut:
                bucket = self._edges.setdefault(h, {})
                if name in bucket:
                    continue
                cycle = self._path(name, h)
                if cycle is not None:
                    # deliberately NOT recorded: if this raise is
                    # absorbed by a broad handler upstream, the next
                    # occurrence must miss the fast path and re-raise —
                    # otherwise retry number two blocks into the real
                    # deadlock with no diagnostic
                    raise LockInversion(
                        self._render(h, name, cycle, tname, snapshot)
                    )
                bucket[name] = (tname, snapshot)

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """Edge path src -> ... -> dst in the current graph, or None."""
        if src == dst:
            return [src]
        prev: Dict[str, str] = {}
        seen = {src}
        queue = [src]
        while queue:
            nxt: List[str] = []
            for n in queue:
                for m in self._edges.get(n, ()):
                    if m in seen:
                        continue
                    prev[m] = n
                    if m == dst:
                        path = [m]
                        while path[-1] != src:
                            path.append(prev[path[-1]])
                        return list(reversed(path))
                    seen.add(m)
                    nxt.append(m)
            queue = nxt
        return None

    def _render(self, held: str, acquiring: str, cycle: List[str],
                tname: str, snapshot: Tuple[str, ...]) -> str:
        lines = [
            f"lock order inversion: thread {tname!r} holds {held} "
            f"(stack: {' -> '.join(snapshot)}) and is acquiring {acquiring},",
            "but the opposite order is already established: "
            + " -> ".join(cycle),
        ]
        for a, b in zip(cycle, cycle[1:]):
            wt, wstack = self._edges[a][b]
            lines.append(
                f"  {a} -> {b} first seen in thread {wt!r} "
                f"(held: {' -> '.join(wstack) or '-'})"
            )
        lines.append(
            "one of these acquisition chains must reorder or narrow its hold"
        )
        return "\n".join(lines)

    def graph(self) -> Dict[str, Dict[str, Tuple[str, Tuple[str, ...]]]]:
        with self._mut:
            return {h: dict(b) for h, b in self._edges.items()}

    def reset(self) -> None:
        with self._mut:
            self._edges.clear()
        # per-thread held stacks intentionally survive: live holds are
        # still live; tests reset between scenarios on fresh threads


_registry = _Registry()


def sentinel_order_graph():
    """Snapshot of the learned order graph (diagnostics/tests)."""
    return _registry.graph()


def reset_sentinel() -> None:
    """Forget all learned edges (test isolation)."""
    _registry.reset()


class _SentinelLock:
    """Instrumented non-reentrant lock.  Held-stack bookkeeping always
    runs (both sentinels consume it); the order-graph check only when
    the lock sentinel proper is armed — a race-sentinel-only process
    wants locksets, not ordering edges."""

    _factory = staticmethod(threading.Lock)

    __slots__ = ("_name", "_inner", "_order")

    def __init__(self, name: str):
        self._name = name
        self._inner = self._factory()
        self._order = sentinel_enabled()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking and self._order:
            # raises LockInversion BEFORE blocking when this acquire
            # would close an order cycle
            _registry.before_blocking_acquire(self._name)
        # this IS the lock implementation: release pairs in release(),
        # driven by the caller's with/try-finally
        ok = self._inner.acquire(blocking, timeout)  # kwoklint: disable=lock-discipline
        if ok:
            _registry.push(self._name)
        return ok

    def release(self) -> None:
        self._inner.release()
        _registry.pop(self._name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        # the context-manager face of the wrapper — __exit__ releases
        self.acquire()  # kwoklint: disable=lock-discipline
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug surface
        return f"<{type(self).__name__} {self._name} {self._inner!r}>"


class _SentinelRLock(_SentinelLock):
    """Instrumented re-entrant lock.  The ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` trio keeps
    ``threading.Condition`` working on top of it (wait() fully
    releases the hold, and the held-stack follows suit so no false
    edges are recorded while waiting)."""

    _factory = staticmethod(threading.RLock)

    __slots__ = ()

    def _release_save(self):
        state = self._inner._release_save()
        _registry.pop(self._name)
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        _registry.push(self._name)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def make_lock(name: str):
    """A ``threading.Lock`` — instrumented when either sentinel is
    armed (KWOK_LOCK_SENTINEL=1 / KWOK_RACE_SENTINEL=1).

    ``name`` is the lock class, conventionally the static analyzer's
    identity ``module.Class.attr`` without the ``kwok_tpu.`` prefix."""
    if sentinel_enabled() or race_sentinel_enabled():
        return _SentinelLock(name)
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` — instrumented when either sentinel is armed."""
    if sentinel_enabled() or race_sentinel_enabled():
        return _SentinelRLock(name)
    return threading.RLock()


def make_condition(name: str):
    """A ``threading.Condition`` whose inner RLock is instrumented
    when either sentinel is armed."""
    if sentinel_enabled() or race_sentinel_enabled():
        return threading.Condition(_SentinelRLock(name))
    return threading.Condition()


# --------------------------------------------------------------------------
# race sentinel: Eraser-style lockset checking on declared attributes


#: per-attribute ownership states (Eraser's refinement, minus the
#: read-shared stage: a control plane's guarded state is read/write)
_FRESH = 0       # declared, no access yet — next toucher owns it
_EXCLUSIVE = 1   # single owner thread; no lock needed
_SHARED = 2      # multiple threads have touched it; lock required


def _access_site() -> str:
    """``file:line (thread)`` of the code touching the guarded
    attribute: three frames up — site -> descriptor hook -> _check ->
    here."""
    fr = sys._getframe(3)
    return (
        f"{fr.f_code.co_filename}:{fr.f_lineno}"
        f" (thread {threading.current_thread().name!r})"
    )


class _GuardedAttr:
    """Data descriptor the race sentinel installs over a declared
    attribute.  Value storage delegates to the class's own slot
    descriptor when there is one, else shadows into the instance
    ``__dict__`` under a private key (a data descriptor wins the
    lookup, so plain attribute syntax keeps working).  Only instances
    explicitly registered via :func:`guarded` are checked — and only
    while KWOK_RACE_SENTINEL=1, so a class that once armed in-process
    stays behaviorally inert for later unarmed code."""

    __slots__ = ("_attr", "_lock_name", "_base", "_shadow", "_skey", "_states")

    def __init__(self, attr: str, lock_name: str, base):
        self._attr = attr
        self._lock_name = lock_name
        self._base = base  # slot member descriptor, or None (dict class)
        self._shadow = f"_kwok_guarded_value__{attr}"
        self._skey = f"_kwok_guarded_state__{attr}"
        #: id(obj) -> (obj, [state, owner_ident, last_site]) for
        #: SLOTTED owners (no instance dict to stash in).  The strong
        #: reference is deliberate: it pins registered ids so a dead
        #: instance's address can never resurface as a different
        #: registered object carrying stale SHARED state (the sentinel
        #: only runs in tests/DST, and adopted slotted objects are
        #: small and few).  Dict-based owners keep state in their own
        #: ``__dict__`` so it dies with them.
        self._states: Dict[int, tuple] = {}

    # ------------------------------------------------------------ state

    def _register(self, obj) -> None:
        st = [_FRESH, 0, "<declared>"]
        if self._base is None:
            obj.__dict__[self._skey] = st
        else:
            self._states[id(obj)] = (obj, st)

    def _state(self, obj):
        if self._base is None:
            return obj.__dict__.get(self._skey)
        ent = self._states.get(id(obj))
        if ent is None or ent[0] is not obj:
            return None  # unregistered instance (or pre-register init write)
        return ent[1]

    def _check(self, obj) -> None:
        if not race_sentinel_enabled():
            return
        st = self._state(obj)
        if st is None:
            return  # never declared on this instance
        ident = threading.get_ident()
        if st[0] == _FRESH:
            st[0] = _EXCLUSIVE
            st[1] = ident
            st[2] = _access_site()
            return
        if st[0] == _EXCLUSIVE and st[1] == ident:
            st[2] = _access_site()
            return
        # second thread arrived (or already shared): lockset check
        st[0] = _SHARED
        if not _registry.holds(self._lock_name):
            here = _access_site()
            raise RaceWitness(
                f"unguarded access to {type(obj).__name__}.{self._attr}: "
                f"declared guarded by {self._lock_name}, which this "
                "thread does not hold\n"
                f"  this access:     {here}\n"
                f"  previous access: {st[2]}\n"
                "hold the lock around the access, or drop the "
                "guarded() declaration if the attribute is deliberately "
                "lock-free (then suppress the static guarded-by rule "
                "with the invariant that makes that safe)"
            )
        st[2] = _access_site()

    # ------------------------------------------------------- descriptor

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._check(obj)
        if self._base is not None:
            return self._base.__get__(obj, objtype)
        try:
            return obj.__dict__[self._shadow]
        except KeyError:
            raise AttributeError(
                f"{type(obj).__name__!r} object has no attribute "
                f"{self._attr!r}"
            ) from None

    def __set__(self, obj, value) -> None:
        self._check(obj)
        if self._base is not None:
            self._base.__set__(obj, value)
        else:
            obj.__dict__[self._shadow] = value

    def __delete__(self, obj) -> None:
        self._check(obj)
        if self._base is not None:
            self._base.__delete__(obj)
        else:
            try:
                del obj.__dict__[self._shadow]
            except KeyError:
                raise AttributeError(
                    f"{type(obj).__name__!r} object has no attribute "
                    f"{self._attr!r}"
                ) from None


_guard_install_mut = threading.Lock()


def guarded(obj, attr: str, lock_name: str) -> None:
    """Declare that ``obj.<attr>`` is protected by lock class
    ``lock_name`` (the ``module.Class.attr`` identity the lock was
    created under).  No-op unless KWOK_RACE_SENTINEL=1.

    Call it from ``__init__`` right after the attribute first exists —
    the adopted sites (store/flowcontrol/election/fleet) pair each
    declaration with the matching static-rule contract, so the lexical
    ``guarded-by`` analyzer and this runtime checker enforce the same
    invariant from two sides.  Once any thread other than the owner
    touches the attribute, every access without the declared lock held
    raises :class:`RaceWitness` naming both access sites."""
    if not race_sentinel_enabled():
        return
    cls = type(obj)
    with _guard_install_mut:
        cur = cls.__dict__.get(attr)
        if isinstance(cur, _GuardedAttr):
            desc = cur
        else:
            base = cur if hasattr(cur, "__set__") else None
            desc = _GuardedAttr(attr, lock_name, base)
            if base is None and attr in getattr(obj, "__dict__", {}):
                # instance predates the descriptor: its value sits in
                # the instance dict, which the data descriptor would
                # mask — migrate it to the shadow slot
                obj.__dict__[desc._shadow] = obj.__dict__.pop(attr)
            setattr(cls, attr, desc)
        desc._register(obj)
