"""Capped exponential backoff with jitter — the one retry schedule
shared by stage retries, the REST client's RetryPolicy, and the
component supervisor (reference pkg/kwok/controllers/utils.go:133-143
defaultBackoff/backoffDelayByStep: 1s × 2ⁿ, jitter 0.2, cap 32 min).

Lives in ``utils`` (layer 0) so both ``cluster`` and ``controllers``
can share it without a layering edge; ``controllers.utils`` re-exports
for its historical importers.

The jitter source is an *explicit* ``random.Random``: there is
deliberately no fallback to the global ``random`` module, so retry
schedules are reproducible under a chaos seed and tracer-safe by
construction (kwoklint's tracer-safety rule bans stdlib randomness in
jitted code; an explicit instance can never leak in ambiently).
"""

from __future__ import annotations

import random
from dataclasses import dataclass


@dataclass
class Backoff:
    """``delay(steps, rng)`` = ``min(duration·factorˢᵗᵉᵖˢ, cap)``
    stretched by up to ``jitter`` of itself."""

    duration: float = 1.0
    factor: float = 2.0
    jitter: float = 0.2
    cap: float = 32 * 60.0

    def delay(self, steps: int, rng: random.Random) -> float:
        d = min(self.duration * (self.factor**steps), self.cap)
        return d * (1.0 + self.jitter * rng.random())


__all__ = ["Backoff"]
