"""Capped exponential backoff with jitter — the one retry schedule
shared by stage retries, the REST client's RetryPolicy, and the
component supervisor (reference pkg/kwok/controllers/utils.go:133-143
defaultBackoff/backoffDelayByStep: 1s × 2ⁿ, jitter 0.2, cap 32 min).

Lives in ``utils`` (layer 0) so both ``cluster`` and ``controllers``
can share it without a layering edge; ``controllers.utils`` re-exports
for its historical importers.

The jitter source is an *explicit* ``random.Random``: there is
deliberately no fallback to the global ``random`` module, so retry
schedules are reproducible under a chaos seed and tracer-safe by
construction (kwoklint's tracer-safety rule bans stdlib randomness in
jitted code; an explicit instance can never leak in ambiently).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, Hashable, Tuple


@dataclass
class Backoff:
    """``delay(steps, rng)`` = ``min(duration·factorˢᵗᵉᵖˢ, cap)``
    stretched by up to ``jitter`` of itself."""

    duration: float = 1.0
    factor: float = 2.0
    jitter: float = 0.2
    cap: float = 32 * 60.0

    def delay(self, steps: int, rng: random.Random) -> float:
        d = min(self.duration * (self.factor**steps), self.cap)
        return d * (1.0 + self.jitter * rng.random())


@dataclass
class WarnGate:
    """Per-key deduplicated warning cadence: first emission immediate,
    then the interval doubles per emission up to ``cap_s`` — the
    event-flood guard shared by the scheduler's per-pod
    ``FailedScheduling`` stream and the gang engine's per-gang one.

    ``ready(key, now)`` is True when a warning may be emitted for
    ``key`` (and advances the schedule); ``clear(key)`` forgets the
    key once its condition resolves.  Clock-free and rng-free — the
    caller passes ``now`` from its injected clock, so gated event
    streams stay DST-deterministic.  Not thread-safe: multi-threaded
    callers hold their own lock around ``ready``."""

    base_s: float = 2.0
    cap_s: float = 60.0

    def __post_init__(self) -> None:
        self._next: Dict[Hashable, Tuple[float, float]] = {}

    def ready(self, key: Hashable, now: float) -> bool:
        next_t, interval = self._next.get(key, (0.0, self.base_s))
        if now < next_t:
            return False
        self._next[key] = (now + interval, min(interval * 2.0, self.cap_s))
        return True

    def clear(self, key: Hashable) -> None:
        self._next.pop(key, None)

    def __len__(self) -> int:
        """Keys with live cadence state (0 = nothing pending)."""
        return len(self._next)


__all__ = ["Backoff", "WarnGate"]
