"""Selector requirements and value getters over kq queries.

Mirrors reference pkg/utils/expression:
- Requirement (selector.go:28-120): key query + In/NotIn/Exists/DoesNotExist,
  values compared as strings (bool -> "true"/"false", ints base-10).
- IntGetter (value_int_from.go:40-80): expression result overrides the
  static value; empty result falls back to the static value; empty-string
  or unparsable results are "not ok".
- DurationGetter (value_duration_from.go:40-79): expression result is
  either an RFC3339 timestamp (duration = t - now) or a Go duration
  string; falls back to the static value on empty result.
"""

from __future__ import annotations

import datetime
import re
from typing import Any, List, Optional, Sequence

from kwok_tpu.utils.kq import KqCompileError, Query

OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"

_OPS = (OP_IN, OP_NOT_IN, OP_EXISTS, OP_DOES_NOT_EXIST)


class Requirement:
    """One selector matchExpression (reference selector.go:28-91)."""

    def __init__(self, key: str, operator: str, values: Optional[Sequence[str]] = None):
        values = list(values or [])
        if operator in (OP_IN, OP_NOT_IN):
            if not values:
                raise ValueError("for 'in', 'notin' operators, values set can't be empty")
        elif operator in (OP_EXISTS, OP_DOES_NOT_EXIST):
            if values:
                raise ValueError("values set must be empty for exists and does not exist")
        else:
            raise ValueError(f"operator {operator!r} is not supported")
        self.key = key
        self.operator = operator
        self.values = values
        self.query = Query(key)

    def matches(self, data: Any) -> bool:
        out = self.query.execute(data)
        if not out:
            # None (error) and [] are both "no data" (selector.go:66-76).
            return self.operator in (OP_NOT_IN, OP_DOES_NOT_EXIST)
        if self.operator == OP_IN:
            return _has_values(out, self.values)
        if self.operator == OP_NOT_IN:
            return not _has_values(out, self.values)
        if self.operator == OP_EXISTS:
            return _exists_value(out)
        return not _exists_value(out)


def value_as_string(d: Any) -> Optional[str]:
    """Selector value stringification (selector.go:96-110 hasValue):
    strings as-is, bools lowercase, ints base-10; other types don't
    participate in In/NotIn comparison."""
    if isinstance(d, bool):
        return "true" if d else "false"
    if isinstance(d, str):
        return d
    if isinstance(d, int):
        return str(d)
    return None


_value_as_string = value_as_string


def _has_values(out: List[Any], values: Sequence[str]) -> bool:
    for d in out:
        s = _value_as_string(d)
        if s is not None and s in values:
            return True
    return False


def _exists_value(out: List[Any]) -> bool:
    return any(d is not None for d in out)


# ---------------------------------------------------------------------------
# Duration parsing (Go time.ParseDuration-compatible subset)
# ---------------------------------------------------------------------------

_GO_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_GO_UNIT_SECONDS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}


def parse_go_duration(s: str) -> Optional[float]:
    """Parse a Go duration string ("1.5h30m", "10s") to seconds."""
    s = s.strip()
    if not s:
        return None
    neg = False
    if s[0] in "+-":
        neg = s[0] == "-"
        s = s[1:]
    if s == "0":
        return 0.0
    pos = 0
    total = 0.0
    while pos < len(s):
        m = _GO_DURATION_RE.match(s, pos)
        if m is None:
            return None
        total += float(m.group(1)) * _GO_UNIT_SECONDS[m.group(2)]
        pos = m.end()
    return -total if neg else total


def parse_rfc3339(s: str) -> Optional[datetime.datetime]:
    try:
        if s.endswith("Z"):
            s = s[:-1] + "+00:00"
        # RFC 3339 allows any number of fractional digits, but
        # fromisoformat before Python 3.11 accepts exactly 3 or 6 —
        # normalize ("00:00:00.5" -> "00:00:00.500000")
        m = re.match(r"^(.*T\d{2}:\d{2}:\d{2})\.(\d+)(.*)$", s)
        if m:
            frac = (m.group(2) + "000000")[:6]
            s = f"{m.group(1)}.{frac}{m.group(3)}"
        t = datetime.datetime.fromisoformat(s)
        if t.tzinfo is None:
            t = t.replace(tzinfo=datetime.timezone.utc)
        return t
    except ValueError:
        return None


class IntGetter:
    """Static int64 optionally overridden by an expression
    (reference value_int_from.go:28-80)."""

    def __init__(self, value: Optional[int], expression: Optional[str]):
        self.value = value
        self.query = Query(expression) if expression else None

    def get(self, data: Any) -> tuple:
        """Returns (value, ok)."""
        if self.query is None:
            if self.value is None:
                return 0, False
            return self.value, True
        out = self.query.execute(data)
        # Runtime query errors are swallowed to an empty result by the
        # reference (query.go:57-59 returns nil, nil), so both None and []
        # fall back to the static value.
        if not out:
            if self.value is not None:
                return self.value, True
            return 0, False
        first = out[0]
        if isinstance(first, str):
            if first == "":
                return 0, False
            try:
                return int(first, 0), True
            except ValueError:
                return 0, False
        if isinstance(first, bool):
            pass  # falls through to static fallback, like the Go default case
        elif isinstance(first, (int, float)):
            return int(first), True
        if self.value is not None:
            return self.value, True
        return 0, False


class DurationGetter:
    """Static duration (seconds) optionally overridden by an expression
    yielding an RFC3339 deadline or Go duration string
    (reference value_duration_from.go:28-79)."""

    def __init__(self, value_seconds: Optional[float], expression: Optional[str]):
        self.value = value_seconds
        self.query = Query(expression) if expression else None

    def get(self, data: Any, now: datetime.datetime) -> tuple:
        """Returns (seconds, ok)."""
        if self.query is None:
            if self.value is None:
                return 0.0, False
            return self.value, True
        out = self.query.execute(data)
        # None (swallowed error) and [] both mean "no data" -> static fallback.
        if not out:
            if self.value is not None:
                return self.value, True
            return 0.0, False
        first = out[0]
        if isinstance(first, str):
            if first == "":
                return 0.0, False
            t = parse_rfc3339(first)
            if t is not None:
                return (t - now).total_seconds(), True
            d = parse_go_duration(first)
            if d is not None:
                return d, True
        return 0.0, False


def compile_requirements(exprs: Sequence[dict]) -> List[Requirement]:
    """Build Requirements from matchExpressions dicts; raises
    KqCompileError/ValueError for out-of-subset queries."""
    reqs = []
    for e in exprs:
        reqs.append(Requirement(e["key"], e["operator"], e.get("values")))
    return reqs


__all__ = [
    "Requirement",
    "IntGetter",
    "DurationGetter",
    "compile_requirements",
    "parse_go_duration",
    "parse_rfc3339",
    "KqCompileError",
    "OP_IN",
    "OP_NOT_IN",
    "OP_EXISTS",
    "OP_DOES_NOT_EXIST",
]
