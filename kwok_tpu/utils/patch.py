"""Patch application: JSON Patch, JSON Merge Patch, strategic merge.

The reference applies stage effects as one of three patch types against
the apiserver (reference: pkg/utils/lifecycle/next.go:96-121,
pkg/kwok/controllers/utils.go:162-304 for no-op detection). Here the
store is in-process, so we implement the appliers directly:

- JSON Patch (RFC 6902) subset: add/remove/replace — what the finalizer
  ops emit (reference finalizers.go:32-116).
- JSON Merge Patch (RFC 7386): recursive merge, null deletes.
- Strategic merge: like merge patch, but lists of objects merge by a
  patch-merge key (k8s semantics). We carry a small key table for the
  types the simulator touches (containers/conditions by name/type);
  unknown lists replace wholesale, which matches the RFC 7386 fallback
  the reference gets for unregistered types.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

PATCH_JSON = "json"
PATCH_MERGE = "merge"
PATCH_STRATEGIC = "strategic"

# ---------------------------------------------------------------------------
# Strategic-merge metadata
#
# The reference discovers patchMergeKey/patchStrategy per type from the
# apiserver's OpenAPI v3 (pkg/utils/patch/openapi.go:43-248).  This repo IS
# the apiserver, so the authoritative metadata lives here: a per-kind table
# mirroring the upstream k8s struct tags (x-kubernetes-patch-merge-key /
# x-kubernetes-patch-strategy), served back out via /openapi/v3
# (cluster/k8s_api.py) so ecosystem tools discover the same truth.
# ---------------------------------------------------------------------------

#: ("merge", key) = merge by key; ("merge", None) = primitive set-merge;
#: absent = atomic (replace wholesale)
_POD_META = {
    ("spec", "containers"): ("merge", "name"),
    ("spec", "initContainers"): ("merge", "name"),
    ("spec", "ephemeralContainers"): ("merge", "name"),
    ("spec", "volumes"): ("merge", "name"),
    ("spec", "containers", "env"): ("merge", "name"),
    ("spec", "containers", "ports"): ("merge", "containerPort"),
    ("spec", "containers", "volumeMounts"): ("merge", "mountPath"),
    ("spec", "containers", "volumeDevices"): ("merge", "devicePath"),
    ("spec", "initContainers", "env"): ("merge", "name"),
    ("spec", "initContainers", "ports"): ("merge", "containerPort"),
    ("spec", "initContainers", "volumeMounts"): ("merge", "mountPath"),
    ("spec", "imagePullSecrets"): ("merge", "name"),
    ("spec", "hostAliases"): ("merge", "ip"),
    ("spec", "readinessGates"): ("merge", "conditionType"),
    ("status", "conditions"): ("merge", "type"),
    # NOTE upstream PodStatus.ContainerStatuses carries NO patch tags:
    # atomic replace (the old name-keyed table diverged here)
}
_NODE_META = {
    ("status", "conditions"): ("merge", "type"),
    ("status", "addresses"): ("merge", "type"),
    # taints, images, volumesAttached: atomic upstream
}
_SERVICE_META = {
    ("spec", "ports"): ("merge", "port"),
}
_COMMON_META = {
    ("metadata", "finalizers"): ("merge", None),  # primitive set-merge
    ("metadata", "ownerReferences"): ("merge", "uid"),
}

#: kind -> {path tuple (list indices elided) -> ("merge", key|None)}
STRATEGIC_META: Dict[str, Dict[tuple, tuple]] = {
    "Pod": {**_COMMON_META, **_POD_META},
    "Node": {**_COMMON_META, **_NODE_META},
    "Service": {**_COMMON_META, **_SERVICE_META},
}

#: legacy field-NAME-keyed fallback for kinds without typed metadata
#: (CRDs and untyped objects): matches the pre-OpenAPI behavior so
#: unknown kinds keep merging the well-known k8s list shapes
_MERGE_KEYS = {
    "conditions": "type",
    "containers": "name",
    "initContainers": "name",
    "ephemeralContainers": "name",
    "containerStatuses": "name",
    "initContainerStatuses": "name",
    "ephemeralContainerStatuses": "name",
    "volumes": "name",
    "env": "name",
    "ports": "containerPort",
    "addresses": "type",
    "finalizers": None,  # set-merge
}


def register_strategic_meta(kind: str, path: tuple, merge_key: Optional[str]) -> None:
    """Register list metadata for a CRD kind (the CRD's
    x-kubernetes-patch-merge-key analog)."""
    STRATEGIC_META.setdefault(kind, dict(_COMMON_META))[tuple(path)] = (
        "merge",
        merge_key,
    )


def list_meta(kind: Optional[str], path: tuple, field_name: str):
    """(strategy, merge_key) for a list field: typed table first, then
    the name-keyed fallback for unknown kinds; None = atomic."""
    if kind:
        table = STRATEGIC_META.get(kind)
        if table is not None:
            return table.get(path)
    if field_name in _MERGE_KEYS:
        return ("merge", _MERGE_KEYS[field_name])
    return None


def apply_json_patch(obj: Any, ops: List[Dict[str, Any]]) -> Any:
    """Apply an RFC 6902 patch (add/remove/replace subset).

    Copy-on-write along each op's path only: untouched subtrees are
    SHARED with the input (the store's handed-out-by-reference contract
    makes inputs immutable; deep-copying a whole 60-node pod to flip
    one finalizer list was a top cost of the 1M-row create wave)."""
    out = _shallow(obj)
    for op in ops:
        path = op["path"]
        parts = [p.replace("~1", "/").replace("~0", "~") for p in path.split("/")[1:]]
        action = op["op"]
        parent, last = _traverse_cow(out, parts)
        if action == "add":
            value = _copy_json(op["value"])
            if isinstance(parent, list):
                if last == "-":
                    parent.append(value)
                else:
                    parent.insert(int(last), value)
            else:
                parent[last] = value
        elif action == "remove":
            if isinstance(parent, list):
                del parent[int(last)]
            else:
                if last not in parent:
                    raise KeyError(f"path not found: {path}")
                del parent[last]
        elif action == "replace":
            value = _copy_json(op["value"])
            if isinstance(parent, list):
                parent[int(last)] = value
            else:
                parent[last] = value
        else:
            raise ValueError(f"unsupported json patch op {action!r}")
    return out


def _traverse(obj: Any, parts: List[str]):
    cur = obj
    for p in parts[:-1]:
        if isinstance(cur, list):
            cur = cur[int(p)]
        else:
            cur = cur[p]
    return cur, parts[-1]


def _shallow(x: Any) -> Any:
    if isinstance(x, dict):
        return dict(x)
    if isinstance(x, list):
        return list(x)
    return x


def _traverse_cow(obj: Any, parts: List[str]):
    """Like _traverse, but shallow-copies each container on the walk
    and re-links it into the (already copied) parent, so mutating the
    returned parent never touches the original's subtrees."""
    cur = obj
    for p in parts[:-1]:
        if isinstance(cur, list):
            i = int(p)
            child = _shallow(cur[i])
            cur[i] = child
        else:
            child = _shallow(cur[p])
            cur[p] = child
        cur = child
    return cur, parts[-1]


def copy_json(x: Any) -> Any:
    """Deep copy for JSON-shaped data (dict/list/scalars) — the ONE
    canonical implementation (cluster.store re-exports it).  Inputs are
    JSON by contract, so the general deepcopy machinery (memo dict,
    reductor dispatch) is pure overhead on the hot copy paths; this is
    ~3x faster and shares immutable leaves."""
    t = type(x)
    if t is dict:
        return {k: copy_json(v) for k, v in x.items()}
    if t is list:
        return [copy_json(v) for v in x]
    return x


_copy_json = copy_json


def apply_merge_patch(obj: Any, patch: Any) -> Any:
    """RFC 7386 JSON Merge Patch."""
    if not isinstance(patch, dict):
        return _copy_json(patch)
    if not isinstance(obj, dict):
        obj = {}
    out = dict(obj)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = apply_merge_patch(out.get(k), v)
    return out


def merge_patch_is_noop(obj: Any, patch: Any) -> bool:
    """Would this RFC 7386 merge patch leave ``obj`` unchanged?
    Allocation-free equivalent of ``apply_merge_patch(obj, patch) ==
    obj`` (the drain runs this once per dirty row)."""
    if not isinstance(patch, dict):
        return obj == patch
    if not isinstance(obj, dict):
        # merging a dict patch onto a non-dict replaces it with the
        # patch applied to {} — a no-op only in degenerate cases the
        # full apply handles; report "changes" conservatively
        return False
    for k, v in patch.items():
        if v is None:
            if k in obj:
                return False
        elif isinstance(v, dict):
            cur = obj.get(k)
            if not isinstance(cur, dict) or not merge_patch_is_noop(cur, v):
                return False
        else:
            if k not in obj or obj[k] != v:
                return False
    return True


_DIRECTIVE = "$patch"
_DEL_PRIMITIVE = "$deleteFromPrimitiveList/"
_SET_ORDER = "$setElementOrder/"


def apply_strategic_merge_patch(
    obj: Any,
    patch: Any,
    field_name: str = "",
    kind: Optional[str] = None,
    path: tuple = (),
) -> Any:
    """Strategic merge with k8s semantics: dicts merge recursively,
    lists of objects merge by the field's patch-merge key (typed
    metadata via ``list_meta``; see STRATEGIC_META), other lists
    replace; ``$patch: replace|delete`` and ``$deleteFromPrimitiveList``
    directives honored (``$setElementOrder`` is accepted and ignored —
    element order follows merge order, a documented divergence).

    (reference consumes the same metadata through OpenAPI discovery,
    pkg/utils/patch/openapi.go:43-248)"""
    if isinstance(patch, dict) and isinstance(obj, dict):
        directive = patch.get(_DIRECTIVE)
        if directive == "replace":
            return {
                k: _copy_json(v) for k, v in patch.items() if k != _DIRECTIVE
            }
        if directive == "delete":
            return None  # caller (dict/list merge) removes the entry
        out = dict(obj)
        for k, v in patch.items():
            if k.startswith(_DEL_PRIMITIVE):
                target = k[len(_DEL_PRIMITIVE):]
                cur = out.get(target)
                if isinstance(cur, list) and isinstance(v, list):
                    out[target] = [x for x in cur if x not in v]
                continue
            if k.startswith(_SET_ORDER) or k == _DIRECTIVE:
                continue
            if v is None:
                out.pop(k, None)
                continue
            merged = (
                apply_strategic_merge_patch(out[k], v, k, kind, path + (k,))
                if k in out
                else _strip_directives(v)
            )
            if merged is None:
                out.pop(k, None)  # nested {"$patch": "delete"}
            else:
                out[k] = merged
        return out
    if isinstance(patch, list) and isinstance(obj, list):
        meta = list_meta(kind, path, field_name)
        if meta is None:
            return _strip_directives(patch)
        key = meta[1]
        if key is None:  # primitive set-merge (e.g. finalizers)
            merged = list(obj)
            for item in patch:
                if item not in merged:
                    merged.append(_copy_json(item))
            return merged
        merged = [_copy_json(i) for i in obj]
        index = {i.get(key): n for n, i in enumerate(merged) if isinstance(i, dict)}
        for item in patch:
            if isinstance(item, dict) and item.get(key) in index:
                n = index[item[key]]
                if item.get(_DIRECTIVE) == "delete":
                    # mark for removal, fix indexes after
                    merged[n] = None
                    continue
                merged[n] = apply_strategic_merge_patch(
                    merged[n], item, "", kind, path
                )
            elif isinstance(item, dict) and item.get(_DIRECTIVE) == "delete":
                continue  # delete of an absent element: no-op
            else:
                merged.append(_strip_directives(item))
                if isinstance(item, dict):
                    index[item.get(key)] = len(merged) - 1
        return [m for m in merged if m is not None]
    return _strip_directives(patch)


def _strip_directives(v: Any) -> Any:
    """Deep copy minus $patch/$setElementOrder bookkeeping keys (a new
    element carrying a directive must not store it)."""
    t = type(v)
    if t is dict:
        return {
            k: _strip_directives(x)
            for k, x in v.items()
            if k != _DIRECTIVE and not k.startswith(_SET_ORDER)
        }
    if t is list:
        return [_strip_directives(x) for x in v]
    return v


def apply_patch(obj: Any, data: Any, patch_type: str, kind: Optional[str] = None) -> Any:
    if patch_type == PATCH_JSON:
        if isinstance(data, (str, bytes)):
            data = json.loads(data)
        return apply_json_patch(obj, data)
    if isinstance(data, (str, bytes)):
        data = json.loads(data)
    if patch_type == PATCH_STRATEGIC:
        return apply_strategic_merge_patch(obj, data, kind=kind)
    return apply_merge_patch(obj, data)


def wrap_with_root(root: str, patch: Any) -> Any:
    """Wrap rendered patch data under a root field (merge-patch flavor),
    mirroring reference next.go:147-155 wrapMergePatchData."""
    if not root:
        return patch
    return {root: patch}


def wrap_json_patch_with_root(root: str, ops: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Prefix JSON-patch op paths with /root (reference next.go:157-170)."""
    if not root:
        return ops
    out = []
    for op in ops:
        op = dict(op)
        if "path" in op:
            op["path"] = f"/{root}{op['path']}"
        out.append(op)
    return out


def is_noop_patch(
    obj: Any, data: Any, patch_type: str, kind: Optional[str] = None
) -> bool:
    """Would applying this patch change the object?
    (reference controllers/utils.go:162-304 checkNeedPatch*)"""
    try:
        if patch_type == PATCH_MERGE:
            if isinstance(data, (str, bytes)):
                data = json.loads(data)
            return merge_patch_is_noop(obj, data)
        return apply_patch(obj, data, patch_type, kind=kind) == obj
    except (KeyError, IndexError, ValueError, TypeError):
        return False
