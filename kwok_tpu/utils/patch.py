"""Patch application: JSON Patch, JSON Merge Patch, strategic merge.

The reference applies stage effects as one of three patch types against
the apiserver (reference: pkg/utils/lifecycle/next.go:96-121,
pkg/kwok/controllers/utils.go:162-304 for no-op detection). Here the
store is in-process, so we implement the appliers directly:

- JSON Patch (RFC 6902) subset: add/remove/replace — what the finalizer
  ops emit (reference finalizers.go:32-116).
- JSON Merge Patch (RFC 7386): recursive merge, null deletes.
- Strategic merge: like merge patch, but lists of objects merge by a
  patch-merge key (k8s semantics). We carry a small key table for the
  types the simulator touches (containers/conditions by name/type);
  unknown lists replace wholesale, which matches the RFC 7386 fallback
  the reference gets for unregistered types.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

PATCH_JSON = "json"
PATCH_MERGE = "merge"
PATCH_STRATEGIC = "strategic"

# patch-merge keys for k8s list types (subset of the OpenAPI metadata the
# reference discovers dynamically via pkg/utils/patch/openapi.go:43-248).
_MERGE_KEYS = {
    "conditions": "type",
    "containers": "name",
    "initContainers": "name",
    "ephemeralContainers": "name",
    "containerStatuses": "name",
    "initContainerStatuses": "name",
    "ephemeralContainerStatuses": "name",
    "volumes": "name",
    "env": "name",
    "ports": "containerPort",
    "addresses": "type",
    # NOTE: node status.images, taints and tolerations are atomic lists in
    # k8s (no patchMergeKey) and must replace wholesale.
    "finalizers": None,  # set-merge
}


def apply_json_patch(obj: Any, ops: List[Dict[str, Any]]) -> Any:
    """Apply an RFC 6902 patch (add/remove/replace subset)."""
    out = _copy_json(obj)
    for op in ops:
        path = op["path"]
        parts = [p.replace("~1", "/").replace("~0", "~") for p in path.split("/")[1:]]
        action = op["op"]
        parent, last = _traverse(out, parts)
        if action == "add":
            value = _copy_json(op["value"])
            if isinstance(parent, list):
                if last == "-":
                    parent.append(value)
                else:
                    parent.insert(int(last), value)
            else:
                parent[last] = value
        elif action == "remove":
            if isinstance(parent, list):
                del parent[int(last)]
            else:
                if last not in parent:
                    raise KeyError(f"path not found: {path}")
                del parent[last]
        elif action == "replace":
            value = _copy_json(op["value"])
            if isinstance(parent, list):
                parent[int(last)] = value
            else:
                parent[last] = value
        else:
            raise ValueError(f"unsupported json patch op {action!r}")
    return out


def _traverse(obj: Any, parts: List[str]):
    cur = obj
    for p in parts[:-1]:
        if isinstance(cur, list):
            cur = cur[int(p)]
        else:
            cur = cur[p]
    return cur, parts[-1]


def copy_json(x: Any) -> Any:
    """Deep copy for JSON-shaped data (dict/list/scalars) — the ONE
    canonical implementation (cluster.store re-exports it).  Inputs are
    JSON by contract, so the general deepcopy machinery (memo dict,
    reductor dispatch) is pure overhead on the hot copy paths; this is
    ~3x faster and shares immutable leaves."""
    t = type(x)
    if t is dict:
        return {k: copy_json(v) for k, v in x.items()}
    if t is list:
        return [copy_json(v) for v in x]
    return x


_copy_json = copy_json


def apply_merge_patch(obj: Any, patch: Any) -> Any:
    """RFC 7386 JSON Merge Patch."""
    if not isinstance(patch, dict):
        return _copy_json(patch)
    if not isinstance(obj, dict):
        obj = {}
    out = dict(obj)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = apply_merge_patch(out.get(k), v)
    return out


def merge_patch_is_noop(obj: Any, patch: Any) -> bool:
    """Would this RFC 7386 merge patch leave ``obj`` unchanged?
    Allocation-free equivalent of ``apply_merge_patch(obj, patch) ==
    obj`` (the drain runs this once per dirty row)."""
    if not isinstance(patch, dict):
        return obj == patch
    if not isinstance(obj, dict):
        # merging a dict patch onto a non-dict replaces it with the
        # patch applied to {} — a no-op only in degenerate cases the
        # full apply handles; report "changes" conservatively
        return False
    for k, v in patch.items():
        if v is None:
            if k in obj:
                return False
        elif isinstance(v, dict):
            cur = obj.get(k)
            if not isinstance(cur, dict) or not merge_patch_is_noop(cur, v):
                return False
        else:
            if k not in obj or obj[k] != v:
                return False
    return True


def apply_strategic_merge_patch(obj: Any, patch: Any, field_name: str = "") -> Any:
    """Strategic merge: dicts merge recursively; lists of objects merge
    by the field's patch-merge key; other lists replace."""
    if isinstance(patch, dict) and isinstance(obj, dict):
        out = dict(obj)
        for k, v in patch.items():
            if v is None:
                out.pop(k, None)
            elif k in out:
                out[k] = apply_strategic_merge_patch(out[k], v, k)
            else:
                out[k] = _copy_json(v)
        return out
    if isinstance(patch, list) and isinstance(obj, list):
        key = _MERGE_KEYS.get(field_name)
        if key is None:
            if field_name in _MERGE_KEYS:  # set-merge (e.g. finalizers)
                merged = list(obj)
                for item in patch:
                    if item not in merged:
                        merged.append(_copy_json(item))
                return merged
            return _copy_json(patch)
        merged = [_copy_json(i) for i in obj]
        index = {i.get(key): n for n, i in enumerate(merged) if isinstance(i, dict)}
        for item in patch:
            if isinstance(item, dict) and item.get(key) in index:
                n = index[item[key]]
                merged[n] = apply_strategic_merge_patch(merged[n], item, "")
            else:
                merged.append(_copy_json(item))
                if isinstance(item, dict):
                    index[item.get(key)] = len(merged) - 1
        return merged
    return _copy_json(patch)


def apply_patch(obj: Any, data: Any, patch_type: str) -> Any:
    if patch_type == PATCH_JSON:
        if isinstance(data, (str, bytes)):
            data = json.loads(data)
        return apply_json_patch(obj, data)
    if isinstance(data, (str, bytes)):
        data = json.loads(data)
    if patch_type == PATCH_STRATEGIC:
        return apply_strategic_merge_patch(obj, data)
    return apply_merge_patch(obj, data)


def wrap_with_root(root: str, patch: Any) -> Any:
    """Wrap rendered patch data under a root field (merge-patch flavor),
    mirroring reference next.go:147-155 wrapMergePatchData."""
    if not root:
        return patch
    return {root: patch}


def wrap_json_patch_with_root(root: str, ops: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Prefix JSON-patch op paths with /root (reference next.go:157-170)."""
    if not root:
        return ops
    out = []
    for op in ops:
        op = dict(op)
        if "path" in op:
            op["path"] = f"/{root}{op['path']}"
        out.append(op)
    return out


def is_noop_patch(obj: Any, data: Any, patch_type: str) -> bool:
    """Would applying this patch change the object?
    (reference controllers/utils.go:162-304 checkNeedPatch*)"""
    try:
        if patch_type == PATCH_MERGE:
            if isinstance(data, (str, bytes)):
                data = json.loads(data)
            return merge_patch_is_noop(obj, data)
        return apply_patch(obj, data, patch_type) == obj
    except (KeyError, IndexError, ValueError, TypeError):
        return False
