"""Server-side apply: field sets, fieldsV1 codec, conflict detection.

The reference gets SSA for free from the real kube-apiserver its
clusters compose (reference runtime/binary/cluster.go:316-728); this
repo IS the apiserver, so the behavior lives here (VERDICT r03 #3).
A managedFields-lite model:

- a manager's ownership is the set of LEAF paths its applied
  configuration mentions (dicts recurse; scalars and lists are leaves —
  lists are atomic at this granularity, the same simplification the
  in-tree strategic-merge metadata makes for untyped CRs);
- ownership is encoded to/from the wire ``fieldsV1`` shape
  (``{"f:spec": {"f:replicas": {}}}``) so kubectl can read it back;
- applying removes the fields the manager owned before but no longer
  mentions (the "abandon" half of apply semantics);
- a second manager applying an owned field conflicts (HTTP 409 with
  FieldManagerConflict causes) unless ``force=true``, which transfers
  ownership — the exact kubectl retry contract.

Object identity and bookkeeping fields are exempt from ownership
(they are shared): apiVersion, kind, metadata.name/namespace/uid/
creationTimestamp/resourceVersion/generation/managedFields.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set, Tuple

Path = Tuple[str, ...]
FieldSet = Set[Path]

#: identity/bookkeeping paths never owned by a manager
EXEMPT: FieldSet = {
    ("apiVersion",),
    ("kind",),
    ("metadata", "name"),
    ("metadata", "namespace"),
    ("metadata", "uid"),
    ("metadata", "creationTimestamp"),
    ("metadata", "resourceVersion"),
    ("metadata", "generation"),
    ("metadata", "managedFields"),
}


def field_set(obj: dict) -> FieldSet:
    """Leaf paths an applied configuration claims."""
    out: FieldSet = set()

    def walk(node, prefix: Path) -> None:
        if isinstance(node, dict) and node:
            for k, v in node.items():
                walk(v, prefix + (str(k),))
        else:
            # scalars, lists, None, and empty dicts are leaves
            if prefix and prefix not in EXEMPT:
                out.add(prefix)

    walk(obj, ())
    return out


def to_fields_v1(fs: FieldSet) -> dict:
    """Encode a field set in the wire ``fieldsV1`` shape."""
    root: dict = {}
    for path in sorted(fs):
        cur = root
        for seg in path:
            cur = cur.setdefault(f"f:{seg}", {})
    return root


def from_fields_v1(node: dict, prefix: Path = ()) -> FieldSet:
    out: FieldSet = set()
    for k, v in (node or {}).items():
        if not k.startswith("f:"):
            continue  # "." / "k:{...}" entries from richer encoders
        path = prefix + (k[2:],)
        if isinstance(v, dict) and any(x.startswith("f:") for x in v):
            out |= from_fields_v1(v, path)
        else:
            out.add(path)
    return out


_MISSING = object()


def path_get(obj, path: Path):
    """Value at a leaf path; ``_MISSING`` when absent."""
    cur = obj
    for seg in path:
        if not isinstance(cur, dict):
            return _MISSING
        if seg not in cur:
            return _MISSING
        cur = cur[seg]
    return cur


def find_conflicts(
    desired: FieldSet,
    others: Iterable[Tuple[str, FieldSet]],
    applied: dict,
    current: dict,
) -> List[Tuple[str, Path, Path]]:
    """(manager, their_path, our_path) triples where another manager
    owns a desired leaf AND the applied value differs from the current
    one — equal values become co-ownership, not a conflict (upstream
    SSA semantics).  Ancestor/descendant overlap (owning ``spec.foo``
    vs claiming ``spec.foo.bar``) is structural and always conflicts.

    Both paths are reported because they serve different consumers: a
    forced apply dispossesses the OTHER manager's entry (their_path —
    the one actually present in their field set), while the Status
    cause names what the APPLIER claimed (our_path).  Collapsing to
    the longer of the two left forced applies unable to strip an
    ancestor claim (ADVICE r04 #2)."""
    out: List[Tuple[str, Path, Path]] = []
    for manager, fs in others:
        hits = set()
        for p in fs & desired:
            if path_get(applied, p) != path_get(current, p):
                hits.add((p, p))
        for theirs in fs:
            for ours in desired:
                if theirs == ours:
                    continue
                shorter, longer = sorted((theirs, ours), key=len)
                if longer[: len(shorter)] == shorter:
                    hits.add((theirs, ours))
        for theirs, ours in sorted(hits):
            out.append((manager, theirs, ours))
    return out


def remove_path(obj: dict, path: Path) -> None:
    """Delete a leaf path in place, pruning emptied parent dicts."""
    parents: List[Tuple[dict, str]] = []
    cur = obj
    for seg in path[:-1]:
        nxt = cur.get(seg)
        if not isinstance(nxt, dict):
            return
        parents.append((cur, seg))
        cur = nxt
    cur.pop(path[-1], None)
    for parent, seg in reversed(parents):
        child = parent.get(seg)
        if isinstance(child, dict) and not child:
            del parent[seg]
        else:
            break


def dotted(path: Path) -> str:
    """k8s Status cause field syntax: ``.spec.replicas``."""
    return "." + ".".join(path)
