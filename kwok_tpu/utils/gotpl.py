"""gotpl — a Go text/template subset renderer.

The reference renders Stage patch templates with Go's text/template
plus sprig and kwok-specific funcs (reference: pkg/utils/gotpl/
{renderer,funcs}.go). This module implements the subset of the template
language that the entire upstream stage vocabulary uses:

- actions: ``{{ expr }}``, ``{{ $v := expr }}``, ``{{ if }}/{{ else if }}/
  {{ else }}/{{ end }}``, ``{{ range }}`` (incl. ``$i, $v :=`` form),
  ``{{ with }}/{{ else }}/{{ end }}``, trim markers ``{{-``/``-}}``;
- pipelines ``a | F``, function calls with args, parenthesized
  sub-expressions, ``$`` for the root context;
- builtins: or, and, eq, ne, not, index, printf, len;
- sprig-isms used by stages/charts: dict, default;
- kwok funcs (funcs.go:42-117): Quote, Now, StartTime, YAML, Version,
  NodeConditions; environment funcs NodeIP/NodeName/NodePort/
  NodeIPWith/PodIPWith are injected per controller
  (reference node_controller.go:521-531, pod_controller.go:559-615).

Divergence note: field access on a missing map key propagates nil
rather than erroring; nil renders as ``<no value>``. The upstream
templates always guard nilable chains with or/with, so rendered output
is identical for the stage vocabulary.

Rendered output is YAML; ``render_to_json`` mirrors renderer.go:110
ToJSON by YAML-parsing the rendered text.
"""

from __future__ import annotations

import datetime
import json
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import yaml

from kwok_tpu import __version__ as KWOK_TPU_VERSION


class TemplateError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Default funcs (reference funcs.go:42-117)
# ---------------------------------------------------------------------------

# The canonical five node conditions (funcs.go:85-116).
NODE_CONDITIONS: List[Dict[str, str]] = [
    {
        "type": "Ready",
        "status": "True",
        "reason": "KubeletReady",
        "message": "kubelet is posting ready status",
    },
    {
        "type": "MemoryPressure",
        "status": "False",
        "reason": "KubeletHasSufficientMemory",
        "message": "kubelet has sufficient memory available",
    },
    {
        "type": "DiskPressure",
        "status": "False",
        "reason": "KubeletHasNoDiskPressure",
        "message": "kubelet has no disk pressure",
    },
    {
        "type": "PIDPressure",
        "status": "False",
        "reason": "KubeletHasSufficientPID",
        "message": "kubelet has sufficient PID available",
    },
    {
        "type": "NetworkUnavailable",
        "status": "False",
        "reason": "RouteCreated",
        "message": "RouteController created a route",
    },
]


def _fn_quote(s: Any) -> str:
    data = json.dumps(s, separators=(",", ":"))
    if data.startswith('"'):
        return data
    return json.dumps(data)


def _go_now() -> str:
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="microseconds")
        .replace("+00:00", "Z")
    )


_START_TIME = _go_now()


def _fn_yaml(value: Any, indent: int = 0) -> str:
    data = yaml.safe_dump(value, default_flow_style=False, sort_keys=False)
    if indent and indent > 0:
        pad = " " * (indent * 2)
        data = ("\n" + data).replace("\n", "\n" + pad)
    return data


def _fn_printf(fmt: str, *args: Any) -> str:
    # Go verbs -> Python: %v/%s -> %s, %d -> %d, %q -> quoted
    out = []
    i = 0
    ai = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            verb = fmt[i + 1]
            if verb == "%":
                out.append("%")
            elif verb in "vs":
                out.append(_to_display(args[ai]))
                ai += 1
            elif verb == "d":
                out.append(str(int(args[ai])))
                ai += 1
            elif verb == "q":
                out.append(_fn_quote(args[ai]))
                ai += 1
            else:
                raise TemplateError(f"unsupported printf verb %{verb}")
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _fn_dict(*pairs: Any) -> Dict[Any, Any]:
    if len(pairs) % 2 != 0:
        raise TemplateError("dict requires an even number of arguments")
    return {pairs[i]: pairs[i + 1] for i in range(0, len(pairs), 2)}


def _is_true(v: Any) -> bool:
    """Go template truthiness: zero values are false."""
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v != 0
    if isinstance(v, (str, list, dict, tuple)):
        return len(v) > 0
    return True


def _fn_index(col: Any, *keys: Any) -> Any:
    cur = col
    for k in keys:
        if cur is None:
            return None
        if isinstance(cur, dict):
            cur = cur.get(k)
        elif isinstance(cur, (list, tuple, str)):
            i = int(k)
            if i < 0 or i >= len(cur):
                raise TemplateError(f"index out of range: {i}")
            cur = cur[i]
        else:
            raise TemplateError(f"can't index item of type {type(cur).__name__}")
    return cur


def _go_eq(a: Any, *rest: Any) -> bool:
    return any(_json_eq(a, b) for b in rest)


def _json_eq(a: Any, b: Any) -> bool:
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b


_SPRIG_TABLE: Dict[str, Callable] = {}


def default_funcs() -> Dict[str, Callable]:
    # sprig at large first (reference funcs.go:42-117 pulls in all of
    # sprig.TxtFuncMap); the engine's own builtins and kwok funcs
    # override on name clashes (quote/default keep kwok semantics).
    # The 165-entry sprig table is built once — default_funcs() is on
    # the per-render path, and rebuilding the closures per call was a
    # measured ~34us tax.
    if not _SPRIG_TABLE:
        from kwok_tpu.utils.sprig import sprig_funcs

        _SPRIG_TABLE.update(sprig_funcs())
    funcs = dict(_SPRIG_TABLE)
    funcs.update(
        {
            "Quote": _fn_quote,
            "Now": _go_now,
            "StartTime": lambda: _START_TIME,
            "YAML": _fn_yaml,
            "Version": lambda: KWOK_TPU_VERSION,
            "NodeConditions": lambda: [dict(c) for c in NODE_CONDITIONS],
            # builtins
            "printf": _fn_printf,
            "index": _fn_index,
            "len": lambda v: len(v) if v is not None else 0,
            "not": lambda v: not _is_true(v),
            "eq": _go_eq,
            "ne": lambda a, b: not _json_eq(a, b),
            # sprig-isms with kwok-pinned semantics
            "dict": _fn_dict,
            "default": lambda d, v=None: v if _is_true(v) else d,
        }
    )
    return funcs


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

_ACTION_RE = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.DOTALL)

_STRING_ESCAPES = {
    "n": "\n",
    "t": "\t",
    "r": "\r",
    "a": "\a",
    "b": "\b",
    "f": "\f",
    "v": "\v",
    '"': '"',
    "'": "'",
    "\\": "\\",
}


def _unescape_string(body: str) -> str:
    """Go string-literal escapes, unicode-safe (no byte round-trip)."""

    def repl(m: "re.Match[str]") -> str:
        c = m.group(1)
        if c[0] in "ux":
            return chr(int(c[1:], 16))
        return _STRING_ESCAPES.get(c, c)

    return re.sub(r"\\(u[0-9a-fA-F]{4}|x[0-9a-fA-F]{2}|.)", repl, body)


class _Node:
    pass


class _Text(_Node):
    def __init__(self, text: str):
        self.text = text


class _Output(_Node):
    def __init__(self, pipe):
        self.pipe = pipe


class _Assign(_Node):
    def __init__(self, name: str, pipe):
        self.name = name
        self.pipe = pipe


class _If(_Node):
    def __init__(self, branches, else_body):
        self.branches = branches  # list of (pipe, body)
        self.else_body = else_body


class _Range(_Node):
    def __init__(self, index_var, value_var, pipe, body, else_body):
        self.index_var = index_var
        self.value_var = value_var
        self.pipe = pipe
        self.body = body
        self.else_body = else_body


class _With(_Node):
    def __init__(self, pipe, body, else_body):
        self.pipe = pipe
        self.body = body
        self.else_body = else_body


_EXPR_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<raw>`(?:[^`])*`)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<op>\||\(|\)|:=|=)
  | (?P<var>\$[A-Za-z0-9_]*)
  | (?P<field>\.[A-Za-z0-9_.]*)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<comma>,)
    """,
    re.VERBOSE,
)


def _tokenize_expr(src: str) -> List[Tuple[str, str]]:
    tokens = []
    pos = 0
    while pos < len(src):
        m = _EXPR_TOKEN_RE.match(src, pos)
        if m is None:
            raise TemplateError(f"bad token at {src[pos:]!r}")
        start = m.start()
        pos = m.end()
        if m.lastgroup != "ws":
            tokens.append((m.lastgroup, m.group(), start))
    return tokens


# Pipeline AST: ("pipe", [command,...]); command: ("call", [term,...])
# term: ("field", path_list) | ("var", name, path_list) | ("lit", v) |
#        ("fn", name) | ("pipe", ...)


class _ExprParser:
    def __init__(self, tokens, src):
        self.toks = tokens
        self.src = src
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self):
        t = self.peek()
        if t is None:
            raise TemplateError(f"unexpected end of action {self.src!r}")
        self.i += 1
        return t

    def parse_pipeline(self):
        cmds = [self.parse_command()]
        while self.peek() is not None and self.peek()[1] == "|":
            self.next()
            cmds.append(self.parse_command())
        return ("pipe", cmds)

    def parse_command(self):
        terms = []
        while True:
            t = self.peek()
            if t is None or t[1] in ("|", ")"):
                break
            terms.append(self.parse_term())
        if not terms:
            raise TemplateError(f"empty command in {self.src!r}")
        return ("call", terms)

    def parse_term(self):
        tok = self.next()
        kind, text = tok[0], tok[1]
        if text == "(":
            pipe = self.parse_pipeline()
            t = self.next()
            if t[1] != ")":
                raise TemplateError(f"expected ) in {self.src!r}")
            nxt = self.peek()
            if (
                nxt is not None
                and nxt[0] == "field"
                and len(nxt) > 2
                and len(t) > 2
                and nxt[2] == t[2] + 1
            ):
                # Go templates allow field access on a parenthesized
                # pipeline, but ONLY when adjacent: `(split "$" .s)._1`
                # is a suffix, `(f .a) .b` is an argument
                self.next()
                return ("suffix", pipe, [p for p in nxt[1].split(".") if p])
            return pipe
        if kind == "field":
            path = [p for p in text.split(".") if p]
            return ("field", path)
        if kind == "var":
            name = text
            path: List[str] = []
            t = self.peek()
            if t is not None and t[0] == "field":
                self.next()
                path = [p for p in t[1].split(".") if p]
            return ("var", name, path)
        if kind == "string":
            return ("lit", _unescape_string(text[1:-1]))
        if kind == "raw":
            return ("lit", text[1:-1])
        if kind == "number":
            return ("lit", float(text) if "." in text else int(text))
        if kind == "ident":
            if text == "true":
                return ("lit", True)
            if text == "false":
                return ("lit", False)
            if text == "nil":
                return ("lit", None)
            return ("fn", text)
        raise TemplateError(f"unexpected token {text!r} in {self.src!r}")


def _split_actions(src: str) -> List[Tuple[str, str]]:
    """Split template into ("text", s) and ("action", body) chunks,
    applying {{- and -}} whitespace trimming."""
    chunks: List[Tuple[str, str]] = []
    pos = 0
    for m in _ACTION_RE.finditer(src):
        text = src[pos : m.start()]
        raw = m.group(0)
        if raw.startswith("{{-"):
            text = text.rstrip()
        chunks.append(("text", text))
        chunks.append(("action", m.group(1)))
        pos = m.end()
        if raw.endswith("-}}"):
            rest = src[pos:]
            stripped = rest.lstrip()
            pos += len(rest) - len(stripped)
    chunks.append(("text", src[pos:]))
    return [c for c in chunks if not (c[0] == "text" and c[1] == "")]


_ASSIGN_RE = re.compile(r"^(\$[A-Za-z0-9_]*)\s*(:=|=)\s*(.*)$", re.DOTALL)
_RANGE_VARS_RE = re.compile(
    r"^(\$[A-Za-z0-9_]*)\s*(?:,\s*(\$[A-Za-z0-9_]*)\s*)?:=\s*(.*)$", re.DOTALL
)


class Template:
    def __init__(self, src: str):
        self.src = src
        chunks = _split_actions(src)
        self.nodes, rest = self._parse_block(chunks, 0, top=True)
        if rest != len(chunks):
            raise TemplateError("unbalanced end in template")

    def _parse_pipe(self, body: str):
        p = _ExprParser(_tokenize_expr(body), body)
        pipe = p.parse_pipeline()
        if p.peek() is not None:
            raise TemplateError(f"trailing tokens in {body!r}")
        return pipe

    def _parse_block(self, chunks, i, top=False, stop=("end",)):
        nodes: List[_Node] = []
        while i < len(chunks):
            kind, body = chunks[i]
            if kind == "text":
                nodes.append(_Text(body))
                i += 1
                continue
            word = body.split(None, 1)[0] if body.strip() else ""
            if word in ("end", "else") and not top:
                return nodes, i
            if word == "if":
                branches = []
                cond = self._parse_pipe(body[2:].strip())
                inner, i = self._parse_block(chunks, i + 1)
                branches.append((cond, inner))
                else_body: List[_Node] = []
                while True:
                    kind2, body2 = chunks[i]
                    w2 = body2.split(None, 1)[0]
                    if w2 == "else":
                        rest = body2[4:].strip()
                        if rest.startswith("if"):
                            cond2 = self._parse_pipe(rest[2:].strip())
                            inner2, i = self._parse_block(chunks, i + 1)
                            branches.append((cond2, inner2))
                            continue
                        else_body, i = self._parse_block(chunks, i + 1)
                        w3 = chunks[i][1].split(None, 1)[0]
                        if w3 != "end":
                            raise TemplateError("expected end after else")
                        i += 1
                        break
                    if w2 == "end":
                        i += 1
                        break
                    raise TemplateError(f"unexpected {w2!r} in if")
                nodes.append(_If(branches, else_body))
                continue
            if word == "range":
                expr = body[5:].strip()
                index_var = value_var = None
                m = _RANGE_VARS_RE.match(expr)
                if m:
                    if m.group(2) is not None:
                        index_var, value_var = m.group(1), m.group(2)
                    else:
                        value_var = m.group(1)
                    expr = m.group(3)
                pipe = self._parse_pipe(expr)
                inner, i = self._parse_block(chunks, i + 1)
                else_body = []
                w2 = chunks[i][1].split(None, 1)[0]
                if w2 == "else":
                    else_body, i = self._parse_block(chunks, i + 1)
                    w2 = chunks[i][1].split(None, 1)[0]
                if w2 != "end":
                    raise TemplateError("expected end after range")
                i += 1
                nodes.append(_Range(index_var, value_var, pipe, inner, else_body))
                continue
            if word == "with":
                pipe = self._parse_pipe(body[4:].strip())
                inner, i = self._parse_block(chunks, i + 1)
                else_body = []
                w2 = chunks[i][1].split(None, 1)[0]
                if w2 == "else":
                    else_body, i = self._parse_block(chunks, i + 1)
                    w2 = chunks[i][1].split(None, 1)[0]
                if w2 != "end":
                    raise TemplateError("expected end after with")
                i += 1
                nodes.append(_With(pipe, inner, else_body))
                continue
            m = _ASSIGN_RE.match(body)
            if m:
                nodes.append(_Assign(m.group(1), self._parse_pipe(m.group(3))))
                i += 1
                continue
            if word in ("end", "else"):
                raise TemplateError(f"unexpected {word!r} at top level")
            nodes.append(_Output(self._parse_pipe(body)))
            i += 1
        if not top:
            raise TemplateError("missing end")
        return nodes, i

    # -- evaluation ---------------------------------------------------------

    def render(self, data: Any, funcs: Optional[Dict[str, Callable]] = None) -> str:
        env = default_funcs()
        if funcs:
            env.update(funcs)
        out: List[str] = []
        variables: Dict[str, Any] = {"$": data}
        self._exec(self.nodes, data, variables, env, out)
        return "".join(out)

    def _exec(self, nodes, dot, variables, env, out):
        for node in nodes:
            if isinstance(node, _Text):
                out.append(node.text)
            elif isinstance(node, _Output):
                v = self._eval_pipe(node.pipe, dot, variables, env)
                out.append(_to_display(v))
            elif isinstance(node, _Assign):
                variables[node.name] = self._eval_pipe(node.pipe, dot, variables, env)
            elif isinstance(node, _If):
                done = False
                for cond, body in node.branches:
                    if _is_true(self._eval_pipe(cond, dot, variables, env)):
                        self._exec(body, dot, variables, env, out)
                        done = True
                        break
                if not done:
                    self._exec(node.else_body, dot, variables, env, out)
            elif isinstance(node, _With):
                v = self._eval_pipe(node.pipe, dot, variables, env)
                if _is_true(v):
                    self._exec(node.body, v, variables, env, out)
                else:
                    self._exec(node.else_body, dot, variables, env, out)
            elif isinstance(node, _Range):
                v = self._eval_pipe(node.pipe, dot, variables, env)
                items: List[Tuple[Any, Any]] = []
                if isinstance(v, dict):
                    items = [(k, v[k]) for k in sorted(v)]
                elif isinstance(v, (list, tuple)):
                    items = list(enumerate(v))
                if items:
                    for k, item in items:
                        scope = dict(variables)
                        if node.index_var and node.value_var:
                            scope[node.index_var] = k
                            scope[node.value_var] = item
                        elif node.value_var:
                            scope[node.value_var] = item
                        self._exec(node.body, item, scope, env, out)
                else:
                    self._exec(node.else_body, dot, variables, env, out)
            else:  # pragma: no cover
                raise TemplateError(f"unknown node {node!r}")

    def _eval_pipe(self, pipe, dot, variables, env):
        _, cmds = pipe
        value = _NO_VALUE
        for cmd in cmds:
            value = self._eval_command(cmd, dot, variables, env, value)
        return value

    def _eval_command(self, cmd, dot, variables, env, piped):
        _, terms = cmd
        head = terms[0]
        args = [self._eval_term(t, dot, variables, env) for t in terms[1:]]
        if piped is not _NO_VALUE:
            args.append(piped)
        if head[0] == "fn":
            name = head[1]
            if name == "or":
                for a in args:
                    if _is_true(a):
                        return a
                return args[-1] if args else None
            if name == "and":
                last = None
                for a in args:
                    last = a
                    if not _is_true(a):
                        return a
                return last
            fn = env.get(name)
            if fn is None:
                raise TemplateError(f"function {name!r} not defined")
            return fn(*args)
        value = self._eval_term(head, dot, variables, env)
        if args:
            if callable(value):
                return value(*args)
            raise TemplateError(f"can't give arguments to non-function {head!r}")
        return value

    def _eval_term(self, term, dot, variables, env):
        kind = term[0]
        if kind == "lit":
            return term[1]
        if kind == "field":
            return _navigate(dot, term[1])
        if kind == "var":
            name, path = term[1], term[2]
            if name == "$":
                base = variables["$"]
            else:
                if name not in variables:
                    raise TemplateError(f"undefined variable {name}")
                base = variables[name]
            return _navigate(base, path)
        if kind == "pipe":
            return self._eval_pipe(term, dot, variables, env)
        if kind == "suffix":
            return _navigate(
                self._eval_pipe(term[1], dot, variables, env), term[2]
            )
        if kind == "fn":
            name = term[1]
            if name == "or":
                return None
            fn = env.get(name)
            if fn is None:
                raise TemplateError(f"function {name!r} not defined")
            return fn()
        raise TemplateError(f"unknown term {term!r}")


class _NoValue:
    def __repr__(self):
        return "<no value>"


_NO_VALUE = _NoValue()


def _navigate(value: Any, path: List[str]) -> Any:
    cur = value
    for p in path:
        if cur is None:
            return None
        if isinstance(cur, dict):
            cur = cur.get(p)
        else:
            return None
    return cur


def _to_display(v: Any) -> str:
    if v is None or v is _NO_VALUE:
        return "<no value>"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def template_read_paths(tpl: "Template") -> set:
    """Conservative static analysis: the set of root-relative object
    paths a template's output can depend on, as tuples of field names.

    Used by the stage compiler to key FSM exploration states: two objects
    agreeing on all read paths render identically (template funcs are
    pure). Unused variable assignments are pruned to a fixpoint first
    (e.g. the zoo's never-referenced ``$origin``/``$root`` bindings), so
    a dead ``index $root.status.containerStatuses $index`` does not drag
    the whole status in. A bare reference to a variable bound to ``.``
    conservatively returns the root path ``()`` (reads everything).

    Reads inside range/with bodies resolve relative to the body's
    source path, which is itself collected — subtree projection
    subsumes them — so only root-context paths and variable-rooted
    paths need recording.
    """
    # 1. count variable uses (excluding their own assignment)
    uses: Dict[str, int] = {}

    def count_pipe(pipe):
        _, cmds = pipe
        for _, terms in cmds:
            for t in terms:
                if t[0] == "var" and t[1] != "$":
                    uses[t[1]] = uses.get(t[1], 0) + 1
                elif t[0] == "pipe":
                    count_pipe(t)
                elif t[0] == "suffix":
                    count_pipe(t[1])

    def count_nodes(nodes):
        for n in nodes:
            if isinstance(n, _Output):
                count_pipe(n.pipe)
            elif isinstance(n, _Assign):
                count_pipe(n.pipe)
            elif isinstance(n, _If):
                for cond, body in n.branches:
                    count_pipe(cond)
                    count_nodes(body)
                count_nodes(n.else_body)
            elif isinstance(n, (_Range, _With)):
                count_pipe(n.pipe)
                count_nodes(n.body)
                count_nodes(n.else_body)

    count_nodes(tpl.nodes)

    # 2. prune assignments of unused variables to a fixpoint
    pruned = dict(uses)
    changed = True
    live_assigns: Dict[str, Any] = {}

    def assigns_of(nodes, out):
        for n in nodes:
            if isinstance(n, _Assign):
                out.setdefault(n.name, []).append(n.pipe)
            elif isinstance(n, _If):
                for _, body in n.branches:
                    assigns_of(body, out)
                assigns_of(n.else_body, out)
            elif isinstance(n, (_Range, _With)):
                assigns_of(n.body, out)
                assigns_of(n.else_body, out)

    all_assigns: Dict[str, list] = {}
    assigns_of(tpl.nodes, all_assigns)
    def count_one(pipe, acc):
        _, cmds = pipe
        for _, terms in cmds:
            for t in terms:
                if t[0] == "var" and t[1] != "$":
                    acc[t[1]] = acc.get(t[1], 0) + 1
                elif t[0] == "pipe":
                    count_one(t, acc)
                elif t[0] == "suffix":
                    count_one(t[1], acc)

    while changed:
        changed = False
        for name in list(all_assigns):
            if pruned.get(name, 0) == 0:
                removed: Dict[str, int] = {}
                for p in all_assigns[name]:
                    count_one(p, removed)
                del all_assigns[name]
                changed = True
                for k, v in removed.items():
                    if pruned.get(k, 0) > 0:
                        pruned[k] = pruned[k] - v
                break

    live_vars = {k for k, v in pruned.items() if v > 0} | set(all_assigns)

    # 3. collect paths: root-context Path terms + live var sources/derefs
    paths: set = set()
    var_sources: Dict[str, Any] = {}  # var -> path tuple or None (opaque)

    def collect_pipe(pipe, root_ctx):
        _, cmds = pipe
        for _, terms in cmds:
            for t in terms:
                if t[0] == "field":
                    if root_ctx:
                        paths.add(tuple(t[1]))
                elif t[0] == "var":
                    name, sub = t[1], tuple(t[2])
                    if name == "$":
                        paths.add(sub)
                    else:
                        src = var_sources.get(name)
                        if src is not None:
                            paths.add(src + sub)
                        elif name in live_vars and name not in var_sources:
                            pass  # range/with-bound: subsumed by source path
                elif t[0] == "pipe":
                    collect_pipe(t, root_ctx)
                elif t[0] == "suffix":
                    collect_pipe(t[1], root_ctx)

    def pipe_as_path(pipe):
        """If a pipeline is a bare path term, return its tuple."""
        _, cmds = pipe
        if len(cmds) == 1 and len(cmds[0][1]) == 1:
            t = cmds[0][1][0]
            if t[0] == "field":
                return tuple(t[1])
        return None

    def walk(nodes, root_ctx):
        for n in nodes:
            if isinstance(n, _Output):
                collect_pipe(n.pipe, root_ctx)
            elif isinstance(n, _Assign):
                if n.name not in all_assigns:
                    continue  # pruned dead assignment
                collect_pipe(n.pipe, root_ctx)
                if root_ctx:
                    var_sources[n.name] = pipe_as_path(n.pipe)
            elif isinstance(n, _If):
                for cond, body in n.branches:
                    collect_pipe(cond, root_ctx)
                    walk(body, root_ctx)
                walk(n.else_body, root_ctx)
            elif isinstance(n, (_Range, _With)):
                collect_pipe(n.pipe, root_ctx)
                # body reads are relative to the (collected) source subtree
                walk(n.body, False)
                walk(n.else_body, root_ctx)

    walk(tpl.nodes, True)
    return paths


class Renderer:
    """Template renderer with an extra func environment
    (reference gotpl/renderer.go:50-118)."""

    def __init__(self, funcs: Optional[Dict[str, Callable]] = None):
        self.funcs = dict(funcs or {})
        self._cache: Dict[str, Template] = {}

    def render(self, template: str, data: Any, extra_funcs: Optional[Dict] = None) -> str:
        tpl = self._cache.get(template)
        if tpl is None:
            tpl = Template(template)
            self._cache[template] = tpl
        env = dict(self.funcs)
        if extra_funcs:
            env.update(extra_funcs)
        return tpl.render(data, env)

    def render_to_json(self, template: str, data: Any, extra_funcs: Optional[Dict] = None) -> Any:
        """Render, then parse the YAML output to a JSON-standard value
        (reference renderer.go:110 ToJSON)."""
        text = self.render(template, data, extra_funcs)
        return yaml.load(text, Loader=_YAML_LOADER)


# the rendered-patch parse is the drain hot path: libyaml's C loader is
# ~20x faster than the pure-Python scanner (bench e2e profile)
_YAML_LOADER = getattr(yaml, "CSafeLoader", yaml.SafeLoader)
