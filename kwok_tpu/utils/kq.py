"""kq — a small jq-subset query engine over JSON-standard objects.

The reference drives all Stage selector matchExpressions, weightFrom and
durationFrom expressions through gojq (reference: pkg/utils/expression/query.go:25-88).
The stage vocabulary only ever uses a narrow jq subset — field paths,
string indexing, array iteration, `select(...)` with equality — so kq
implements exactly that subset with gojq-compatible behavior:

- results are a stream; `null` outputs are dropped from the result list
  (reference: query.go:60-66);
- any evaluation error aborts the query and yields an *empty* result
  (gojq errors are swallowed: query.go:57-59 returns nil, nil);
- iterating a non-iterable (including null/missing) is an error;
- field access on null/missing yields null, not an error.

Queries that fall outside the subset raise ``KqCompileError`` at parse
time; callers route those objects to the host slow path.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Iterator, List, Optional, Sequence, Tuple


class KqCompileError(ValueError):
    """The query is not valid kq (parse/compile-time)."""


class _KqRuntimeError(Exception):
    """Evaluation error; swallowed by Query.execute (gojq parity)."""


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<op>==|!=|\||\(|\)|\[|\]|\.|,)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    """,
    re.VERBOSE,
)


def _tokenize(src: str) -> List[Tuple[str, str]]:
    tokens: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise KqCompileError(f"unexpected character {src[pos]!r} at {pos} in {src!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        tokens.append((kind, m.group()))
    return tokens


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Field:
    name: str


@dataclass(frozen=True)
class Iterate:
    pass


@dataclass(frozen=True)
class Path:
    """A `.a.b["c"].[]`-style navigation; ops are Field/Iterate."""

    ops: Tuple[Any, ...]


@dataclass(frozen=True)
class Literal:
    value: Any


@dataclass(frozen=True)
class Compare:
    left: Any
    op: str  # "==" or "!="
    right: Any


@dataclass(frozen=True)
class Select:
    cond: Any


@dataclass(frozen=True)
class Pipe:
    stages: Tuple[Any, ...]


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]], src: str):
        self.tokens = tokens
        self.src = src
        self.i = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def next(self) -> Tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise KqCompileError(f"unexpected end of query: {self.src!r}")
        self.i += 1
        return tok

    def expect(self, text: str) -> None:
        tok = self.next()
        if tok[1] != text:
            raise KqCompileError(f"expected {text!r}, got {tok[1]!r} in {self.src!r}")

    def parse_query(self) -> Any:
        node = self.parse_pipe()
        if self.peek() is not None:
            raise KqCompileError(f"trailing tokens in {self.src!r}")
        return node

    def parse_pipe(self) -> Any:
        stages = [self.parse_term()]
        while self.peek() is not None and self.peek()[1] == "|":
            self.next()
            stages.append(self.parse_term())
        if len(stages) == 1:
            return stages[0]
        return Pipe(tuple(stages))

    def parse_term(self) -> Any:
        """One pipe stage: a path, select(...), or a literal — optionally
        followed by an ==/!= comparison."""
        node = self.parse_primary()
        tok = self.peek()
        if tok is not None and tok[1] in ("==", "!="):
            op = self.next()[1]
            right = self.parse_primary()
            node = Compare(node, op, right)
        return node

    def parse_primary(self) -> Any:
        tok = self.peek()
        if tok is None:
            raise KqCompileError(f"unexpected end of query: {self.src!r}")
        kind, text = tok
        if text == ".":
            return self.parse_path()
        if text == "(":
            self.next()
            node = self.parse_pipe()
            self.expect(")")
            return node
        if kind == "string":
            self.next()
            return Literal(_unquote(text))
        if kind == "number":
            self.next()
            return Literal(float(text) if "." in text else int(text))
        if kind == "ident":
            if text == "select":
                self.next()
                self.expect("(")
                cond = self.parse_pipe()
                self.expect(")")
                return Select(cond)
            if text in ("true", "false", "null"):
                self.next()
                return Literal({"true": True, "false": False, "null": None}[text])
            raise KqCompileError(f"unsupported function {text!r} in {self.src!r}")
        raise KqCompileError(f"unexpected token {text!r} in {self.src!r}")

    def parse_path(self) -> Path:
        ops: List[Any] = []
        self.expect(".")
        while True:
            tok = self.peek()
            if tok is None:
                break
            kind, text = tok
            if kind == "ident":
                self.next()
                ops.append(Field(text))
            elif text == "[":
                self.next()
                nxt = self.next()
                if nxt[1] == "]":
                    ops.append(Iterate())
                elif nxt[0] == "string":
                    self.expect("]")
                    ops.append(Field(_unquote(nxt[1])))
                else:
                    raise KqCompileError(
                        f"unsupported index {nxt[1]!r} in {self.src!r}"
                    )
            elif text == ".":
                # `.a.b` / `.a.[]` — separator between segments
                self.next()
                nxt = self.peek()
                if nxt is None or (nxt[0] != "ident" and nxt[1] != "["):
                    raise KqCompileError(f"dangling '.' in {self.src!r}")
            else:
                break
        return Path(tuple(ops))


def _unquote(s: str) -> str:
    body = s[1:-1]
    return body.replace('\\"', '"').replace("\\\\", "\\")


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


def _truthy(v: Any) -> bool:
    # jq: false and null are falsy; everything else truthy.
    return v is not None and v is not False


def _eval(node: Any, value: Any) -> Iterator[Any]:
    if isinstance(node, Literal):
        yield node.value
    elif isinstance(node, Path):
        yield from _eval_path(node.ops, 0, value)
    elif isinstance(node, Pipe):
        yield from _eval_pipe(node.stages, 0, value)
    elif isinstance(node, Select):
        for out in _eval(node.cond, value):
            if _truthy(out):
                yield value
    elif isinstance(node, Compare):
        for lv in _eval(node.left, value):
            for rv in _eval(node.right, value):
                eq = _json_equal(lv, rv)
                yield eq if node.op == "==" else not eq
    else:  # pragma: no cover
        raise _KqRuntimeError(f"unknown node {node!r}")


def _eval_pipe(stages: Sequence[Any], i: int, value: Any) -> Iterator[Any]:
    if i == len(stages):
        yield value
        return
    for out in _eval(stages[i], value):
        yield from _eval_pipe(stages, i + 1, out)


def _eval_path(ops: Sequence[Any], i: int, value: Any) -> Iterator[Any]:
    if i == len(ops):
        yield value
        return
    op = ops[i]
    if isinstance(op, Field):
        if value is None:
            yield from _eval_path(ops, i + 1, None)
        elif isinstance(value, dict):
            yield from _eval_path(ops, i + 1, value.get(op.name))
        else:
            raise _KqRuntimeError(
                f"cannot index {type(value).__name__} with {op.name!r}"
            )
    else:  # Iterate
        if isinstance(value, list):
            for item in value:
                yield from _eval_path(ops, i + 1, item)
        elif isinstance(value, dict):
            for item in value.values():
                yield from _eval_path(ops, i + 1, item)
        else:
            raise _KqRuntimeError(f"cannot iterate over {type(value).__name__}")


def _json_equal(a: Any, b: Any) -> bool:
    # Avoid bool == int coercion surprises (jq: true != 1).
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b


class Query:
    """Compiled kq query (reference: expression.Query, query.go:28-49)."""

    def __init__(self, src: str):
        self.src = src
        self._ast = _Parser(_tokenize(src), src).parse_query()

    def execute(self, value: Any) -> Optional[List[Any]]:
        """Run the query; returns the non-null output stream.

        Mirrors reference query.go:48-68: errors swallow the whole result
        (returns None), null outputs are dropped.
        """
        out: List[Any] = []
        try:
            for v in _eval(self._ast, value):
                if v is None:
                    continue
                out.append(v)
        except (_KqRuntimeError, RecursionError):
            return None
        return out


def compile_query(src: str) -> Query:
    return Query(src)
