"""kq — a jq query engine over JSON-standard objects.

The reference drives all Stage selector matchExpressions, weightFrom and
durationFrom expressions through gojq (reference:
pkg/utils/expression/query.go:25-88 — the *whole* language).  kq is an
independent jq interpreter covering the constructs real stages use —
paths, iteration, ``select``, pipes, the alternative operator ``//``,
boolean/comparison/arithmetic operators, array/object construction,
``if/then/elif/else/end``, the ``?`` error suppressor, and the common
builtin functions (length, any, all, map, has, test, split, join,
startswith, contains, ...) — with gojq-compatible semantics:

- results are a stream; ``null`` outputs are dropped from the result
  list (reference: query.go:60-66);
- any evaluation error aborts the query and yields an *empty* result
  (gojq errors are swallowed: query.go:57-59 returns nil, nil);
- iterating a non-iterable (including null/missing) is an error unless
  suppressed with ``?``;
- field access on null/missing yields null, not an error;
- jq's total value order (null < false < true < numbers < strings <
  arrays < objects) backs ``< <= > >=``, sort, min, max;
- ``true != 1`` (no bool/number coercion).

The full-language tail is in too (r04): variables and ``as`` bindings
(including ``[$a, $b]`` / ``{k: $v}`` destructuring patterns),
``reduce``/``foreach``, ``def`` with filter and ``$value`` parameters
(including recursion), ``try``/``catch``, ``label``/``break``, and the
``@format`` strings (@text/@json/@base64/@base64d/@uri/@html/@sh/
@csv/@tsv) — so out-of-subset stages run on the host path, and
selector expressions using them lower as opaque host-evaluated feature
columns on the device path — plus string interpolation ``"\\(e)"``
with bindings visible inside, recursive descent ``..``/``recurse``,
``limit``/``range(a;b;c)``/``while``/``until``, the ``?//`` pattern
alternative operator, destructuring patterns in ``reduce``/``foreach``
sources, ``input``/``inputs`` (``Query.execute(v, inputs=...)``
feeds the rest-of-stream; the default stream is empty, so ``input``
errors at end-of-input like jq), the regex family (``test``/``match``
flags, ``sub``/``gsub`` with filter replacements and named captures in
Oniguruma ``(?<name>)`` syntax, ``capture``, ``splits``,
``split/2``), the entries family
(``to_entries``/``from_entries``/``with_entries``), paths
(``paths``/``leaf_paths``/``getpath``/``del``), and the collection
tail (``group_by``/``unique_by``/``flatten``/``map_values``/
``in``/``inside``/``index``/``rindex``/``indices``/``ltrimstr``/
``rtrimstr``/``trim``/``explode``/``implode``/``utf8bytelength``),
``setpath``/``delpaths``, and the assignment family
(``=``/``|=``/``+=``/``-=``/``*=``/``/=``/``%=``/``//=`` over path
expressions, jq's original-input rhs and first-output update
semantics; ``|= empty`` deletes).  Unbound ``$vars`` and breaks
outside their label are compile errors like jq.

Lhs path-expression subset (assignment targets, ``del``, ``path``):
field/index/iterate navigation (``.a.b``, ``.a[0]``, ``.a[]``),
commas and pipes of those, ``select(cond)`` stages, and the ``?``
suppressor (``.a? = x`` on a scalar yields the input unchanged, like
jq's empty-paths semantics).  Array slices (``.a[1:2]``) are not in
the grammar at all — a slice lhs is a parse error, not a silent
no-op.  Anything else in path position raises jq's "invalid path
expression" (swallowed to an empty result like every other runtime
error).

The AST node classes (Path/Field/Iterate/Pipe/Select/Compare/Literal)
are public shape contracts: the device compiler pattern-matches them to
lower selector expressions (engine/features.py).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Any, Iterator, List, Optional, Sequence, Tuple


class KqCompileError(ValueError):
    """The query is not valid kq (parse/compile-time)."""


class _KqRuntimeError(Exception):
    """Evaluation error; swallowed by Query.execute (gojq parity).

    ``value`` preserves the original error payload for try/catch
    (jq: ``try error({a: 1}) catch .`` yields the object, not a
    stringification)."""

    def __init__(self, message: str, value: Any = None, has_value: bool = False):
        super().__init__(message)
        self.value = value if has_value else message
        self.has_value = has_value


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
  | (?P<var>\$[A-Za-z_][A-Za-z0-9_]*)
  | (?P<format>@[a-z0-9]+)
  | (?P<op>\?//|//=|//|\.\.|==|!=|<=|>=|\|=|\+=|-=|\*=|/=|%=|=|<|>|\+|-|\*|/|%|\||\(|\)|\[|\]|\{|\}|\.|,|:|\?|;)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    """,
    re.VERBOSE,
)


def _scan_string(src: str, start: int) -> int:
    """End index (past the closing quote) of the string starting at
    ``src[start] == '"'`` — interpolation-aware: inside ``\\( ... )``
    nested quotes open full inner strings (recursively), so
    ``"\\(.a + "x")"`` is ONE token like jq."""
    i = start + 1
    n = len(src)
    while i < n:
        c = src[i]
        if c == '"':
            return i + 1
        if c == "\\":
            if i + 1 < n and src[i + 1] == "(":
                depth = 1
                i += 2
                while i < n and depth:
                    if src[i] == '"':
                        i = _scan_string(src, i)
                        continue
                    if src[i] == "(":
                        depth += 1
                    elif src[i] == ")":
                        depth -= 1
                    i += 1
                continue
            i += 2
            continue
        i += 1
    raise KqCompileError(f"unterminated string in {src!r}")


def _has_interp(body: str) -> bool:
    """Escape-parity-aware: is there an UNESCAPED ``\\(`` in the string
    body?  (A regex lookbehind cannot count backslashes: ``\\\\\\(``
    is an escaped backslash followed by a live interpolation.)"""
    i = 0
    n = len(body)
    while i < n:
        if body[i] == "\\":
            if i + 1 < n and body[i + 1] == "(":
                return True
            i += 2
            continue
        i += 1
    return False


def _tokenize(src: str) -> List[Tuple[str, str]]:
    tokens: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        if src[pos] == '"':
            end = _scan_string(src, pos)
            tokens.append(("string", src[pos:end]))
            pos = end
            continue
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise KqCompileError(f"unexpected character {src[pos]!r} at {pos} in {src!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        tokens.append((kind, m.group()))
    return tokens


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Field:
    name: str


@dataclass(frozen=True)
class Iterate:
    pass


@dataclass(frozen=True)
class Index:
    """Array index ``.[0]`` (negative from the end, like jq)."""

    i: int


@dataclass(frozen=True)
class Path:
    """A `.a.b["c"].[]`-style navigation; ops are Field/Iterate/Index."""

    ops: Tuple[Any, ...]
    optional: bool = False  # trailing '?'


@dataclass(frozen=True)
class Literal:
    value: Any


@dataclass(frozen=True)
class Compare:
    left: Any
    op: str  # == != < <= > >=
    right: Any


@dataclass(frozen=True)
class Select:
    cond: Any


@dataclass(frozen=True)
class Pipe:
    stages: Tuple[Any, ...]


@dataclass(frozen=True)
class Comma:
    parts: Tuple[Any, ...]


@dataclass(frozen=True)
class Alternative:
    left: Any
    right: Any


@dataclass(frozen=True)
class BoolOp:
    op: str  # "and" | "or"
    left: Any
    right: Any


@dataclass(frozen=True)
class Arith:
    op: str  # + - * / %
    left: Any
    right: Any


@dataclass(frozen=True)
class Neg:
    expr: Any


@dataclass(frozen=True)
class Func:
    name: str
    args: Tuple[Any, ...]


@dataclass(frozen=True)
class If:
    cond: Any
    then: Any
    orelse: Any  # None -> identity


@dataclass(frozen=True)
class ArrayCons:
    expr: Any  # None -> []


@dataclass(frozen=True)
class ObjectCons:
    entries: Tuple[Tuple[Any, Any], ...]  # (key expr|str, value expr)


@dataclass(frozen=True)
class Optional_:
    """`expr?` — suppress evaluation errors of expr."""

    expr: Any


@dataclass(frozen=True)
class Var:
    """``$x`` — environment lookup (bound by as/reduce/foreach/def)."""

    name: str


@dataclass(frozen=True)
class As:
    """``SRC as $x | BODY`` — bind each output of SRC for BODY."""

    source: Any
    var: str
    body: Any


@dataclass(frozen=True)
class Reduce:
    """``reduce SRC as PATTERN [?// ALT...] (INIT; UPDATE)``.

    ``patterns`` is a tuple of destructuring-pattern trees (see
    AsPattern); the common ``$x`` binding is ``(("$", "x"),)``."""

    source: Any
    patterns: Tuple[Any, ...]
    init: Any
    update: Any


@dataclass(frozen=True)
class Foreach:
    """``foreach SRC as PATTERN [?// ALT...] (INIT; UPDATE[; EXTRACT])``."""

    source: Any
    patterns: Tuple[Any, ...]
    init: Any
    update: Any
    extract: Any  # None -> emit the accumulator


@dataclass(frozen=True)
class Def:
    """``def f(p1; p2): BODY; REST`` — REST sees f in scope."""

    name: str
    params: Tuple[str, ...]  # "$x" value params or bare filter params
    body: Any
    rest: Any


@dataclass(frozen=True)
class Call:
    """Application of a def-defined function."""

    name: str
    args: Tuple[Any, ...]


@dataclass(frozen=True)
class TryCatch:
    """``try BODY [catch HANDLER]`` — HANDLER sees the error message."""

    body: Any
    handler: Any  # None -> swallow


@dataclass(frozen=True)
class Label:
    """``label $out | BODY`` — a scope ``break $out`` jumps out of."""

    name: str
    body: Any


@dataclass(frozen=True)
class Break:
    """``break $out`` — stop producing outputs up to the label."""

    name: str


@dataclass(frozen=True)
class Format:
    """``@base64`` etc. — format the input value as a string."""

    name: str


@dataclass(frozen=True)
class StrInterp:
    """``"a\\(expr)b"`` — string interpolation; parts are literal
    strings and compiled sub-queries (cartesian across parts)."""

    parts: Tuple[Any, ...]


@dataclass(frozen=True)
class Assign:
    """``PATHEXPR op EXPR`` — jq's update/assignment family.  ``op`` is
    one of = |= += -= *= /= %= //=.  The left side must be a path
    expression (jq "Invalid path expression" otherwise)."""

    op: str
    target: Any
    expr: Any


@dataclass(frozen=True)
class AsPattern:
    """``SRC as [$a, $b] | BODY`` / ``SRC as {k: $v} | BODY`` —
    destructuring binds; each pattern is nested lists/dicts with leaf
    ``("$", name)`` markers.  ``patterns`` holds the ``?//``
    alternatives in order (usually just one): jq tries each pattern,
    and on a destructuring *or body* error moves to the next; every
    variable named in any alternative is in scope (null when the
    matching alternative does not bind it)."""

    source: Any
    patterns: Tuple[Any, ...]
    body: Any


#: zero-arg builtins (applied as a filter to each input)
_FUNCS0 = {
    "length", "keys", "values", "type", "tostring", "tonumber", "not",
    "empty", "add", "any", "all", "first", "last", "min", "max", "sort",
    "unique", "floor", "ceil", "ascii_downcase", "ascii_upcase", "abs",
    "reverse", "tojson", "fromjson", "error", "recurse", "input", "inputs",
    "to_entries", "from_entries", "paths", "leaf_paths", "flatten",
    "explode", "implode", "infinite", "nan", "isnan",
    "isinfinite", "isnormal", "utf8bytelength", "trim", "ltrim", "rtrim",
    "now", "todate", "fromdate", "todateiso8601", "fromdateiso8601",
}

#: env key carrying the shared rest-of-inputs iterator for
#: ``input``/``inputs`` (a tuple so it can never collide with a $var
#: name; def closures copy the env, so the iterator is shared)
_INPUTS_KEY = ("inputs",)
#: one-arg builtins
_FUNCS1 = {
    "select", "has", "map", "test", "startswith", "endswith", "contains",
    "split", "join", "any", "all", "sort_by", "min_by", "max_by", "range",
    "error", "recurse", "with_entries", "group_by", "unique_by",
    "ltrimstr", "rtrimstr", "getpath", "flatten", "in", "inside",
    "splits", "index", "rindex", "indices", "capture", "match", "del",
    "map_values", "paths", "delpaths", "path",
}
#: multi-arg builtins: name -> allowed arities beyond 0/1
_FUNCS_N = {
    "limit": {2},
    "range": {2, 3},
    "while": {2},
    "until": {2},
    "test": {2},
    "match": {2},
    "split": {2},
    "splits": {2},
    "sub": {2, 3},
    "gsub": {2, 3},
    "capture": {2},
    "setpath": {2},
}


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]], src: str):
        self.tokens = tokens
        self.src = src
        self.i = 0
        #: lexically-scoped $variables (unbound use is a compile error,
        #: like jq)
        self.var_scope: List[str] = []
        #: def-defined functions in scope as (name, arity); bare filter
        #: params enter with arity 0
        self.fn_scope: List[Tuple[str, int]] = []
        #: >0 while parsing a reduce/foreach source, whose own 'as'
        #: belongs to the construct, not to a Term binding
        self._no_as = 0
        #: lexically-scoped labels (break outside its label is a
        #: compile error, like jq)
        self.label_scope: List[str] = []

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def peek_text(self) -> Optional[str]:
        t = self.peek()
        return t[1] if t else None

    def next(self) -> Tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise KqCompileError(f"unexpected end of query: {self.src!r}")
        self.i += 1
        return tok

    def expect(self, text: str) -> None:
        tok = self.next()
        if tok[1] != text:
            raise KqCompileError(f"expected {text!r}, got {tok[1]!r} in {self.src!r}")

    # precedence chain: pipe > comma > // > or > and > cmp > add > mul > unary

    def parse_query(self) -> Any:
        node = self.parse_pipe()
        if self.peek() is not None:
            raise KqCompileError(f"trailing tokens in {self.src!r}")
        return node

    def parse_pipe(self) -> Any:
        stages = [self.parse_comma()]
        while self.peek_text() == "|":
            self.next()
            stages.append(self.parse_comma())
        if len(stages) == 1:
            return stages[0]
        return Pipe(tuple(stages))

    def parse_comma(self) -> Any:
        parts = [self.parse_alt()]
        while self.peek_text() == ",":
            self.next()
            parts.append(self.parse_alt())
        if len(parts) == 1:
            return parts[0]
        return Comma(tuple(parts))

    def parse_alt(self) -> Any:
        node = self.parse_assign()
        while self.peek_text() == "//":
            self.next()
            node = Alternative(node, self.parse_assign())
        return node

    _ASSIGN_OPS = ("=", "|=", "+=", "-=", "*=", "/=", "%=", "//=")

    def parse_assign(self) -> Any:
        node = self.parse_or()
        t = self.peek_text()
        if t in self._ASSIGN_OPS:
            self.next()
            rhs = self.parse_or()
            # %nonassoc in jq.y: `.a = .b = 1` is a syntax error
            if self.peek_text() in self._ASSIGN_OPS:
                raise KqCompileError(
                    f"chained assignment in {self.src!r}"
                )
            return Assign(t, node, rhs)
        return node

    def parse_or(self) -> Any:
        node = self.parse_and()
        while self.peek_text() == "or":
            self.next()
            node = BoolOp("or", node, self.parse_and())
        return node

    def parse_and(self) -> Any:
        node = self.parse_cmp()
        while self.peek_text() == "and":
            self.next()
            node = BoolOp("and", node, self.parse_cmp())
        return node

    def parse_cmp(self) -> Any:
        node = self.parse_add()
        tok = self.peek()
        if tok is not None and tok[1] in ("==", "!=", "<", "<=", ">", ">="):
            op = self.next()[1]
            right = self.parse_add()
            node = Compare(node, op, right)
        return node

    def parse_add(self) -> Any:
        node = self.parse_mul()
        while self.peek_text() in ("+", "-"):
            op = self.next()[1]
            node = Arith(op, node, self.parse_mul())
        return node

    def parse_mul(self) -> Any:
        node = self.parse_unary()
        while self.peek_text() in ("*", "/", "%"):
            op = self.next()[1]
            node = Arith(op, node, self.parse_unary())
        return node

    def parse_unary(self) -> Any:
        if self.peek_text() == "-":
            self.next()
            return Neg(self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self) -> Any:
        node = self.parse_primary()
        while True:
            t = self.peek_text()
            if t == "?":
                self.next()
                node = Optional_(node)
            elif t == ".":
                # path suffix on a primary — `$i.name`, `(.a).b.[0]` —
                # jq sugar for `expr | .path`.  (A directly-parsed Path
                # never leaves a '.' behind, so this only triggers on
                # non-path primaries.)
                suffix = self.parse_path()
                node = Pipe((node, suffix))
            else:
                break
        if self.peek_text() == "as" and not self._no_as:
            # jq grammar: Term 'as' Pattern '|' Exp — the source is the
            # TERM, and the body extends maximally to the right
            # (`1, 2 as $x | e` is `1, (2 as $x | e)`)
            self.next()
            patterns = self._parse_patterns()
            names = [n for p in patterns for n in _pattern_vars(p)]
            self.expect("|")
            self.var_scope.extend(names)
            try:
                body = self.parse_pipe()
            finally:
                del self.var_scope[len(self.var_scope) - len(names) :]
            if len(patterns) == 1 and patterns[0][0] == "$":
                return As(node, patterns[0][1], body)
            return AsPattern(node, patterns, body)
        return node

    def _parse_patterns(self) -> Tuple[Any, ...]:
        """One destructuring pattern plus any ``?//`` alternatives."""
        patterns = [self.parse_pattern()]
        while self.peek_text() == "?//":
            self.next()
            patterns.append(self.parse_pattern())
        return tuple(patterns)

    def _parse_call_args(self) -> List[Any]:
        """``( a; b; ... )`` argument list, empty when no paren."""
        args: List[Any] = []
        if self.peek_text() == "(":
            self.next()
            args.append(self.parse_pipe())
            while self.peek_text() == ";":
                self.next()
                args.append(self.parse_pipe())
            self.expect(")")
        return args

    def _builtin_call(self, text: str, args: List[Any]) -> Optional[Any]:
        """Builtin node for (name, arity), or None when unknown."""
        ok = (
            (len(args) == 0 and text in _FUNCS0)
            or (len(args) == 1 and text in _FUNCS1)
            or (len(args) in _FUNCS_N.get(text, ()))
        )
        if not ok:
            return None
        if text == "select":
            return Select(args[0])
        return Func(text, tuple(args))

    def _parse_interp(self, body: str) -> Any:
        """Split a string body on ``\\( ... )`` (paren-balanced, string
        literals inside skipped) and compile the embedded queries with
        THIS parser's scopes, so ``"\\($x)"`` sees its binding."""
        parts: List[Any] = []
        lit: List[str] = []
        i = 0
        n = len(body)
        while i < n:
            if body[i] == "\\" and i + 1 < n and body[i + 1] == "(":
                depth = 1
                j = i + 2
                while j < n and depth:
                    c = body[j]
                    if c == '"':
                        j += 1
                        while j < n and body[j] != '"':
                            j += 2 if body[j] == "\\" else 1
                    elif c == "(":
                        depth += 1
                    elif c == ")":
                        depth -= 1
                    j += 1
                if depth:
                    raise KqCompileError(
                        f"unbalanced interpolation in {self.src!r}"
                    )
                src = body[i + 2 : j - 1]
                if lit:
                    parts.append(_unquote(f'"{"".join(lit)}"'))
                    lit = []
                sub = _Parser(_tokenize(src), src)
                sub.var_scope = self.var_scope
                sub.fn_scope = self.fn_scope
                sub.label_scope = self.label_scope
                parts.append(sub.parse_query())
                i = j
            elif body[i] == "\\":
                lit.append(body[i : i + 2])
                i += 2
            else:
                lit.append(body[i])
                i += 1
        if lit:
            parts.append(_unquote(f'"{"".join(lit)}"'))
        return StrInterp(tuple(parts))

    def parse_pattern(self) -> Any:
        """Destructuring pattern: ``$x`` | ``[p, ...]`` | ``{k: p, $x}``."""
        tok = self.next()
        if tok[0] == "var":
            return ("$", tok[1][1:])
        if tok[1] == "[":
            elems = [self.parse_pattern()]
            while self.peek_text() == ",":
                self.next()
                elems.append(self.parse_pattern())
            self.expect("]")
            return ("arr", tuple(elems))
        if tok[1] == "{":
            entries = []
            while True:
                k = self.next()
                if k[0] == "var":
                    # {$x} shorthand: key "x" binds $x
                    entries.append((k[1][1:], ("$", k[1][1:])))
                elif k[0] in ("ident", "string"):
                    key = _unquote(k[1]) if k[0] == "string" else k[1]
                    self.expect(":")
                    entries.append((key, self.parse_pattern()))
                else:
                    raise KqCompileError(
                        f"bad pattern key {k[1]!r} in {self.src!r}"
                    )
                if self.peek_text() == ",":
                    self.next()
                    continue
                break
            self.expect("}")
            return ("obj", tuple(entries))
        raise KqCompileError(f"bad pattern {tok[1]!r} in {self.src!r}")

    def parse_primary(self) -> Any:
        tok = self.peek()
        if tok is None:
            raise KqCompileError(f"unexpected end of query: {self.src!r}")
        kind, text = tok
        if text == ".":
            return self.parse_path()
        if text == "(":
            self.next()
            # parens reset the reduce/foreach 'as'-suppression: an
            # inner binding like `reduce (.[] as $y | $y) as $x (...)`
            # is fully parenthesized and unambiguous
            saved_no_as, self._no_as = self._no_as, 0
            try:
                node = self.parse_pipe()
            finally:
                self._no_as = saved_no_as
            self.expect(")")
            return node
        if text == "[":
            self.next()
            if self.peek_text() == "]":
                self.next()
                return ArrayCons(None)
            node = self.parse_pipe()
            self.expect("]")
            return ArrayCons(node)
        if text == "{":
            return self.parse_object()
        if kind == "string":
            self.next()
            body = text[1:-1]
            if _has_interp(body):
                return self._parse_interp(body)
            return Literal(_unquote(text))
        if kind == "number":
            self.next()
            is_float = "." in text or "e" in text or "E" in text
            return Literal(float(text) if is_float else int(text))
        if kind == "var":
            self.next()
            name = text[1:]
            if name not in self.var_scope:
                raise KqCompileError(f"${name} is not defined in {self.src!r}")
            return Var(name)
        if kind == "format":
            self.next()
            name = text[1:]
            if name not in _FORMATS:
                raise KqCompileError(f"unknown format @{name} in {self.src!r}")
            return Format(name)
        if kind == "ident":
            if text == "if":
                return self.parse_if()
            if text == "reduce":
                return self.parse_reduce()
            if text == "foreach":
                return self.parse_foreach()
            if text == "def":
                return self.parse_def()
            if text == "try":
                return self.parse_try()
            if text == "label":
                self.next()
                tok = self.next()
                if tok[0] != "var":
                    raise KqCompileError(
                        f"'label' needs a $name in {self.src!r}"
                    )
                lbl = tok[1][1:]
                self.expect("|")
                self.label_scope.append(lbl)
                try:
                    body = self.parse_pipe()
                finally:
                    self.label_scope.pop()
                return Label(lbl, body)
            if text == "break":
                self.next()
                tok = self.next()
                if tok[0] != "var" or tok[1][1:] not in self.label_scope:
                    raise KqCompileError(
                        f"break outside its label in {self.src!r}"
                    )
                return Break(tok[1][1:])
            if text in ("true", "false", "null"):
                self.next()
                return Literal({"true": True, "false": False, "null": None}[text])
            # def-defined functions shadow builtins per (name, arity);
            # an arity not def'd falls through to the builtin of that
            # arity (jq resolves map/1 past a user def map/0)
            if any(n == text for n, _ in self.fn_scope):
                self.next()
                args = self._parse_call_args()
                if (text, len(args)) in self.fn_scope:
                    return Call(text, tuple(args))
                node = self._builtin_call(text, args)
                if node is not None:
                    return node
                raise KqCompileError(
                    f"{text}/{len(args)} is not defined in {self.src!r}"
                )
            if text in _FUNCS0 or text in _FUNCS1 or text in _FUNCS_N:
                self.next()
                args = self._parse_call_args()
                node = self._builtin_call(text, args)
                if node is None:
                    raise KqCompileError(
                        f"{text}/{len(args)} is not defined in {self.src!r}"
                    )
                return node
            raise KqCompileError(f"unsupported function {text!r} in {self.src!r}")
        if text == "..":
            self.next()
            return Func("recurse", ())
        raise KqCompileError(f"unexpected token {text!r} in {self.src!r}")

    def _parse_as_binding(self, kw: str) -> Tuple[Any, Tuple[Any, ...]]:
        """Shared ``KW SRC as PATTERN [?// ALT...]`` prefix of
        reduce/foreach — full destructuring patterns, like jq's
        grammar (gojq behind reference query.go:33 accepts them)."""
        self.expect(kw)
        self._no_as += 1
        try:
            source = self.parse_postfix()
        finally:
            self._no_as -= 1
        self.expect("as")
        return source, self._parse_patterns()

    def parse_reduce(self) -> Any:
        source, patterns = self._parse_as_binding("reduce")
        names = [n for p in patterns for n in _pattern_vars(p)]
        self.expect("(")
        init = self.parse_pipe()
        self.expect(";")
        self.var_scope.extend(names)
        try:
            update = self.parse_pipe()
        finally:
            del self.var_scope[len(self.var_scope) - len(names) :]
        self.expect(")")
        return Reduce(source, patterns, init, update)

    def parse_foreach(self) -> Any:
        source, patterns = self._parse_as_binding("foreach")
        names = [n for p in patterns for n in _pattern_vars(p)]
        self.expect("(")
        init = self.parse_pipe()
        self.expect(";")
        self.var_scope.extend(names)
        try:
            update = self.parse_pipe()
            extract = None
            if self.peek_text() == ";":
                self.next()
                extract = self.parse_pipe()
        finally:
            del self.var_scope[len(self.var_scope) - len(names) :]
        self.expect(")")
        return Foreach(source, patterns, init, update, extract)

    def parse_def(self) -> Any:
        self.expect("def")
        tok = self.next()
        if tok[0] != "ident":
            raise KqCompileError(f"bad def name {tok[1]!r} in {self.src!r}")
        name = tok[1]
        params: List[str] = []
        if self.peek_text() == "(":
            self.next()
            while True:
                p = self.next()
                if p[0] == "var":
                    params.append(p[1])  # keep the $ to mark value params
                elif p[0] == "ident":
                    params.append(p[1])
                else:
                    raise KqCompileError(
                        f"bad def parameter {p[1]!r} in {self.src!r}"
                    )
                if self.peek_text() == ";":
                    self.next()
                    continue
                break
            self.expect(")")
        self.expect(":")
        # body scope: $params are variables, bare params are 0-ary
        # filters, and the function itself is visible (recursion)
        n_vars = 0
        n_fns = 1
        self.fn_scope.append((name, len(params)))
        for p in params:
            if p.startswith("$"):
                self.var_scope.append(p[1:])
                n_vars += 1
            else:
                self.fn_scope.append((p, 0))
                n_fns += 1
        try:
            body = self.parse_pipe()
        finally:
            del self.var_scope[len(self.var_scope) - n_vars :]
            del self.fn_scope[len(self.fn_scope) - n_fns :]
        self.expect(";")
        self.fn_scope.append((name, len(params)))
        try:
            rest = self.parse_pipe()
        finally:
            self.fn_scope.pop()
        return Def(name, tuple(params), body, rest)

    def parse_try(self) -> Any:
        self.expect("try")
        body = self.parse_postfix()
        handler = None
        if self.peek_text() == "catch":
            self.next()
            handler = self.parse_postfix()
        return TryCatch(body, handler)

    def parse_if(self) -> Any:
        self.expect("if")
        cond = self.parse_pipe()
        self.expect("then")
        then = self.parse_pipe()
        tok = self.peek()
        if tok is not None and tok[1] == "elif":
            # rewrite elif as nested if
            self.next()
            # re-parse as if-chain: build manually
            sub_cond = self.parse_pipe()
            self.expect("then")
            sub_then = self.parse_pipe()
            rest = self._finish_if(sub_cond, sub_then)
            return If(cond, then, rest)
        if tok is not None and tok[1] == "else":
            self.next()
            orelse = self.parse_pipe()
            self.expect("end")
            return If(cond, then, orelse)
        self.expect("end")
        return If(cond, then, None)

    def _finish_if(self, cond: Any, then: Any) -> Any:
        tok = self.peek()
        if tok is not None and tok[1] == "elif":
            self.next()
            sub_cond = self.parse_pipe()
            self.expect("then")
            sub_then = self.parse_pipe()
            return If(cond, then, self._finish_if(sub_cond, sub_then))
        if tok is not None and tok[1] == "else":
            self.next()
            orelse = self.parse_pipe()
            self.expect("end")
            return If(cond, then, orelse)
        self.expect("end")
        return If(cond, then, None)

    def parse_object(self) -> Any:
        self.expect("{")
        entries: List[Tuple[Any, Any]] = []
        if self.peek_text() != "}":
            while True:
                tok = self.next()
                if tok[0] == "ident":
                    key: Any = tok[1]
                elif tok[0] == "string":
                    key = _unquote(tok[1])
                elif tok[1] == "(":
                    key = self.parse_pipe()
                    self.expect(")")
                else:
                    raise KqCompileError(f"bad object key {tok[1]!r} in {self.src!r}")
                if self.peek_text() == ":":
                    self.next()
                    val = self.parse_alt()
                else:
                    if not isinstance(key, str):
                        raise KqCompileError(f"shorthand needs ident key in {self.src!r}")
                    val = Path((Field(key),))
                entries.append((key, val))
                if self.peek_text() == ",":
                    self.next()
                    continue
                break
        self.expect("}")
        return ObjectCons(tuple(entries))

    def parse_path(self) -> Path:
        ops: List[Any] = []
        self.expect(".")
        while True:
            tok = self.peek()
            if tok is None:
                break
            kind, text = tok
            if kind == "ident":
                # identifiers that are keywords/operators end the path
                if text in ("and", "or", "then", "else", "elif", "end", "as"):
                    break
                self.next()
                ops.append(Field(text))
            elif text == "[":
                self.next()
                nxt = self.next()
                if nxt[1] == "]":
                    ops.append(Iterate())
                elif nxt[0] == "string":
                    self.expect("]")
                    ops.append(Field(_unquote(nxt[1])))
                elif nxt[0] == "number" and "." not in nxt[1]:
                    self.expect("]")
                    ops.append(Index(int(nxt[1])))
                elif nxt[1] == "-" and self.peek() and self.peek()[0] == "number":
                    num = self.next()[1]
                    self.expect("]")
                    ops.append(Index(-int(num)))
                else:
                    raise KqCompileError(
                        f"unsupported index {nxt[1]!r} in {self.src!r}"
                    )
            elif text == ".":
                # `.a.b` / `.a.[]` — separator between segments
                self.next()
                nxt = self.peek()
                if nxt is None or (nxt[0] != "ident" and nxt[1] != "["):
                    raise KqCompileError(f"dangling '.' in {self.src!r}")
            else:
                break
        if self.peek_text() == "?":
            self.next()
            return Path(tuple(ops), optional=True)
        return Path(tuple(ops))


def _unquote(s: str) -> str:
    body = s[1:-1]
    if _has_interp(body):
        # silently rendering "\(e)" as a literal would be wrong output
        # — interpolation is only wired for value position, so fail
        # loudly where it is not (object keys, path brackets)
        raise KqCompileError(f"interpolation not supported here: {s!r}")
    return body.replace('\\"', '"').replace("\\\\", "\\")


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


def _truthy(v: Any) -> bool:
    # jq: false and null are falsy; everything else truthy.
    return v is not None and v is not False


_TYPE_ORDER = {"null": 0, "boolean": 1, "number": 2, "string": 3, "array": 4, "object": 5}


def _jq_type(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, list):
        return "array"
    if isinstance(v, dict):
        return "object"
    raise _KqRuntimeError(f"non-JSON value {type(v).__name__}")


def _jq_cmp(a: Any, b: Any) -> int:
    """jq's total value order."""
    ta, tb = _jq_type(a), _jq_type(b)
    if ta != tb:
        return -1 if _TYPE_ORDER[ta] < _TYPE_ORDER[tb] else 1
    if ta in ("null",):
        return 0
    if ta == "boolean":
        return (a > b) - (a < b)
    if ta in ("number", "string"):
        return (a > b) - (a < b)
    if ta == "array":
        for x, y in zip(a, b):
            c = _jq_cmp(x, y)
            if c:
                return c
        return (len(a) > len(b)) - (len(a) < len(b))
    # object: compare sorted keys, then values in key order
    ka, kb = sorted(a), sorted(b)
    c = _jq_cmp(ka, kb)
    if c:
        return c
    for k in ka:
        c = _jq_cmp(a[k], b[k])
        if c:
            return c
    return 0


def _arith(op: str, a: Any, b: Any) -> Any:
    if op == "+":
        if a is None:
            return b
        if b is None:
            return a
        if isinstance(a, bool) or isinstance(b, bool):
            raise _KqRuntimeError("boolean + boolean")
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            return a + b
        if isinstance(a, str) and isinstance(b, str):
            return a + b
        if isinstance(a, list) and isinstance(b, list):
            return a + b
        if isinstance(a, dict) and isinstance(b, dict):
            out = dict(a)
            out.update(b)
            return out
        raise _KqRuntimeError(f"cannot add {_jq_type(a)} and {_jq_type(b)}")
    if op == "-":
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) and not (
            isinstance(a, bool) or isinstance(b, bool)
        ):
            return a - b
        if isinstance(a, list) and isinstance(b, list):
            return [x for x in a if x not in b]
        raise _KqRuntimeError(f"cannot subtract {_jq_type(b)} from {_jq_type(a)}")
    if op == "*":
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) and not (
            isinstance(a, bool) or isinstance(b, bool)
        ):
            return a * b
        if isinstance(a, dict) and isinstance(b, dict):
            return _deep_merge(a, b)
        raise _KqRuntimeError(f"cannot multiply {_jq_type(a)} and {_jq_type(b)}")
    if op == "/":
        if isinstance(a, str) and isinstance(b, str):
            return a.split(b)
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) and not (
            isinstance(a, bool) or isinstance(b, bool)
        ):
            if b == 0:
                raise _KqRuntimeError("division by zero")
            out = a / b
            return out
        raise _KqRuntimeError(f"cannot divide {_jq_type(a)} by {_jq_type(b)}")
    if op == "%":
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) and not (
            isinstance(a, bool) or isinstance(b, bool)
        ):
            if int(b) == 0:
                raise _KqRuntimeError("modulo by zero")
            return int(math.fmod(int(a), int(b)))
        raise _KqRuntimeError(f"cannot mod {_jq_type(a)} by {_jq_type(b)}")
    raise _KqRuntimeError(f"unknown operator {op}")


def _deep_merge(a: dict, b: dict) -> dict:
    out = dict(a)
    for k, v in b.items():
        if isinstance(out.get(k), dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _eval(node: Any, value: Any, env: dict) -> Iterator[Any]:
    if isinstance(node, Literal):
        yield node.value
    elif isinstance(node, Path):
        if node.optional:
            # stream-then-swallow, like `try` (jq: `e?` is `try e`)
            it = _eval_path(node.ops, 0, value)
            while True:
                try:
                    out = next(it)
                except (StopIteration, _KqRuntimeError):
                    return
                yield out
        else:
            yield from _eval_path(node.ops, 0, value)
    elif isinstance(node, Pipe):
        yield from _eval_pipe(node.stages, 0, value, env)
    elif isinstance(node, Comma):
        for part in node.parts:
            yield from _eval(part, value, env)
    elif isinstance(node, Select):
        for out in _eval(node.cond, value, env):
            if _truthy(out):
                yield value
    elif isinstance(node, Compare):
        for lv in _eval(node.left, value, env):
            for rv in _eval(node.right, value, env):
                if node.op == "==":
                    yield _json_equal(lv, rv)
                elif node.op == "!=":
                    yield not _json_equal(lv, rv)
                else:
                    c = _jq_cmp(lv, rv)
                    yield {
                        "<": c < 0,
                        "<=": c <= 0,
                        ">": c > 0,
                        ">=": c >= 0,
                    }[node.op]
    elif isinstance(node, Alternative):
        got = False
        try:
            for out in _eval(node.left, value, env):
                if _truthy(out):
                    got = True
                    yield out
        except _KqRuntimeError:
            pass
        if not got:
            yield from _eval(node.right, value, env)
    elif isinstance(node, BoolOp):
        for lv in _eval(node.left, value, env):
            lt = _truthy(lv)
            if node.op == "and" and not lt:
                yield False
            elif node.op == "or" and lt:
                yield True
            else:
                for rv in _eval(node.right, value, env):
                    yield _truthy(rv)
    elif isinstance(node, Arith):
        for lv in _eval(node.left, value, env):
            for rv in _eval(node.right, value, env):
                yield _arith(node.op, lv, rv)
    elif isinstance(node, Neg):
        for v in _eval(node.expr, value, env):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise _KqRuntimeError(f"cannot negate {_jq_type(v)}")
            yield -v
    elif isinstance(node, If):
        for c in _eval(node.cond, value, env):
            if _truthy(c):
                yield from _eval(node.then, value, env)
            elif node.orelse is not None:
                yield from _eval(node.orelse, value, env)
            else:
                yield value
    elif isinstance(node, ArrayCons):
        if node.expr is None:
            yield []
        else:
            yield list(_eval(node.expr, value, env))
    elif isinstance(node, ObjectCons):
        yield from _eval_object(node.entries, 0, value, {}, env)
    elif isinstance(node, Optional_):
        # jq defines `e?` as `try e`: stream outputs until the error,
        # then swallow it (not discard-the-whole-prefix)
        it = _eval(node.expr, value, env)
        while True:
            try:
                out = next(it)
            except StopIteration:
                return
            except _KqRuntimeError:
                return
            yield out
    elif isinstance(node, Func):
        yield from _eval_func(node, value, env)
    elif isinstance(node, Var):
        try:
            yield env[node.name]
        except KeyError:
            raise _KqRuntimeError(f"${node.name} is not defined")
    elif isinstance(node, As):
        for bound in _eval(node.source, value, env):
            yield from _eval(node.body, value, {**env, node.var: bound})
    elif isinstance(node, Reduce):
        for acc0 in _eval(node.init, value, env):
            acc = acc0
            for x in _eval(node.source, value, env):
                acc = _fold_bind_step(node.update, acc, node.patterns, x, env)
            yield acc
    elif isinstance(node, Foreach):
        pats = node.patterns
        for acc0 in _eval(node.init, value, env):
            acc = acc0
            for x in _eval(node.source, value, env):
                if len(pats) == 1:
                    e2 = dict(env)
                    _bind_pattern(pats[0], x, e2)
                    acc = _fold_step(node.update, acc, e2)
                    if node.extract is None:
                        yield acc
                    else:
                        yield from _eval(node.extract, acc, e2)
                else:
                    acc, outs = _foreach_alt_step(node, acc, x, env)
                    yield from outs
    elif isinstance(node, Def):
        env2 = dict(env)
        env2[("fn", node.name, len(node.params))] = (node.params, node.body, env2)
        yield from _eval(node.rest, value, env2)
    elif isinstance(node, Call):
        yield from _eval_call(node, value, env)
    elif isinstance(node, TryCatch):
        it = _eval(node.body, value, env)
        while True:
            try:
                out = next(it)
            except StopIteration:
                return
            except _KqRuntimeError as exc:
                if node.handler is not None:
                    yield from _eval(node.handler, exc.value, env)
                return
            yield out
    elif isinstance(node, Label):
        it = _eval(node.body, value, env)
        while True:
            try:
                out = next(it)
            except StopIteration:
                return
            except _KqBreak as brk:
                if brk.name != node.name:
                    raise
                return
            yield out
    elif isinstance(node, Break):
        raise _KqBreak(node.name)
    elif isinstance(node, Format):
        yield _apply_format(node.name, value)
    elif isinstance(node, StrInterp):

        def build(i: int, acc: str):
            if i == len(node.parts):
                yield acc
                return
            part = node.parts[i]
            if isinstance(part, str):
                yield from build(i + 1, acc + part)
                return
            for out in _eval(part, value, env):
                yield from build(
                    i + 1,
                    acc + (out if isinstance(out, str) else _apply_format("text", out)),
                )

        yield from build(0, "")
    elif isinstance(node, Assign):
        pths = list(_collect_ast_paths(node.target, value, env))
        if node.op == "=":
            # rhs is evaluated against the ORIGINAL input; one output
            # per rhs output, all paths set to the same value (jq)
            for v in _eval(node.expr, value, env):
                out = value
                for pth in pths:
                    out = _setpath(out, pth, v)
                yield out
        elif node.op == "|=":
            # per-path update with the FIRST output of the filter on
            # the current value; an empty update deletes the path.
            # Deletions are batched (index-safe) — GOJQ semantics, the
            # engine the reference embeds (query.go:33); jq 1.7 itself
            # shifts indices mid-reduce, a documented jq bug gojq fixed.
            out = value
            dels = []
            for pth in pths:
                cur = _getpath(out, pth)
                nv = next(iter(_eval(node.expr, cur, env)), _MISSING_V)
                if nv is _MISSING_V:
                    dels.append(pth)
                else:
                    out = _setpath(out, pth, nv)
            if dels:
                out = _delpaths(out, dels)
            yield out
        else:
            arith_op = node.op[:-1]  # "+", "-", "*", "/", "%", "//"
            for v in _eval(node.expr, value, env):
                out = value
                for pth in pths:
                    cur = _getpath(out, pth)
                    if arith_op == "//":
                        nv = cur if cur is not None and cur is not False else v
                    else:
                        nv = _arith(arith_op, cur, v)
                    out = _setpath(out, pth, nv)
                yield out
    elif isinstance(node, AsPattern):
        pats = node.patterns
        if len(pats) == 1:
            for bound in _eval(node.source, value, env):
                e2 = dict(env)
                _bind_pattern(pats[0], bound, e2)
                yield from _eval(node.body, value, e2)
        else:
            for bound in _eval(node.source, value, env):
                yield from _alt_bind_outputs(
                    pats, bound, env, lambda e2: _eval(node.body, value, e2)
                )
    else:  # pragma: no cover
        raise _KqRuntimeError(f"unknown node {node!r}")


def _eval_func_n(node: Func, value: Any, env: dict) -> Iterator[Any]:
    """Multi-arg builtins: limit/2, range/2-3, while/2, until/2, plus
    the regex family (test/split/splits with flags, sub/gsub with a
    filter replacement, capture)."""
    name, args = node.name, node.args
    if name in ("test", "capture", "match", "split", "splits") and len(args) == 2:
        if not isinstance(value, str):
            raise _KqRuntimeError(f"{name} on non-string")
        for pat in _eval(args[0], value, env):
            for fl in _eval(args[1], value, env):
                if fl is not None and not isinstance(fl, str):
                    raise _KqRuntimeError("regex flags must be a string")
                rx, g = _regex(pat, fl)
                if name == "test":
                    yield rx.search(value) is not None
                elif name == "split":
                    yield _regex_split(value, rx)
                else:
                    yield from _regex_stream(name, value, pat, fl)
        return
    if name == "setpath" and len(args) == 2:
        for pth in _eval(args[0], value, env):
            if not isinstance(pth, list):
                raise _KqRuntimeError("setpath path must be an array")
            for v in _eval(args[1], value, env):
                yield _setpath(value, pth, v)
        return
    if name in ("sub", "gsub"):
        for pat in _eval(args[0], value, env):
            flags_out = (
                [None]
                if len(args) < 3
                else list(_eval(args[2], value, env))
            )
            for fl in flags_out:
                if fl is not None and not isinstance(fl, str):
                    raise _KqRuntimeError("regex flags must be a string")
                yield from _sub_impl(
                    value,
                    pat,
                    fl,
                    lambda cap: _eval(args[1], cap, env),
                    name == "gsub",
                )
        return
    if name == "limit":
        for n in _eval(args[0], value, env):
            if isinstance(n, bool) or not isinstance(n, (int, float)):
                raise _KqRuntimeError("limit count must be a number")
            n = int(n)
            if n <= 0:
                continue
            emitted = 0
            for out in _eval(args[1], value, env):
                yield out
                emitted += 1
                if emitted >= n:
                    break
        return
    if name == "range":
        exprs = [list(_eval(a, value, env)) for a in args]
        import itertools

        for combo in itertools.product(*exprs):
            for v in combo:
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise _KqRuntimeError("range over non-number")
            start, stop = combo[0], combo[1]
            step = combo[2] if len(combo) > 2 else 1
            if step == 0:
                continue
            cur = start
            while (cur < stop) if step > 0 else (cur > stop):
                yield cur
                cur += step
        return
    if name in ("while", "until"):
        cond, update = args[0], args[1]

        def gen(x):
            # jq: def while(c; u): if c then ., (u | while(c; u))
            #     def until(c; u): if c then . else (u | until(c; u))
            for c in _eval(cond, x, env):
                if name == "while":
                    if _truthy(c):
                        yield x
                        for nx in _eval(update, x, env):
                            yield _Recur(nx)
                else:
                    if _truthy(c):
                        yield x
                    else:
                        for nx in _eval(update, x, env):
                            yield _Recur(nx)

        yield from _trampoline(gen, value)
        return
    raise _KqRuntimeError(f"unknown function {name}/{len(args)}")


class _Recur:
    """Trampoline marker: 'descend into this value' (loop builtins run
    on an explicit stack, not Python recursion — jq's TCO means
    while/until/recurse must handle unbounded iteration counts)."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


def _trampoline(gen, x0) -> Iterator[Any]:
    """Depth-first preorder over generators that yield values (passed
    through) and _Recur markers (descend): recursion order without
    Python stack frames."""
    stack = [gen(x0)]
    while stack:
        try:
            item = next(stack[-1])
        except StopIteration:
            stack.pop()
            continue
        if type(item) is _Recur:
            stack.append(gen(item.value))
        else:
            yield item


class _KqBreak(Exception):
    """Control-flow escape for label/break (never leaves Query.execute:
    an unmatched break is a compile error)."""

    def __init__(self, name: str):
        super().__init__(name)
        self.name = name


def _bind_pattern(pattern, value, env: dict) -> None:
    kind = pattern[0]
    if kind == "$":
        env[pattern[1]] = value
        return
    if kind == "arr":
        if value is None:
            value = []
        if not isinstance(value, list):
            raise _KqRuntimeError(
                f"cannot destructure {_jq_type(value)} as an array"
            )
        for i, sub in enumerate(pattern[1]):
            _bind_pattern(sub, value[i] if i < len(value) else None, env)
        return
    if value is None:
        value = {}
    if not isinstance(value, dict):
        raise _KqRuntimeError(
            f"cannot destructure {_jq_type(value)} as an object"
        )
    for key, sub in pattern[1]:
        _bind_pattern(sub, value.get(key), env)


def _csv_cell(v: Any, quote: str) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return _num_str(v)
    if isinstance(v, str):
        return quote + v.replace(quote, quote + quote) + quote
    raise _KqRuntimeError(f"{_jq_type(v)} is not valid in a csv row")


def _num_str(v: Any) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return str(v)


def _apply_format(name: str, value: Any) -> Any:
    import base64 as _b64
    import json as _json
    import urllib.parse as _url

    if name == "text":
        return value if isinstance(value, str) else _json.dumps(value)
    s = value if isinstance(value, str) else _json.dumps(value)
    if name == "json":
        return _json.dumps(value, separators=(",", ":"))
    if name == "base64":
        return _b64.b64encode(s.encode()).decode()
    if name == "base64d":
        try:
            return _b64.b64decode(s.encode() + b"==").decode()
        except Exception:
            raise _KqRuntimeError(f"{s!r} is not valid base64")
    if name == "uri":
        return _url.quote(s, safe="")
    if name == "html":
        return (
            s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")
            .replace("'", "&#39;").replace('"', "&quot;")
        )
    if name == "sh":
        if isinstance(value, list):
            return " ".join(_sh_word(x) for x in value)
        return "'" + s.replace("'", "'\\''") + "'"
    if name == "csv":
        if not isinstance(value, list):
            raise _KqRuntimeError("@csv needs an array input")
        return ",".join(_csv_cell(v, '"') for v in value)
    if name == "tsv":
        if not isinstance(value, list):
            raise _KqRuntimeError("@tsv needs an array input")
        out = []
        for v in value:
            if isinstance(v, str):
                out.append(
                    v.replace("\\", "\\\\").replace("\t", "\\t").replace("\n", "\\n")
                )
            elif v is None:
                out.append("")
            elif isinstance(v, bool):
                out.append("true" if v else "false")
            elif isinstance(v, (int, float)):
                out.append(_num_str(v))
            else:
                raise _KqRuntimeError(
                    f"{_jq_type(v)} is not valid in a tsv row"
                )
        return "\t".join(out)
    raise _KqRuntimeError(f"unknown format @{name}")


def _sh_word(v: Any) -> str:
    """One @sh shell word: strings quoted, scalars via tostring, and
    composites are an error (jq parity)."""
    if isinstance(v, str):
        return "'" + v.replace("'", "'\\''") + "'"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return _num_str(v)
    raise _KqRuntimeError(f"{_jq_type(v)} can not be escaped for shell")


_FORMATS = {"text", "json", "base64", "base64d", "uri", "html", "sh", "csv", "tsv"}


def _to_entries(value: Any) -> list:
    if not isinstance(value, dict):
        raise _KqRuntimeError("to_entries over non-object")
    return [{"key": k, "value": v} for k, v in value.items()]


def _from_entries(value: Any) -> dict:
    if not isinstance(value, list):
        raise _KqRuntimeError("from_entries over non-array")
    out: dict = {}
    for e in value:
        if not isinstance(e, dict):
            raise _KqRuntimeError("from_entries element is not an object")
        # jq: key = .key // .k // .name // .Name (null/false FALL
        # THROUGH, unlike presence checks); value uses has()
        k = None
        for kk in ("key", "k", "name", "Name"):
            cand = e.get(kk)
            if cand is not None and cand is not False:
                k = cand
                break
        v = None
        for vk in ("value", "v"):
            if vk in e:
                v = e[vk]
                break
        if k is None:
            raise _KqRuntimeError("from_entries element has no key")
        if isinstance(k, bool):
            k = "true" if k else "false"
        elif isinstance(k, (int, float)):
            k = _num_str(k)
        elif not isinstance(k, str):
            raise _KqRuntimeError("from_entries key is not a scalar")
        out[k] = v
    return out


def _all_paths_vals(value: Any, prefix: tuple = ()):
    """Yield (path, sub-value) pairs, jq paths order (document order,
    parents before children; the root [] excluded)."""
    if isinstance(value, dict):
        for k, v in value.items():
            yield list(prefix) + [k], v
            yield from _all_paths_vals(v, prefix + (k,))
    elif isinstance(value, list):
        for i, v in enumerate(value):
            yield list(prefix) + [i], v
            yield from _all_paths_vals(v, prefix + (i,))


def _all_paths(value: Any):
    for p, _v in _all_paths_vals(value):
        yield p


def _getpath(value: Any, path: list) -> Any:
    cur = value
    for seg in path:
        if cur is None:
            return None
        if isinstance(cur, dict):
            if not isinstance(seg, str):
                raise _KqRuntimeError("cannot index object with number")
            cur = cur.get(seg)
        elif isinstance(cur, list):
            if isinstance(seg, bool) or not isinstance(seg, (int, float)):
                raise _KqRuntimeError("cannot index array with string")
            i = int(seg)
            n = len(cur)
            if i < 0:
                i += n
            cur = cur[i] if 0 <= i < n else None
        else:
            raise _KqRuntimeError(
                f"cannot index {_jq_type(cur)} with path segment"
            )
    return cur


def _flatten(value: Any, depth: float) -> list:
    if not isinstance(value, list):
        raise _KqRuntimeError("flatten over non-array")
    out: list = []
    for v in value:
        if isinstance(v, list) and depth > 0:
            out.extend(_flatten(v, depth - 1))
        else:
            out.append(v)
    return out


def _collect_ast_paths(node: Any, value: Any, env: Optional[dict] = None):
    """Paths addressed by a path expression (the subset del() and the
    assignment family use: ``.a.b``, ``.a[0]``, ``.a[]``, commas and
    pipes of those, ``select(cond)`` stages, and the ``?`` suppressor
    — ``.a?``/``(expr)?`` drops error branches instead of aborting, so
    ``.a? = x`` on a scalar input yields the input unchanged like jq).
    Raises for non-path expressions like jq's "Invalid path
    expression"; slices are not in the grammar (see the module
    docstring's lhs-subset note)."""
    env = env or {}
    if isinstance(node, Comma):
        for part in node.parts:
            yield from _collect_ast_paths(part, value, env)
        return
    if isinstance(node, Pipe):
        def rec(stages, prefix, val):
            if not stages:
                yield list(prefix)
                return
            for sub in _collect_ast_paths(stages[0], val, env):
                yield from rec(
                    stages[1:], list(prefix) + sub, _getpath(val, sub)
                )

        yield from rec(list(node.stages), [], value)
        return
    if isinstance(node, Optional_):
        # `(expr)?` — suppress path-collection errors: the erroring
        # branches contribute no paths (jq: `paths(.a?)` on 5 is empty)
        try:
            yield from list(_collect_ast_paths(node.expr, value, env))
        except _KqRuntimeError:
            return
        return
    if isinstance(node, Select):
        # `select(cond)` in path position addresses the identity path
        # for every truthy cond output — the lhs shape
        # `(.a | select(. == null)) = x` uses
        for out in _eval(node.cond, value, env):
            if out is not None and out is not False:
                yield []
        return
    if not isinstance(node, Path):
        raise _KqRuntimeError("invalid path expression")
    optional = node.optional
    prefixes: List[tuple] = [()]
    cur_vals: List[Any] = [value]
    for op in node.ops:
        nxt_p: List[tuple] = []
        nxt_v: List[Any] = []
        for pref, cur in zip(prefixes, cur_vals):
            if isinstance(op, Field):
                if cur is not None and not isinstance(cur, dict):
                    if optional:
                        continue  # `?`: drop the erroring branch
                    # keep the path: _setpath raises the jq error
                nxt_p.append(pref + (op.name,))
                nxt_v.append(cur.get(op.name) if isinstance(cur, dict) else None)
            elif isinstance(op, Index):
                if cur is not None and not isinstance(cur, list) and optional:
                    continue
                nxt_p.append(pref + (op.i,))
                nxt_v.append(
                    cur[op.i]
                    if isinstance(cur, list) and -len(cur) <= op.i < len(cur)
                    else None
                )
            elif isinstance(op, Iterate):
                if isinstance(cur, dict):
                    for k, v in cur.items():
                        nxt_p.append(pref + (k,))
                        nxt_v.append(v)
                elif isinstance(cur, list):
                    for i, v in enumerate(cur):
                        nxt_p.append(pref + (i,))
                        nxt_v.append(v)
                elif cur is None:
                    continue
                elif optional:
                    continue  # `.a[]?` over a non-iterable: no paths
                else:
                    raise _KqRuntimeError(
                        f"cannot iterate over {_jq_type(cur)}"
                    )
            else:
                raise _KqRuntimeError("invalid path expression")
        prefixes, cur_vals = nxt_p, nxt_v
    for pref in prefixes:
        yield list(pref)


def _kq_deep_copy(x: Any) -> Any:
    t = type(x)
    if t is dict:
        return {k: _kq_deep_copy(v) for k, v in x.items()}
    if t is list:
        return [_kq_deep_copy(v) for v in x]
    return x


def _setpath(value: Any, path: list, newval: Any) -> Any:
    """jq setpath: copy-on-write along the path, creating objects/array
    slots as needed (null-padded like jq)."""
    if not path:
        return newval
    seg = _norm_seg(path[0])
    if isinstance(seg, str):
        if value is None:
            base: Any = {}
        elif isinstance(value, dict):
            base = dict(value)
        else:
            raise _KqRuntimeError(
                f"cannot set field of {_jq_type(value)}"
            )
        base[seg] = _setpath(base.get(seg), path[1:], newval)
        return base
    i = seg
    if value is None:
        lst: list = []
    elif isinstance(value, list):
        lst = list(value)
    else:
        raise _KqRuntimeError(f"cannot index {_jq_type(value)} with number")
    if i < 0:
        i += len(lst)
        if i < 0:
            raise _KqRuntimeError("out of bounds negative array index")
    while len(lst) <= i:
        lst.append(None)
    lst[i] = _setpath(lst[i], path[1:], newval)
    return lst


def _norm_seg(seg: Any) -> Any:
    """Validate/normalize a path segment: strings stay, numbers
    truncate to int (jq numbers are doubles), anything else —
    including bools — is an invalid path segment."""
    if isinstance(seg, str):
        return seg
    if not isinstance(seg, bool) and isinstance(seg, (int, float)):
        return int(seg)
    raise _KqRuntimeError(f"invalid path segment {_jq_type(seg)}")


def _p_key(path: list):
    # total-order sortable key across str/int segments
    return tuple(
        (0, seg, "") if isinstance(seg, int) else (1, 0, seg) for seg in path
    )


def _delpaths(value: Any, paths: List[list]) -> Any:
    """Delete paths (longest/rightmost first so indices stay valid)."""
    norm = [[_norm_seg(seg) for seg in path] for path in paths]
    out = _kq_deep_copy(value)
    for path in sorted(norm, key=lambda p: (len(p), _p_key(p)), reverse=True):
        cur = out
        ok = True
        for seg in path[:-1]:
            if isinstance(cur, dict) and isinstance(seg, str) and seg in cur:
                cur = cur[seg]
            elif isinstance(cur, list) and isinstance(seg, int) and 0 <= seg < len(cur):
                cur = cur[seg]
            else:
                ok = False
                break
        if not ok or not path:
            continue
        last = path[-1]
        if isinstance(cur, dict) and isinstance(last, str):
            cur.pop(last, None)
        elif isinstance(cur, list) and isinstance(last, int):
            if -len(cur) <= last < len(cur):
                del cur[last]
    return out


_RE_FLAG_MAP = {"i": re.IGNORECASE, "x": re.VERBOSE, "s": re.DOTALL, "m": re.MULTILINE}

#: map_values' "empty output deletes" sentinel
_MISSING_V = object()


def _indices(value: Any, needle: Any) -> list:
    """jq indices: substring starts (string), element or subsequence
    starts (array)."""
    out: list = []
    if isinstance(value, str):
        if not isinstance(needle, str) or not needle:
            raise _KqRuntimeError("indices needle must be a non-empty string")
        i = value.find(needle)
        while i != -1:
            out.append(i)
            i = value.find(needle, i + 1)
        return out
    if isinstance(value, list):
        if isinstance(needle, list):
            if not needle:
                return []
            n = len(needle)
            for i in range(len(value) - n + 1):
                if all(_json_equal(value[i + j], needle[j]) for j in range(n)):
                    out.append(i)
            return out
        for i, v in enumerate(value):
            if _json_equal(v, needle):
                out.append(i)
        return out
    if value is None:
        return []
    raise _KqRuntimeError(f"cannot get indices of {_jq_type(value)}")


def _regex(pattern: Any, flags: Any):
    """Compile a jq regex + flag string; returns (compiled, global)."""
    if not isinstance(pattern, str):
        raise _KqRuntimeError("regex must be a string")
    g = False
    f = 0
    for ch in flags or "":
        if ch == "g":
            g = True
        elif ch in _RE_FLAG_MAP:
            f |= _RE_FLAG_MAP[ch]
        elif ch == "n":
            pass  # ignore-empty-matches: harmless to ignore
        else:
            raise _KqRuntimeError(f"unsupported regex flag {ch!r}")
    # jq speaks Oniguruma: named groups are (?<name>...), which Python
    # spells (?P<name>...).  Leave lookbehinds (?<=, (?<! alone.
    translated = re.sub(r"\(\?<(?![=!])", "(?P<", pattern)
    try:
        return re.compile(translated, f), g
    except re.error as exc:
        raise _KqRuntimeError(f"bad regex: {exc}") from exc


def _capture_obj(m: "re.Match") -> dict:
    out = {}
    for name, idx in (m.re.groupindex or {}).items():
        out[name] = m.group(idx)
    return out


def _sub_impl(value, pat, flags, repl_eval, global_) -> Iterator[str]:
    """sub/gsub: the replacement is a FILTER evaluated with the capture
    object as input (jq lets it interpolate named groups).  Iterative —
    multi-output replacements fan out via itertools.product like jq's
    stream semantics, without one generator frame per match."""
    import itertools

    if not isinstance(value, str):
        raise _KqRuntimeError("sub on non-string")
    rx, g2 = _regex(pat, flags)
    global_ = global_ or g2
    matches = []
    pos = 0
    while pos <= len(value):
        m = rx.search(value, pos)
        if m is None:
            break
        matches.append(m)
        if not global_:
            break
        pos = m.end() if m.end() > m.start() else m.start() + 1
    if not matches:
        yield value
        return
    option_sets = []
    for m in matches:
        opts = list(repl_eval(_capture_obj(m)))
        if not all(isinstance(o, str) for o in opts):
            raise _KqRuntimeError("sub replacement must be a string")
        if not opts:
            return  # empty replacement stream -> no outputs (jq)
        option_sets.append(opts)
    for combo in itertools.product(*option_sets):
        out = []
        last = 0
        for m, rep in zip(matches, combo):
            out.append(value[last:m.start()])
            out.append(rep)
            last = max(m.end(), last)
        out.append(value[last:])
        yield "".join(out)


def _regex_split(value: str, rx) -> list:
    """Split on regex matches WITHOUT interleaving capture groups
    (Python re.split would; jq never does)."""
    out = []
    last = 0
    pos = 0
    while pos <= len(value):
        m = rx.search(value, pos)
        if m is None:
            break
        out.append(value[last:m.start()])
        last = m.end()
        pos = m.end() if m.end() > m.start() else m.start() + 1
    out.append(value[last:])
    return out


def _regex_stream(name: str, value: str, pat: Any, fl: Any):
    """Shared machinery for capture/match (per-match objects, honoring
    the g flag) and splits (group-free splitting) — both arities route
    here so their semantics cannot drift apart."""
    rx, g = _regex(pat, fl)
    if name == "splits":
        yield from _regex_split(value, rx)
        return
    shape = _capture_obj if name == "capture" else _match_obj
    pos = 0
    while pos <= len(value):
        m = rx.search(value, pos)
        if m is None:
            break
        yield shape(m)
        if not g:
            break
        pos = m.end() if m.end() > m.start() else m.start() + 1


def _match_obj(m: "re.Match") -> dict:
    names = {idx: name for name, idx in (m.re.groupindex or {}).items()}
    captures = []
    for i in range(1, (m.re.groups or 0) + 1):
        g = m.group(i)
        captures.append(
            {
                "offset": m.start(i) if g is not None else -1,
                "length": len(g) if g is not None else 0,
                "string": g,
                "name": names.get(i),
            }
        )
    return {
        "offset": m.start(),
        "length": len(m.group(0)),
        "string": m.group(0),
        "captures": captures,
    }


def _pattern_vars(pattern) -> List[str]:
    kind = pattern[0]
    if kind == "$":
        return [pattern[1]]
    if kind == "arr":
        return [n for sub in pattern[1] for n in _pattern_vars(sub)]
    return [n for _, sub in pattern[1] for n in _pattern_vars(sub)]


def _alt_bind_outputs(
    patterns: Tuple[Any, ...], bound: Any, env: dict, run
) -> Iterator[Any]:
    """The jq ``?//`` protocol, shared by as/reduce/foreach: try each
    alternative in order; a destructuring or evaluation error moves to
    the next (only the last alternative's errors propagate).  Every
    variable named in any alternative is in scope, null when the
    matching pattern does not bind it.  ``run(e2)`` returns the body's
    output iterator; evaluation stays lazy, and — like jq's
    backtracking — outputs already yielded before a mid-stream error
    stand while the next alternative re-runs the body from the start."""
    allvars = [n for p in patterns for n in _pattern_vars(p)]
    last = len(patterns) - 1
    for i, pat in enumerate(patterns):
        e2 = dict(env)
        for n in allvars:
            e2[n] = None
        try:
            _bind_pattern(pat, bound, e2)
        except _KqRuntimeError:
            if i == last:
                raise
            continue
        it = run(e2)
        erred = False
        while True:
            try:
                out = next(it)
            except StopIteration:
                break
            except _KqRuntimeError:
                if i == last:
                    raise
                erred = True
                break
            yield out
        if not erred:
            return


def _fold_bind_step(
    update: Any, acc: Any, patterns: Tuple[Any, ...], x: Any, env: dict
) -> Any:
    """One reduce step with destructuring: bind ``x`` via the first
    ``?//`` alternative whose destructuring AND update succeed (errors
    of the last alternative propagate)."""
    if len(patterns) == 1:
        e2 = dict(env)
        _bind_pattern(patterns[0], x, e2)
        return _fold_step(update, acc, e2)

    def run(e2):
        # generator so the update's error raises inside the retry
        # protocol's next(), not at run() call time
        yield _fold_step(update, acc, e2)

    out = acc
    for out in _alt_bind_outputs(patterns, x, env, run):
        pass
    return out


def _foreach_alt_step(node: "Foreach", acc: Any, x: Any, env: dict):
    """One foreach step under ``?//`` alternatives: returns the new
    accumulator and this step's outputs (one step's output set is
    collected so the accumulator can advance; the *source* stream
    stays lazy)."""
    box = {"acc": acc}

    def run(e2):
        new_acc = _fold_step(node.update, acc, e2)
        box["acc"] = new_acc
        if node.extract is None:
            yield new_acc
        else:
            yield from _eval(node.extract, new_acc, e2)

    outs = list(_alt_bind_outputs(node.patterns, x, env, run))
    return box["acc"], outs


def _fold_step(update: Any, acc: Any, env: dict) -> Any:
    """One reduce/foreach step: the accumulator becomes the LAST output
    of the update filter (jq folds this way; empty output -> null,
    jq 1.6 behavior)."""
    out = None
    for out in _eval(update, acc, env):
        pass
    return out


def _eval_call(node: Call, value: Any, env: dict) -> Iterator[Any]:
    fn = env.get(("fn", node.name, len(node.args)))
    if fn is None:
        raise _KqRuntimeError(f"{node.name}/{len(node.args)} is not defined")
    params, body, def_env = fn

    def bind(i: int, bound: dict) -> Iterator[Any]:
        if i == len(params):
            call_env = dict(def_env)
            # recursion: the function sees itself
            call_env[("fn", node.name, len(params))] = fn
            call_env.update(bound)
            yield from _eval(body, value, call_env)
            return
        p, arg = params[i], node.args[i]
        if p.startswith("$"):
            # value parameter: cartesian over the argument's outputs
            # (jq semantics), evaluated in the CALLER's environment
            for v in _eval(arg, value, env):
                bound[p[1:]] = v
                yield from bind(i + 1, bound)
            return
        # bare filter parameter: a 0-ary closure over the caller env
        bound[("fn", p, 0)] = ((), arg, env)
        yield from bind(i + 1, bound)

    yield from bind(0, {})


def _eval_object(entries, i, value, acc, env) -> Iterator[Any]:
    if i == len(entries):
        yield dict(acc)
        return
    key, val = entries[i]
    keys = [key] if isinstance(key, str) else list(_eval(key, value, env))
    for k in keys:
        if not isinstance(k, str):
            raise _KqRuntimeError("object key must be a string")
        for v in _eval(val, value, env):
            acc[k] = v
            yield from _eval_object(entries, i + 1, value, acc, env)


def _eval_func(node: Func, value: Any, env: dict) -> Iterator[Any]:
    name = node.name
    if len(node.args) >= 2:
        yield from _eval_func_n(node, value, env)
        return
    if name == "recurse":
        # jq: def recurse(f): ., (f | recurse(f));  `..` is recurse/0
        # with f = .[]? (children of arrays/objects, never an error)
        def gen(x):
            yield x
            if node.args:
                for nx in _eval(node.args[0], x, env):
                    yield _Recur(nx)
            elif isinstance(x, list):
                for nx in x:
                    yield _Recur(nx)
            elif isinstance(x, dict):
                for nx in x.values():
                    yield _Recur(nx)

        yield from _trampoline(gen, value)
        return
    if node.args:
        arg = node.args[0]
        if name == "has":
            for k in _eval(arg, value, env):
                if isinstance(value, dict) and isinstance(k, str):
                    yield k in value
                elif isinstance(value, list) and isinstance(k, int):
                    yield 0 <= k < len(value)
                else:
                    raise _KqRuntimeError(f"cannot check has() on {_jq_type(value)}")
        elif name == "map":
            if not isinstance(value, list):
                raise _KqRuntimeError("map over non-array")
            out = []
            for item in value:
                out.extend(_eval(arg, item, env))
            yield out
        elif name in ("any", "all"):
            if not isinstance(value, list):
                raise _KqRuntimeError(f"{name} over non-array")
            results = []
            for item in value:
                results.extend(_truthy(v) for v in _eval(arg, item, env))
            yield any(results) if name == "any" else all(results)
        elif name in ("test", "startswith", "endswith", "split"):
            if not isinstance(value, str):
                raise _KqRuntimeError(f"{name} on non-string")
            for pat in _eval(arg, value, env):
                if not isinstance(pat, str):
                    raise _KqRuntimeError(f"{name} pattern must be a string")
                if name == "test":
                    yield re.search(pat, value) is not None
                elif name == "startswith":
                    yield value.startswith(pat)
                elif name == "endswith":
                    yield value.endswith(pat)
                else:
                    yield value.split(pat)
        elif name == "contains":
            for b in _eval(arg, value, env):
                yield _contains(value, b)
        elif name == "join":
            if not isinstance(value, list):
                raise _KqRuntimeError("join over non-array")
            for sep in _eval(arg, value, env):
                if not isinstance(sep, str):
                    raise _KqRuntimeError("join separator must be a string")
                yield sep.join(
                    "" if x is None else (x if isinstance(x, str) else _tostring(x))
                    for x in value
                )
        elif name in ("sort_by", "min_by", "max_by"):
            if not isinstance(value, list):
                raise _KqRuntimeError(f"{name} over non-array")
            import functools

            def key_of(item):
                return list(_eval(arg, item, env))

            decorated = [(key_of(x), x) for x in value]
            cmp = functools.cmp_to_key(lambda p, q: _jq_cmp(p[0], q[0]))
            if name == "sort_by":
                yield [x for _, x in sorted(decorated, key=cmp)]
            elif not decorated:
                yield None
            elif name == "min_by":
                yield min(decorated, key=cmp)[1]
            else:
                yield max(decorated, key=cmp)[1]
        elif name == "range":
            for n in _eval(arg, value, env):
                if isinstance(n, bool) or not isinstance(n, (int, float)):
                    raise _KqRuntimeError("range over non-number")
                i = 0
                while i < n:
                    yield i
                    i += 1
        elif name == "error":
            for msg in _eval(arg, value, env):
                raise _KqRuntimeError(str(msg), msg, True)
        elif name == "with_entries":
            # to_entries | map(f) | from_entries
            entries = _to_entries(value)
            mapped = []
            for e in entries:
                mapped.extend(_eval(arg, e, env))
            yield _from_entries(mapped)
        elif name == "group_by":
            if not isinstance(value, list):
                raise _KqRuntimeError("group_by over non-array")
            import functools

            keyed = [(list(_eval(arg, v, env)), v) for v in value]
            keyed.sort(
                key=functools.cmp_to_key(lambda p, q: _jq_cmp(p[0], q[0]))
            )
            out = []
            for i, (k, v) in enumerate(keyed):
                if i and _json_equal(k, keyed[i - 1][0]):
                    out[-1].append(v)
                else:
                    out.append([v])
            yield out
        elif name == "unique_by":
            if not isinstance(value, list):
                raise _KqRuntimeError("unique_by over non-array")
            import functools

            keyed = [(list(_eval(arg, v, env)), v) for v in value]
            keyed.sort(
                key=functools.cmp_to_key(lambda p, q: _jq_cmp(p[0], q[0]))
            )
            out = []
            for i, (k, v) in enumerate(keyed):
                if not (i and _json_equal(k, keyed[i - 1][0])):
                    out.append(v)
            yield out
        elif name == "map_values":
            # .[] |= f : first output of f per value; empty deletes
            if isinstance(value, dict):
                out = {}
                for k, v in value.items():
                    res = next(iter(_eval(arg, v, env)), _MISSING_V)
                    if res is not _MISSING_V:
                        out[k] = res
                yield out
            elif isinstance(value, list):
                outl = []
                for v in value:
                    res = next(iter(_eval(arg, v, env)), _MISSING_V)
                    if res is not _MISSING_V:
                        outl.append(res)
                yield outl
            else:
                raise _KqRuntimeError("map_values over non-iterable")
        elif name in ("ltrimstr", "rtrimstr"):
            for pre in _eval(arg, value, env):
                if not isinstance(value, str) or not isinstance(pre, str):
                    yield value
                elif name == "ltrimstr":
                    yield value[len(pre):] if value.startswith(pre) else value
                else:
                    yield value[: -len(pre)] if pre and value.endswith(pre) else value
        elif name == "getpath":
            for pth in _eval(arg, value, env):
                if not isinstance(pth, list):
                    raise _KqRuntimeError("getpath arg must be an array")
                yield _getpath(value, pth)
        elif name == "flatten":
            for d in _eval(arg, value, env):
                if isinstance(d, bool) or not isinstance(d, (int, float)) or d < 0:
                    raise _KqRuntimeError("flatten depth must be a number >= 0")
                yield _flatten(value, d)
        elif name == "in":
            for xs in _eval(arg, value, env):
                if isinstance(xs, dict):
                    yield isinstance(value, str) and value in xs
                elif isinstance(xs, list):
                    yield (
                        not isinstance(value, bool)
                        and isinstance(value, (int, float))
                        and 0 <= int(value) < len(xs)
                    )
                else:
                    raise _KqRuntimeError(f"cannot check in() on {_jq_type(xs)}")
        elif name == "inside":
            for b in _eval(arg, value, env):
                yield _contains(b, value)
        elif name == "splits":
            if not isinstance(value, str):
                raise _KqRuntimeError("splits on non-string")
            for pat in _eval(arg, value, env):
                yield from _regex_stream("splits", value, pat, None)
        elif name in ("index", "rindex", "indices"):
            for needle in _eval(arg, value, env):
                idxs = _indices(value, needle)
                if name == "indices":
                    yield idxs
                elif name == "index":
                    yield idxs[0] if idxs else None
                else:
                    yield idxs[-1] if idxs else None
        elif name in ("capture", "match"):
            if not isinstance(value, str):
                raise _KqRuntimeError(f"{name} on non-string")
            for pat in _eval(arg, value, env):
                yield from _regex_stream(name, value, pat, None)
        elif name == "del":
            pths = list(_collect_ast_paths(arg, value, env))
            yield _delpaths(value, pths)
        elif name == "path":
            for pth in _collect_ast_paths(arg, value, env):
                yield pth
        elif name == "delpaths":
            for plist in _eval(arg, value, env):
                if not isinstance(plist, list) or not all(
                    isinstance(pp, list) for pp in plist
                ):
                    raise _KqRuntimeError("delpaths arg must be an array of paths")
                yield _delpaths(value, plist)
        elif name == "paths":
            for p, node_val in _all_paths_vals(value):
                if any(_truthy(x) for x in _eval(arg, node_val, env)):
                    yield p
        else:  # pragma: no cover
            raise _KqRuntimeError(f"unknown function {name}")
        return

    # zero-arg builtins
    if name == "error":
        # jq: the input becomes the error (try error catch . round-trip
        # preserves the VALUE, not a stringification)
        raise _KqRuntimeError(str(value), value, True)
    if name == "length":
        if value is None:
            yield 0
        elif isinstance(value, bool):
            raise _KqRuntimeError("boolean has no length")
        elif isinstance(value, (int, float)):
            yield abs(value)
        elif isinstance(value, (str, list, dict)):
            yield len(value)
        else:
            raise _KqRuntimeError("no length")
    elif name == "keys":
        if isinstance(value, dict):
            yield sorted(value)
        elif isinstance(value, list):
            yield list(range(len(value)))
        else:
            raise _KqRuntimeError("keys on non-object")
    elif name == "values":
        if isinstance(value, dict):
            yield [value[k] for k in sorted(value)]
        elif isinstance(value, list):
            yield list(value)
        else:
            raise _KqRuntimeError("values on non-object")
    elif name == "type":
        yield _jq_type(value)
    elif name == "tostring":
        yield value if isinstance(value, str) else _tostring(value)
    elif name == "tonumber":
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            yield value
        elif isinstance(value, str):
            try:
                yield float(value) if "." in value or "e" in value.lower() else int(value)
            except ValueError:
                raise _KqRuntimeError(f"cannot parse {value!r} as number") from None
        else:
            raise _KqRuntimeError(f"cannot parse {_jq_type(value)} as number")
    elif name == "not":
        yield not _truthy(value)
    elif name == "empty":
        return
    elif name == "input":
        it = env.get(_INPUTS_KEY)
        if it is None:
            raise _KqRuntimeError("No more inputs")
        try:
            yield next(it)
        except StopIteration:
            raise _KqRuntimeError("No more inputs") from None
    elif name == "inputs":
        it = env.get(_INPUTS_KEY)
        if it is not None:
            yield from it
    elif name == "to_entries":
        yield _to_entries(value)
    elif name == "from_entries":
        yield _from_entries(value)
    elif name == "paths":
        yield from _all_paths(value)
    elif name == "leaf_paths":
        for p, v in _all_paths_vals(value):
            if not isinstance(v, (dict, list)):
                yield p
    elif name == "flatten":
        yield _flatten(value, float("inf"))
    elif name == "explode":
        if not isinstance(value, str):
            raise _KqRuntimeError("explode on non-string")
        yield [ord(c) for c in value]
    elif name == "implode":
        if not isinstance(value, list):
            raise _KqRuntimeError("implode on non-array")
        try:
            yield "".join(chr(int(c)) for c in value)
        except (TypeError, ValueError) as exc:
            raise _KqRuntimeError(f"implode: {exc}") from exc
    elif name == "infinite":
        yield float("inf")
    elif name == "nan":
        yield float("nan")
    elif name == "isnan":
        yield isinstance(value, float) and math.isnan(value)
    elif name == "isinfinite":
        yield isinstance(value, float) and math.isinf(value)
    elif name == "isnormal":
        yield (
            isinstance(value, (int, float))
            and not isinstance(value, bool)
            and not math.isnan(value)
            and not math.isinf(value)
            and value != 0
        )
    elif name == "utf8bytelength":
        if not isinstance(value, str):
            raise _KqRuntimeError("utf8bytelength on non-string")
        yield len(value.encode("utf-8"))
    elif name in ("trim", "ltrim", "rtrim"):
        if not isinstance(value, str):
            raise _KqRuntimeError(f"{name} on non-string")
        yield (
            value.strip()
            if name == "trim"
            else value.lstrip() if name == "ltrim" else value.rstrip()
        )
    elif name == "now":
        import time as _time

        yield _time.time()
    elif name in ("todate", "todateiso8601"):
        import datetime as _dt

        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise _KqRuntimeError("todate requires a number")
        try:
            t = _dt.datetime.fromtimestamp(value, _dt.timezone.utc)
        except (ValueError, OverflowError, OSError) as exc:
            raise _KqRuntimeError(f"todate: {exc}") from exc
        yield t.strftime("%Y-%m-%dT%H:%M:%SZ")
    elif name in ("fromdate", "fromdateiso8601"):
        import datetime as _dt

        if not isinstance(value, str):
            raise _KqRuntimeError("fromdate requires a string")
        try:
            t = _dt.datetime.strptime(value, "%Y-%m-%dT%H:%M:%SZ")
        except ValueError:
            # tolerate fractional seconds (k8s timestamps carry them)
            try:
                t = _dt.datetime.strptime(value, "%Y-%m-%dT%H:%M:%S.%fZ")
            except ValueError as exc:
                raise _KqRuntimeError(f"fromdate: {exc}") from exc
        yield int(t.replace(tzinfo=_dt.timezone.utc).timestamp())
    elif name == "add":
        if not isinstance(value, list):
            raise _KqRuntimeError("add over non-array")
        acc: Any = None
        for item in value:
            acc = _arith("+", acc, item)
        yield acc
    elif name in ("any", "all"):
        if not isinstance(value, list):
            raise _KqRuntimeError(f"{name} over non-array")
        yield any(_truthy(v) for v in value) if name == "any" else all(
            _truthy(v) for v in value
        )
    elif name == "first":
        if not isinstance(value, list):
            raise _KqRuntimeError("first over non-array")
        if not value:
            raise _KqRuntimeError("first of empty array")
        yield value[0]
    elif name == "last":
        if not isinstance(value, list):
            raise _KqRuntimeError("last over non-array")
        if not value:
            raise _KqRuntimeError("last of empty array")
        yield value[-1]
    elif name in ("min", "max"):
        if not isinstance(value, list):
            raise _KqRuntimeError(f"{name} over non-array")
        if not value:
            yield None
        else:
            import functools

            key = functools.cmp_to_key(_jq_cmp)
            yield (min if name == "min" else max)(value, key=key)
    elif name in ("sort", "unique"):
        if not isinstance(value, list):
            raise _KqRuntimeError(f"{name} over non-array")
        import functools

        key = functools.cmp_to_key(_jq_cmp)
        out = sorted(value, key=key)
        if name == "unique":
            dedup: List[Any] = []
            for x in out:
                if not dedup or not _json_equal(dedup[-1], x):
                    dedup.append(x)
            out = dedup
        yield out
    elif name == "reverse":
        if isinstance(value, list):
            yield list(reversed(value))
        elif isinstance(value, str):
            yield value[::-1]
        else:
            raise _KqRuntimeError("reverse on non-array")
    elif name in ("floor", "ceil", "abs"):
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise _KqRuntimeError(f"{name} on non-number")
        yield {
            "floor": math.floor,
            "ceil": math.ceil,
            "abs": abs,
        }[name](value)
    elif name in ("ascii_downcase", "ascii_upcase"):
        if not isinstance(value, str):
            raise _KqRuntimeError(f"{name} on non-string")
        yield value.lower() if name == "ascii_downcase" else value.upper()
    elif name == "tojson":
        import json as _json

        yield _json.dumps(value, separators=(",", ":"))
    elif name == "fromjson":
        import json as _json

        if not isinstance(value, str):
            raise _KqRuntimeError("fromjson on non-string")
        try:
            yield _json.loads(value)
        except ValueError:
            raise _KqRuntimeError("invalid json") from None
    else:  # pragma: no cover
        raise _KqRuntimeError(f"unknown function {name}")


def _tostring(v: Any) -> str:
    import json as _json

    return _json.dumps(v, separators=(",", ":"))


def _contains(a: Any, b: Any) -> bool:
    if isinstance(a, str) and isinstance(b, str):
        return b in a
    if isinstance(a, list) and isinstance(b, list):
        return all(any(_contains(x, y) for x in a) for y in b)
    if isinstance(a, dict) and isinstance(b, dict):
        return all(k in a and _contains(a[k], v) for k, v in b.items())
    return _json_equal(a, b)


def _eval_pipe(stages: Sequence[Any], i: int, value: Any, env: dict) -> Iterator[Any]:
    if i == len(stages):
        yield value
        return
    for out in _eval(stages[i], value, env):
        yield from _eval_pipe(stages, i + 1, out, env)


def _eval_path(ops: Sequence[Any], i: int, value: Any) -> Iterator[Any]:
    if i == len(ops):
        yield value
        return
    op = ops[i]
    if isinstance(op, Field):
        if value is None:
            yield from _eval_path(ops, i + 1, None)
        elif isinstance(value, dict):
            yield from _eval_path(ops, i + 1, value.get(op.name))
        else:
            raise _KqRuntimeError(
                f"cannot index {type(value).__name__} with {op.name!r}"
            )
    elif isinstance(op, Index):
        if value is None:
            yield from _eval_path(ops, i + 1, None)
        elif isinstance(value, list):
            n = len(value)
            j = op.i if op.i >= 0 else n + op.i
            yield from _eval_path(ops, i + 1, value[j] if 0 <= j < n else None)
        else:
            raise _KqRuntimeError(f"cannot index {type(value).__name__} with number")
    else:  # Iterate
        if isinstance(value, list):
            for item in value:
                yield from _eval_path(ops, i + 1, item)
        elif isinstance(value, dict):
            for item in value.values():
                yield from _eval_path(ops, i + 1, item)
        else:
            raise _KqRuntimeError(f"cannot iterate over {type(value).__name__}")


def _json_equal(a: Any, b: Any) -> bool:
    # Avoid bool == int coercion surprises (jq: true != 1).
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b


class Query:
    """Compiled kq query (reference: expression.Query, query.go:28-49)."""

    def __init__(self, src: str):
        self.src = src
        self._ast = _Parser(_tokenize(src), src).parse_query()

    def execute(
        self, value: Any, inputs: Optional[Sequence[Any]] = None
    ) -> Optional[List[Any]]:
        """Run the query; returns the non-null output stream.

        Mirrors reference query.go:48-68: errors swallow the whole result
        (returns None), null outputs are dropped.

        ``inputs`` is the rest-of-stream for ``input``/``inputs`` (jq
        reads them from the file stream after the current document; the
        stage engine evaluates one document, so the default stream is
        empty — ``input`` then errors like jq at end of input).
        """
        out: List[Any] = []
        env: dict = {}
        if inputs is not None:
            env[_INPUTS_KEY] = iter(inputs)
        try:
            for v in _eval(self._ast, value, env):
                if v is None:
                    continue
                out.append(v)
        except (_KqRuntimeError, RecursionError):
            return None
        return out


def compile_query(src: str) -> Query:
    return Query(src)
