"""WebSocket client for the Kubernetes channel protocols.

The client half of server/websocket.py: speaks RFC 6455 with masked
frames plus the k8s conventions — remote-command channels
(``v4/v5.channel.k8s.io``: 0 stdin, 1 stdout, 2 stderr, 3 status
trailer) and per-port port-forward channels
(``portforward.k8s.io``).  Used by ``kwokctl kubectl
exec/attach/port-forward`` (the kubectl seat; reference e2e exercises
the same flows, test/e2e/cases.go:7-50) and by the protocol tests.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import struct
from typing import List, Optional, Tuple

# one source of truth for the protocol vocabulary — utils.wsproto
# defines it for both halves (the server imports the same module);
# drifting copies would break negotiation silently
from kwok_tpu.utils.wsproto import (
    CHAN_ERROR,
    CHAN_STDERR,
    CHAN_STDIN,
    CHAN_STDOUT,
    PORT_FORWARD_PROTOCOLS,
    REMOTE_COMMAND_PROTOCOLS,
    _GUID,
)

__all__ = [
    "WSClient",
    "exec_stream",
    "REMOTE_COMMAND_PROTOCOLS",
    "PORT_FORWARD_PROTOCOLS",
    "CHAN_STDIN",
    "CHAN_STDOUT",
    "CHAN_STDERR",
    "CHAN_ERROR",
]


class WSClient:
    """One upgraded connection (client side, masked frames)."""

    def __init__(
        self,
        host: str,
        port: int,
        path: str,
        protocols: List[str],
        timeout: float = 30.0,
        ssl_context=None,
    ):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        if ssl_context is not None:
            self.sock = ssl_context.wrap_socket(self.sock, server_hostname=host)
        key = base64.b64encode(os.urandom(16)).decode()
        req = (
            f"GET {path} HTTP/1.1\r\n"
            f"Host: {host}:{port}\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n"
            f"Sec-WebSocket-Protocol: {', '.join(protocols)}\r\n"
            "\r\n"
        )
        self.sock.sendall(req.encode())
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise ConnectionError(f"no handshake response: {buf!r}")
            buf += chunk
        head, _, rest = buf.partition(b"\r\n\r\n")
        self.handshake = head.decode(errors="replace")
        self._buf = rest
        status = self.handshake.split("\r\n")[0]
        if "101" not in status:
            # drain the rejection body (a k8s Status JSON) so callers
            # can show its message instead of a raw header dump
            import re

            m = re.search(r"content-length:\s*(\d+)", self.handshake, re.I)
            body = self._buf
            if m:
                want = int(m.group(1))
                while len(body) < want:
                    try:
                        chunk = self.sock.recv(4096)
                    except OSError:
                        break
                    if not chunk:
                        break
                    body += chunk
            self.sock.close()
            raise ConnectionError(
                f"{status}: {body.decode(errors='replace')}".strip(": ")
            )
        accept = base64.b64encode(
            hashlib.sha1((key + _GUID).encode()).digest()
        ).decode()
        if accept not in self.handshake:
            raise ConnectionError("bad Sec-WebSocket-Accept")
        self.protocol: Optional[str] = next(
            (
                line.split(":", 1)[1].strip()
                for line in self.handshake.split("\r\n")
                if line.lower().startswith("sec-websocket-protocol:")
            ),
            None,
        )
        # the timeout covered connect+handshake only: an idle stream
        # (exec waiting on input, quiet attach) must not hit a 30s recv
        # deadline that _read_exact would treat as clean EOF (ADVICE r02)
        self.sock.settimeout(None)

    # ------------------------------------------------------------------ recv

    def _read_exact(self, n: int) -> Optional[bytes]:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except (OSError, ValueError):
                # socket closed (possibly by another thread's close())
                return None
            if not chunk:
                return None
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def recv(self) -> Optional[Tuple[int, bytes]]:
        """Next (opcode, payload); None on close/EOF; answers pings."""
        while True:
            head = self._read_exact(2)
            if head is None:
                return None
            opcode = head[0] & 0x0F
            n = head[1] & 0x7F
            if n == 126:
                ext = self._read_exact(2)
                if ext is None:
                    return None
                n = struct.unpack(">H", ext)[0]
            elif n == 127:
                ext = self._read_exact(8)
                if ext is None:
                    return None
                n = struct.unpack(">Q", ext)[0]
            payload = self._read_exact(n) if n else b""
            if payload is None:
                return None
            if opcode == 0x8:  # close
                return None
            if opcode == 0x9:  # ping
                self.send(payload, opcode=0xA)
                continue
            if opcode == 0xA:  # pong
                continue
            return opcode, payload

    # ------------------------------------------------------------------ send

    def send(self, payload: bytes, opcode: int = 0x2) -> None:
        mask = os.urandom(4)
        head = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head += bytes([0x80 | n])
        elif n < 2**16:
            head += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            head += bytes([0x80 | 127]) + struct.pack(">Q", n)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        self.sock.sendall(head + mask + masked)

    def send_channel(self, channel: int, data: bytes = b"") -> None:
        self.send(bytes([channel]) + data)

    def close(self) -> None:
        try:
            self.send(struct.pack(">H", 1000), opcode=0x8)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def exec_stream(
    host: str,
    port: int,
    path: str,
    stdin: Optional[bytes] = None,
    on_stdout=None,
    on_stderr=None,
    ssl_context=None,
) -> Tuple[int, dict]:
    """Run a remote-command stream to completion: returns (exit_code,
    status_dict).  Exit code decodes the NonZeroExitCode Status trailer
    the way kubectl does."""
    c = WSClient(
        host, port, path, REMOTE_COMMAND_PROTOCOLS, ssl_context=ssl_context
    )
    status: dict = {}
    try:
        if stdin is not None:
            c.send_channel(CHAN_STDIN, stdin)
            if c.protocol == "v5.channel.k8s.io":
                c.send_channel(255, bytes([0]))  # close stdin
        while True:
            msg = c.recv()
            if msg is None:
                break
            _, payload = msg
            if not payload:
                continue
            channel, data = payload[0], payload[1:]
            if channel == CHAN_STDOUT and on_stdout:
                on_stdout(data)
            elif channel == CHAN_STDERR and on_stderr:
                on_stderr(data)
            elif channel == CHAN_ERROR:
                try:
                    status = json.loads(data)
                except ValueError:
                    status = {
                        "status": "Failure",
                        "message": data.decode(errors="replace"),
                    }
    finally:
        c.close()
    if status.get("status") == "Success":
        return 0, status
    for cause in ((status.get("details") or {}).get("causes")) or []:
        if cause.get("reason") == "ExitCode":
            try:
                return int(cause.get("message") or 1), status
            except ValueError:
                break
    return 1, status
