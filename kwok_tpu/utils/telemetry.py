"""SLO telemetry substrate: observed latency histograms + flight recorder.

The reference delegates real observability to the ecosystem — kwokctl
composes a prometheus scrape config and a Jaeger all-in-one around the
cluster (reference pkg/kwokctl/components/prometheus.go:49,
pkg/kwokctl/components/jaeger.go:42) and the components themselves only
expose what client-go/apiserver libraries emit.  This rebuild has no
library emitting request-duration series for it, so this module is the
in-tree substrate every control-plane hot path observes into:

- :class:`HistogramFamily` — a thread-safe *observed* (incremented, not
  CEL-set) latency histogram with a bounded label set, the counterpart
  of the settable CEL collectors in
  ``kwok_tpu/metrics/collectors.py:108``;
- :class:`Telemetry` — the process-global registry; every ``/metrics``
  endpoint in the process (apiserver, fake-kubelet server) appends
  :meth:`Telemetry.expose` to its existing exposition, so one scrape
  sees both the synthetic CR-driven metrics and the observed SLO
  series;
- :class:`FlightRecorder` — a bounded in-memory ring of recent
  per-tick stage breakdowns and slow-request samples (each carrying
  its trace id as an exemplar), served at ``/debug/flightrecorder`` so
  a slow window is diagnosable after the fact without a profiler
  attached.

Design constraints (the tentpole contract):

- **observation-only**: nothing read from a histogram or the recorder
  feeds back into control flow — deterministic-simulation runs
  (kwok_tpu.dst) produce byte-identical trace digests with
  instrumentation armed vs disarmed;
- **monotonic time**: durations are measured with ``time.monotonic()``
  (the ``utils.clock.MonotonicClock`` discipline — never wall time,
  which the kwoklint ``wallclock-deadline`` rule polices in deadline
  arithmetic);
- **cardinality-safe**: label values must come from bounded sets
  (verbs, kinds, APF levels, shard indexes, stage names — never object
  names/uids/namespaces; the kwoklint ``metric-cardinality`` rule
  enforces this at the call sites).  As a runtime backstop a family
  caps its children at :data:`MAX_CHILDREN` and folds the overflow
  into one ``(other)`` series instead of growing without bound;
- **cheap when off**: ``set_enabled(False)`` turns every observe into
  one attribute check (the bench ``obs`` A/B measures the armed
  overhead at <=5% on the store bulk lane).
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

from kwok_tpu.utils.locks import make_lock

__all__ = [
    "DEFAULT_BUCKETS",
    "FlightRecorder",
    "HistogramFamily",
    "JourneyRecorder",
    "Telemetry",
    "enabled",
    "flight_recorder",
    "histogram",
    "journey",
    "registry",
    "set_enabled",
]

#: default latency bounds (seconds): sub-ms store appends up to
#: multi-second catch-up macro-ticks
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)

#: per-family child (label-set) cap — the runtime backstop under the
#: static ``metric-cardinality`` rule.  Hitting it means a call site is
#: feeding unbounded values; the overflow folds into one child so the
#: leak is visible (as ``(other)``) instead of eating memory
MAX_CHILDREN = 64

#: the label-value tuple the overflow folds into
_OTHER = "(other)"


class _Child:
    """One label-set's distribution; guarded by the family lock."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1 = the +Inf bucket
        self.sum = 0.0
        self.count = 0


class HistogramFamily:
    """An observed histogram with a fixed label-name set.

    ``observe(value, *labelvalues)`` increments the matching child's
    bucket (bisect over the sorted bounds), sum and count under one
    short lock hold — safe from any thread, including under the store
    mutex (it acquires nothing else, so it can never participate in a
    lock cycle)."""

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        labelnames: Sequence[str] = (),
        max_children: int = MAX_CHILDREN,
    ):
        self.name = name
        self.help = (help or "").strip()
        self.bounds: Tuple[float, ...] = tuple(sorted(float(b) for b in buckets))
        self.labelnames: Tuple[str, ...] = tuple(labelnames)
        #: per-family child cap; families whose legitimate label
        #: product is wide (verb x kind x level x shard) raise it at
        #: registration — the cap is a leak backstop, not a quota
        self.max_children = int(max_children)
        self._mut = make_lock("utils.telemetry.HistogramFamily._mut")
        self._children: Dict[Tuple[str, ...], _Child] = {}
        #: observations folded into the ``(other)`` overflow child
        self.overflowed = 0

    # ------------------------------------------------------------- observe

    def observe(self, value: float, *labelvalues: str) -> None:
        """Record one observation (seconds).  Extra/missing label
        values are normalized to the declared width so a bad call site
        degrades to a visible mismatch, not a crash on the hot path."""
        if not _STATE.enabled:
            return
        lv = tuple(str(v) for v in labelvalues)
        if len(lv) != len(self.labelnames):
            lv = (lv + ("",) * len(self.labelnames))[: len(self.labelnames)]
        v = float(value)
        if v < 0.0:
            # monotonic races (ring eviction, clock source swap in
            # tests) must not corrupt the distribution
            v = 0.0
        idx = bisect.bisect_left(self.bounds, v)
        with self._mut:
            child = self._children.get(lv)
            if child is None:
                if len(self._children) >= self.max_children:
                    self.overflowed += 1
                    lv = (_OTHER,) * len(self.labelnames) if self.labelnames else ()
                    child = self._children.get(lv)
                if child is None:
                    child = self._children[lv] = _Child(len(self.bounds))
            child.counts[idx] += 1
            child.sum += v
            child.count += 1

    # ------------------------------------------------------------ querying

    def snapshot(self) -> Dict[Tuple[str, ...], Dict[str, object]]:
        """{labelvalues: {"counts", "sum", "count"}} — a consistent
        copy for tests and summaries."""
        with self._mut:
            return {
                lv: {
                    "counts": list(c.counts),
                    "sum": c.sum,
                    "count": c.count,
                }
                for lv, c in self._children.items()
            }

    def total_count(self) -> int:
        with self._mut:
            return sum(c.count for c in self._children.values())

    def clear(self) -> None:
        """Drop every child's observations (tests / registry reset) —
        the family object itself stays live for its import-time
        references."""
        with self._mut:
            self._children.clear()
            self.overflowed = 0

    def quantile(self, q: float) -> Optional[float]:
        """Aggregate quantile estimate across every child (standard
        cumulative-bucket interpolation; the +Inf bucket reports the
        largest finite bound).  None with no observations."""
        with self._mut:
            agg = [0] * (len(self.bounds) + 1)
            total = 0
            for c in self._children.values():
                total += c.count
                for i, n in enumerate(c.counts):
                    agg[i] += n
        if total == 0:
            return None
        target = q * total
        run = 0.0
        for i, n in enumerate(agg):
            prev = run
            run += n
            if run >= target and n:
                if i >= len(self.bounds):
                    return self.bounds[-1] if self.bounds else 0.0
                lo = self.bounds[i - 1] if i else 0.0
                hi = self.bounds[i]
                return lo + (hi - lo) * ((target - prev) / n)
        return self.bounds[-1] if self.bounds else 0.0

    # ---------------------------------------------------------- exposition

    def expose_lines(self) -> List[str]:
        """Prometheus text lines (HELP/TYPE + per-child bucket/sum/
        count), cumulative per le like any real histogram."""
        snap = self.snapshot()
        lines: List[str] = []
        if self.help:
            esc = self.help.replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {self.name} {esc}")
        lines.append(f"# TYPE {self.name} histogram")
        for lv in sorted(snap):
            data = snap[lv]
            base = ",".join(
                f'{k}="{_escape(v)}"' for k, v in zip(self.labelnames, lv)
            )
            run = 0
            for bound, n in zip(
                list(self.bounds) + [float("inf")], data["counts"]
            ):
                run += n
                le = "+Inf" if bound == float("inf") else _fmt(bound)
                sep = "," if base else ""
                lines.append(
                    f'{self.name}_bucket{{{base}{sep}le="{le}"}} {run}'
                )
            lab = f"{{{base}}}" if base else ""
            lines.append(f"{self.name}_sum{lab} {_fmt(data['sum'])}")
            lines.append(f"{self.name}_count{lab} {data['count']}")
        return lines


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


# ------------------------------------------------------------------ recorder


class FlightRecorder:
    """Bounded ring of recent tick stage breakdowns + slow-request
    samples.

    Overwrite-oldest semantics (``deque(maxlen=N)``): the recorder
    always holds the most recent window, never grows, and costs one
    append per record.  Each slow-request sample carries the request's
    trace id (W3C ``traceparent`` / tracer span) as the exemplar
    linking the latency outlier to its distributed trace."""

    #: default ring depth per record kind
    SIZE = int(os.environ.get("KWOK_FLIGHT_RECORDER_N", "256"))

    def __init__(self, size: Optional[int] = None):
        n = self.SIZE if size is None else int(size)
        self.size = max(1, n)
        self._mut = make_lock("utils.telemetry.FlightRecorder._mut")
        self._ticks: deque = deque(maxlen=self.size)
        self._slow: deque = deque(maxlen=self.size)
        #: slow-request gate (seconds); samples below it are not
        #: recorded.  KWOK_SLOW_REQUEST_S overrides the default.
        self.slow_threshold_s = float(
            os.environ.get("KWOK_SLOW_REQUEST_S", "0.5")
        )
        #: requests inspected vs recorded (the gate's visibility)
        self.slow_seen = 0
        self.slow_recorded = 0

    def record_tick(
        self, kind: str, fired: int, stages: Dict[str, float]
    ) -> None:
        """One macro-tick's stage breakdown (seconds per stage)."""
        if not _STATE.enabled:
            return
        entry = {
            "t_mono": time.monotonic(),
            "kind": str(kind),
            "fired": int(fired),
            "stages": {k: round(float(v), 6) for k, v in stages.items()},
        }
        with self._mut:
            self._ticks.append(entry)

    def note_request(
        self,
        verb: str,
        path: str,
        level: str,
        seconds: float,
        trace_id: Optional[str] = None,
        status: Optional[int] = None,
    ) -> None:
        """Threshold-gated slow-request sample.  ``path`` may carry
        object names — the recorder is a bounded debug ring, not a
        metric label set, so per-object detail is exactly what it is
        for."""
        if not _STATE.enabled:
            return
        with self._mut:
            self.slow_seen += 1
            if seconds < self.slow_threshold_s:
                return
            self.slow_recorded += 1
            self._slow.append(
                {
                    "t_mono": time.monotonic(),
                    "verb": str(verb),
                    "path": str(path),
                    "level": str(level or ""),
                    "seconds": round(float(seconds), 6),
                    "trace_id": trace_id or "",
                    "status": status,
                }
            )

    def dump(self) -> Dict[str, object]:
        """The ``/debug/flightrecorder`` body: newest-last lists plus
        the ring geometry so a reader knows the window it is seeing.
        When the process exports to a trace collector, each slow
        sample's trace-id exemplar is rendered as a ``trace_url`` deep
        link into the collector's browser — the one-click hop from "a
        request was slow" to its distributed trace."""
        with self._mut:
            slow = [dict(s) for s in self._slow]
            out = {
                "size": self.size,
                "slow_threshold_s": self.slow_threshold_s,
                "slow_seen": self.slow_seen,
                "slow_recorded": self.slow_recorded,
                "ticks": list(self._ticks),
                "slow_requests": slow,
            }
        base = _collector_base()
        if base:
            for s in slow:
                tid = s.get("trace_id")
                if tid:
                    s["trace_url"] = f"{base}/trace/{tid}"
        return out

    def reset(self) -> None:
        with self._mut:
            self._ticks.clear()
            self._slow.clear()
            self.slow_seen = 0
            self.slow_recorded = 0


def _collector_base() -> str:
    """Base URL of the trace collector this process exports to, or ""
    (the flight recorder and journey surfaces render trace ids as deep
    links when — and only when — a collector is armed)."""
    from kwok_tpu.utils.trace import peek_global

    tracer = peek_global()
    endpoint = (
        tracer.endpoint if tracer is not None and tracer.endpoint else ""
    ) or os.environ.get("KWOK_TRACE_ENDPOINT", "")
    if not endpoint:
        return ""
    return endpoint.split("/v1/traces")[0].rstrip("/")


# ------------------------------------------------------------------ journey


class JourneyRecorder:
    """Bounded per-object lifecycle timeline, keyed by uid.

    Fed observation-only from the store's commit hooks and the watch
    servers' delivery hooks (``cluster/store.py`` ``_note_commit`` /
    ``observe_watch_delivery``): every single-object commit appends one
    ``commit`` hop (rv, event type, phase, committing trace id) and
    every watch-burst flush appends one ``watch`` hop (delivery lag) —
    so ``/debug/journey?kind=&ns=&name=`` answers "what happened to
    THIS pod, when, and under which trace" without touching metric
    label space (per-object detail stays in this bounded ring; kwoklint
    ``metric-cardinality`` forbids it in labels).

    Bounds: at most ``SIZE`` objects (LRU-evicted, counted) with at
    most ``HOPS`` hops each (oldest-dropped, counted); both counters
    surface at ``/metrics`` so truncation is visible, never silent.
    The bulk drain lane deliberately bypasses this recorder (its
    per-batch commit note carries no object), keeping the 1M-pod hot
    path at PR 12's measured overhead."""

    SIZE = int(os.environ.get("KWOK_JOURNEY_N", "512"))
    HOPS = int(os.environ.get("KWOK_JOURNEY_HOPS", "64"))

    def __init__(self, size: Optional[int] = None, hops: Optional[int] = None):
        self.size = max(1, self.SIZE if size is None else int(size))
        self.hops = max(1, self.HOPS if hops is None else int(hops))
        self._mut = make_lock("utils.telemetry.JourneyRecorder._mut")
        #: uid -> {"uid","kind","namespace","name","hops": deque}
        self._objects: "OrderedDict[str, Dict[str, object]]" = OrderedDict()
        #: objects LRU-evicted by the SIZE bound (drop counter)
        self.evicted_objects = 0
        #: hops dropped by a full per-object ring (drop counter)
        self.dropped_hops = 0

    def record(
        self,
        uid: str,
        kind: str,
        namespace: str,
        name: str,
        hop: str,
        dedupe_rv: Optional[int] = None,
        **attrs,
    ) -> None:
        """Append one hop to an object's timeline.  ``dedupe_rv``
        collapses repeats of the same (hop, rv) — several watch streams
        deliver the same commit, and one ``watch`` hop per rv is the
        useful record.  The check scans a small recent window (not just
        the newest entries) because deliveries from independent streams
        interleave with newer commits."""
        if not _STATE.enabled or not uid:
            return
        entry = {
            "hop": str(hop),
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
        }
        entry.update(attrs)
        with self._mut:
            obj = self._objects.get(uid)
            if obj is None:
                if len(self._objects) >= self.size:
                    self._objects.popitem(last=False)
                    self.evicted_objects += 1
                obj = self._objects[uid] = {
                    "uid": uid,
                    "kind": str(kind),
                    "namespace": str(namespace or ""),
                    "name": str(name),
                    "hops": deque(maxlen=self.hops),
                }
            else:
                self._objects.move_to_end(uid)
            ring: deque = obj["hops"]
            if dedupe_rv is not None:
                recent = 0
                for h in reversed(ring):
                    if h.get("hop") == entry["hop"] and h.get("rv") == dedupe_rv:
                        return
                    recent += 1
                    if recent >= 16:
                        break
            if len(ring) == ring.maxlen:
                self.dropped_hops += 1
            ring.append(entry)

    # ------------------------------------------------------------- querying

    @staticmethod
    def _render(obj: Dict[str, object]) -> Dict[str, object]:
        out = {k: v for k, v in obj.items() if k != "hops"}
        out["hops"] = [dict(h) for h in obj["hops"]]
        return out

    def lookup(
        self,
        kind: Optional[str] = None,
        namespace: Optional[str] = None,
        name: Optional[str] = None,
        uid: Optional[str] = None,
    ) -> Optional[Dict[str, object]]:
        """One object's timeline by uid, or by (kind, namespace, name)
        — newest match wins when a name was reused."""
        with self._mut:
            if uid:
                obj = self._objects.get(uid)
                return self._render(obj) if obj is not None else None
            k = (kind or "").lower()
            for obj in reversed(self._objects.values()):
                if k and str(obj["kind"]).lower() not in (
                    k,
                    k.rstrip("s"),
                ):
                    continue
                if namespace is not None and obj["namespace"] != namespace:
                    continue
                if name is not None and obj["name"] != name:
                    continue
                return self._render(obj)
        return None

    def journeys(
        self, kind: Optional[str] = None, limit: int = 20
    ) -> List[Dict[str, object]]:
        """Most-recently-touched timelines, newest first."""
        out: List[Dict[str, object]] = []
        k = (kind or "").lower()
        with self._mut:
            for obj in reversed(self._objects.values()):
                if k and str(obj["kind"]).lower() not in (k, k.rstrip("s")):
                    continue
                out.append(self._render(obj))
                if len(out) >= limit:
                    break
        return out

    def stats(self) -> Dict[str, int]:
        with self._mut:
            return {
                "objects": len(self._objects),
                "size": self.size,
                "hops_per_object": self.hops,
                "evicted_objects": self.evicted_objects,
                "dropped_hops": self.dropped_hops,
            }

    def reset(self) -> None:
        with self._mut:
            self._objects.clear()
            self.evicted_objects = 0
            self.dropped_hops = 0


# ------------------------------------------------------------------ registry


class Telemetry:
    """Process-global family registry + exposition."""

    def __init__(self):
        self._mut = make_lock("utils.telemetry.Telemetry._mut")
        self._families: Dict[str, HistogramFamily] = {}
        self.recorder = FlightRecorder()
        self.journey = JourneyRecorder()

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        labelnames: Sequence[str] = (),
        max_children: int = MAX_CHILDREN,
    ) -> HistogramFamily:
        """Get-or-create (idempotent by name: the first registration's
        geometry wins, so hot paths can call this unconditionally)."""
        with self._mut:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = HistogramFamily(
                    name,
                    help=help,
                    buckets=buckets,
                    labelnames=labelnames,
                    max_children=max_children,
                )
            return fam

    def families(self) -> List[HistogramFamily]:
        with self._mut:
            return list(self._families.values())

    def expose(self) -> str:
        """Prometheus text for every observed family (appended to the
        host process's existing /metrics exposition)."""
        lines: List[str] = []
        for fam in sorted(self.families(), key=lambda f: f.name):
            lines.extend(fam.expose_lines())
        return "\n".join(lines) + ("\n" if lines else "")

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Compact {family: {count, p50_s, p99_s}} for ``/stats`` and
        ``kwokctl get components`` — only families with observations."""
        out: Dict[str, Dict[str, float]] = {}
        for fam in self.families():
            n = fam.total_count()
            if not n:
                continue
            p50 = fam.quantile(0.5)
            p99 = fam.quantile(0.99)
            out[fam.name] = {
                "count": n,
                "p50_s": round(p50, 6) if p50 is not None else 0.0,
                "p99_s": round(p99, 6) if p99 is not None else 0.0,
            }
        return out

    def reset(self) -> None:
        """Clear every family's observations and the recorder contents
        (tests).  Families are cleared IN PLACE, never dropped: hot
        paths hold module-level references bound at import time, and
        replacing the objects would orphan every one of them (observing
        into series no scrape can see)."""
        for fam in self.families():
            fam.clear()
        self.recorder.reset()
        self.journey.reset()


class _State:
    __slots__ = ("enabled",)

    def __init__(self):
        self.enabled = os.environ.get("KWOK_TELEMETRY", "1") not in (
            "0",
            "false",
            "off",
        )


_STATE = _State()
_REGISTRY = Telemetry()


def registry() -> Telemetry:
    return _REGISTRY


def histogram(
    name: str,
    help: str = "",
    buckets: Sequence[float] = DEFAULT_BUCKETS,
    labelnames: Sequence[str] = (),
    max_children: int = MAX_CHILDREN,
) -> HistogramFamily:
    """Shortcut onto the process-global registry."""
    return _REGISTRY.histogram(
        name,
        help=help,
        buckets=buckets,
        labelnames=labelnames,
        max_children=max_children,
    )


def flight_recorder() -> FlightRecorder:
    return _REGISTRY.recorder


def journey() -> JourneyRecorder:
    return _REGISTRY.journey


def set_enabled(on: bool) -> bool:
    """Arm/disarm every observation in the process (the bench A/B and
    the DST neutrality test flip this); returns the previous state."""
    prev = _STATE.enabled
    _STATE.enabled = bool(on)
    return prev


def enabled() -> bool:
    return _STATE.enabled
