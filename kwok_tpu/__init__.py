"""kwok-tpu: a TPU-native cluster-simulation framework.

Re-expresses KWOK's Stage finite-state-machine (reference:
pkg/utils/lifecycle, pkg/kwok/controllers) as a vectorized,
device-resident state-transition kernel in JAX/XLA: every simulated
Node/Pod is one row in a struct-of-arrays; stage matching, weighted
transitions, delay timers and heartbeats run as a single batched tick
on TPU. A host-side CPU engine with identical semantics serves as the
parity oracle and the slow path for arbitrary custom resources.
"""

__version__ = "0.1.0"
