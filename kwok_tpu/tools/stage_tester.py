"""Offline stage tester: apply Stages to one object without a cluster.

Equivalent of reference pkg/tools/stage/stage.go:37-212 (driven by
hack/test_stage/main.go): deterministic fake template funcs render
placeholders like ``<Now>`` / ``<NodeIPWith("node")>`` so outputs are
stable, and the result structure matches the reference's golden files
(kustomize/stage/*/testdata/*.output.yaml) byte-for-structure.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from kwok_tpu.api.types import Stage
from kwok_tpu.engine.lifecycle import Lifecycle, NextEffects

_FAKE_FUNC_NAMES = [
    "NodeIP",
    "NodeName",
    "NodePort",
    "PodIP",
    "NodeIPWith",
    "PodIPWith",
    "Now",
    "now",
    "Version",
]


def _go_repr(v: Any) -> str:
    """Go %#v for the JSON scalar types the fake funcs receive
    (reference stage.go:172-193 wrapFunction)."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return json.dumps(v)
    if v is None:
        return "interface {}(nil)"
    return str(v)


def _wrap_function(name: str):
    def fake(*args: Any) -> str:
        if not args:
            return f"<{name}>"
        return f"<{name}({', '.join(_go_repr(a) for a in args)})>"

    return fake


def fake_funcs() -> Dict[str, Any]:
    return {name: _wrap_function(name) for name in _FAKE_FUNC_NAMES}


def testing_stages(target: Dict[str, Any], stages: List[Stage]) -> Dict[str, Any]:
    """Test stages against a target object (reference stage.go:37-86)."""
    api_version = target.get("apiVersion", "v1")
    kind = target.get("kind", "")
    meta_obj = target.get("metadata") or {}

    out_meta: Dict[str, Any] = {
        "apiGroup": api_version,
        "kind": kind,
        "name": meta_obj.get("name", ""),
    }
    if meta_obj.get("namespace"):
        out_meta["namespace"] = meta_obj["namespace"]

    matching = [
        s
        for s in stages
        if s.resource_ref.api_group == api_version and s.resource_ref.kind == kind
    ]
    lc = Lifecycle(matching)

    labels = meta_obj.get("labels") or {}
    annotations = meta_obj.get("annotations") or {}
    candidates = lc.list_all_possible(labels, annotations, target)

    out_meta["stages"] = [_testing_stage(lc, target, s) for s in candidates]
    return out_meta


def _testing_stage(lc: Lifecycle, target: Dict[str, Any], stage) -> Dict[str, Any]:
    meta: Dict[str, Any] = {"stage": stage.name}

    # Reference bug-compatibility (stage.go:122): delay is evaluated with
    # the *compiled stage* as data, which marshals to {} — so expression
    # overrides always fall back to the static values.
    delay, ok = stage.delay({}, now=None, rng=_ZeroRandom())
    if ok:
        meta["delay"] = int(round(delay * 1e9))  # time.Duration ns in YAML

    weight, ok = stage.weight(target)
    if ok:
        meta["weight"] = weight

    if stage.next is None:
        # The reference's StageNext is a value struct, never nil; a stage
        # without a next block produces an empty effects list.
        meta["next"] = []
        return meta

    effects = NextEffects(stage.next, lc.renderer)
    out: List[Any] = []

    fin = effects.finalizers_patch((target.get("metadata") or {}).get("finalizers") or [])
    if fin is not None:
        out.append(_format_patch(fin))

    if effects.delete:
        out.append({"kind": "delete"})
        meta["next"] = out
        return meta

    for patch in effects.patches(target, fake_funcs()):
        out.append(_format_patch(patch))

    if stage.immediate_next_stage:
        out.append({"kind": "immediate"})

    meta["next"] = out
    return meta


class _ZeroRandom:
    """Deterministic rng: jitter always resolves to the lower bound."""

    def random(self) -> float:
        return 0.0

    def randrange(self, n: int) -> int:
        return 0


def main(argv: Optional[List[str]] = None) -> int:
    """CLI driver (reference hack/test_stage/main.go:46-80): apply
    stage files to one resource YAML, print the outcome structure.

    usage: python -m kwok_tpu.tools.stage_tester OBJECT.yaml STAGE.yaml...
    """
    import argparse
    import sys

    import yaml

    from kwok_tpu.api.loader import load_stages

    p = argparse.ArgumentParser(
        prog="stage-tester",
        description="apply Stages to one object offline, no cluster needed",
    )
    p.add_argument("object", help="YAML file with the target object")
    p.add_argument("stages", nargs="+", help="Stage YAML files")
    args = p.parse_args(argv)

    with open(args.object, "r", encoding="utf-8") as f:
        target = yaml.safe_load(f)
    stages: List[Stage] = []
    for path in args.stages:
        stages.extend(load_stages(path))
    out = testing_stages(target, stages)
    yaml.safe_dump(out, sys.stdout, sort_keys=False)
    return 0


def _format_patch(patch) -> Dict[str, Any]:
    out: Dict[str, Any] = {"kind": "patch", "type": patch.content_type}
    if patch.subresource:
        out["subresource"] = patch.subresource
    out["data"] = patch.data
    if patch.impersonation:
        out["impersonation"] = patch.impersonation
    return out


if __name__ == "__main__":  # pragma: no cover — exercised via CLI test
    import sys

    sys.exit(main())
