"""Shared machinery for the workload controllers.

The reference gets ReplicaSet/Deployment/Job/HPA behavior for free by
composing a real kube-controller-manager into every cluster (reference
pkg/kwokctl/components/kube_controller_manager.go:46); this package is
the rebuild's seat for those app-level control loops.  This module
holds what every loop shares:

- the pod-template revision hash (the ``pod-template-hash`` label a
  Deployment stamps on each ReplicaSet generation — k8s's
  ControllerRevision hash, upstream pkg/controller/deployment/util),
- label-selector handling (``matchLabels`` + ``matchExpressions``
  rendered to the store's selector grammar, so listing a workload's
  pods is one indexed store query),
- controller ownerReferences and owned-by checks (feeding the existing
  GC cascade in controllers/gc_controller.py),
- pod stamping from a workload's ``spec.template`` (the in-cluster
  analog of ctl/scale.py's per-index rendering: same generateName
  uniqueness, no per-pod YAML round-trip),
- ``BulkWriter``: the bulk-mutation lane.  Reconciliation never issues
  per-pod requests — creates/deletes accumulate and flush through
  ``store.bulk`` in large chunks, so scaling a Deployment by 100k
  replicas costs O(replicas / chunk) round-trips (each marked in the
  store's audit log as one ``bulk`` entry), not 100k PATCHes.

Store-duck-typed like every controller here: a ResourceStore or a
ClusterClient both work (the separate-daemon topology rides
``python -m kwok_tpu.cmd.kcm --controllers gc,workloads``).
"""

from __future__ import annotations

import datetime
import hashlib
import json
import math
from typing import Any, Dict, List, Optional, Tuple

from kwok_tpu.cluster.store import selector_to_string  # noqa: F401 — re-export
from kwok_tpu.utils.log import get_logger

logger = get_logger("workloads")

#: the label a Deployment stamps on every ReplicaSet generation and its
#: pods (upstream apps/v1 convention; `kubectl get rs --show-labels`
#: surfaces the same key on real clusters)
POD_TEMPLATE_HASH = "pod-template-hash"

#: revision annotation on Deployment-owned ReplicaSets (upstream key)
REVISION_ANN = "deployment.kubernetes.io/revision"

#: impersonation identity the workload loops mutate under — audit log
#: lines attribute workload writes to this user
CONTROLLER_USER = "system:kwok-workloads"

#: ops per store.bulk round-trip.  Large on purpose: the O(round-trips)
#: ≪ O(replicas) contract means a 100k-replica scale is ~10 calls.
BULK_CHUNK = 10_000


def now_string(now_s: Optional[float] = None) -> str:
    import time as _time

    t = datetime.datetime.fromtimestamp(
        now_s if now_s is not None else _time.time(), datetime.timezone.utc
    )
    return t.isoformat(timespec="seconds").replace("+00:00", "Z")


# ------------------------------------------------------------------ selectors


def pod_template_hash(template: dict) -> str:
    """Stable 10-hex revision hash of a pod template (process- and
    run-independent, so a restarted controller adopts the same RS)."""
    canon = json.dumps(template or {}, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(canon.encode()).hexdigest()[:10]


def owner_reference(obj: dict, controller: bool = True) -> dict:
    meta = obj.get("metadata") or {}
    ref = {
        "apiVersion": obj.get("apiVersion") or "",
        "kind": obj.get("kind") or "",
        "name": meta.get("name") or "",
        "uid": meta.get("uid") or "",
    }
    if controller:
        ref["controller"] = True
        ref["blockOwnerDeletion"] = True
    return ref


def owned_by(obj: dict, owner: dict) -> bool:
    """Is ``obj`` controlled by ``owner``?  uid wins when both sides
    carry one (a re-created owner must not adopt the old generation's
    pods); kind+name otherwise."""
    ometa = owner.get("metadata") or {}
    want_uid = ometa.get("uid")
    for ref in (obj.get("metadata") or {}).get("ownerReferences") or []:
        if ref.get("kind") != owner.get("kind"):
            continue
        ref_uid = ref.get("uid")
        if want_uid and ref_uid:
            if ref_uid == want_uid:
                return True
            continue
        if ref.get("name") == ometa.get("name"):
            return True
    return False


def resolve_int_or_percent(value: Any, total: int, round_up: bool) -> int:
    """k8s intstr semantics: ints pass through, "25%" resolves against
    ``total`` (ceil for maxSurge, floor for maxUnavailable)."""
    if value is None:
        return 0
    if isinstance(value, str) and value.endswith("%"):
        frac = float(value[:-1] or 0) / 100.0
        return (
            math.ceil(frac * total) if round_up else math.floor(frac * total)
        )
    return int(value)


# ------------------------------------------------------------------ pod state


def pod_is_terminal(pod: dict) -> bool:
    return ((pod.get("status") or {}).get("phase")) in ("Succeeded", "Failed")


def pod_is_active(pod: dict) -> bool:
    """Counts toward a workload's replicas: not terminal, not already
    terminating (a deletionTimestamp'd pod is on its way out through
    the stage machinery and must be replaced now, like k8s)."""
    if (pod.get("metadata") or {}).get("deletionTimestamp"):
        return False
    return not pod_is_terminal(pod)


def pod_is_ready(pod: dict) -> bool:
    status = pod.get("status") or {}
    if status.get("phase") != "Running":
        return False
    for c in status.get("conditions") or []:
        if c.get("type") == "Ready":
            return c.get("status") == "True"
    return False


def _deletion_class(pod: dict) -> int:
    """Scale-down victim class (the spirit of k8s's
    ActivePodsWithRanks: unscheduled < unready < ready)."""
    if not (pod.get("spec") or {}).get("nodeName"):
        return 0
    if not pod_is_ready(pod):
        return 1
    return 2


def rank_for_deletion(pods: List[dict]) -> List[dict]:
    """Victims-first ordering (take the first N to scale down by N):
    unscheduled, then unready, then ready pods; youngest first within
    a class.  creationTimestamps share a second at bulk-create rates,
    so the monotonic uid breaks ties deterministically."""

    def age_key(pod: dict) -> Tuple[str, str]:
        meta = pod.get("metadata") or {}
        return (meta.get("creationTimestamp") or "", meta.get("uid") or "")

    # youngest-first within class: descending age key, then a stable
    # ascending sort on the class
    by_age = sorted(pods, key=age_key, reverse=True)
    return sorted(by_age, key=_deletion_class)


def stamp_pod(
    template: dict,
    namespace: str,
    owner: dict,
    generate_name: str,
    extra_labels: Optional[Dict[str, str]] = None,
) -> dict:
    """One pod from a workload's ``spec.template``: metadata rebuilt
    (generateName uniqueness rides the store's uid counter, the same
    mechanism ctl/scale.py's streamed creates use), labels from the
    template plus ``extra_labels``, controller ownerReference set."""
    from kwok_tpu.utils.patch import copy_json

    tmeta = template.get("metadata") or {}
    labels = dict(tmeta.get("labels") or {})
    labels.update(extra_labels or {})
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "generateName": generate_name,
            "namespace": namespace,
            "labels": labels,
            "ownerReferences": [owner_reference(owner)],
        },
        "spec": copy_json(template.get("spec") or {}),
    }
    if tmeta.get("annotations"):
        pod["metadata"]["annotations"] = copy_json(tmeta["annotations"])
    return pod


# ---------------------------------------------------------------- bulk writes


class BulkWriter:
    """Accumulate mutations, flush through ``store.bulk`` in
    ``BULK_CHUNK``-sized round-trips.  Per-op errors are collected, not
    raised (reconcile loops are retried by the resync tick; a half
    successful wave still moved toward the goal)."""

    def __init__(self, store, chunk: int = BULK_CHUNK):
        self.store = store
        self.chunk = chunk
        self._ops: List[dict] = []
        self.results: List[dict] = []
        self.errors: List[dict] = []
        self.round_trips = 0

    def create(self, obj: dict, namespace: Optional[str] = None) -> None:
        self._ops.append(
            {
                "verb": "create",
                "data": obj,
                "namespace": namespace,
                "as_user": CONTROLLER_USER,
            }
        )
        if len(self._ops) >= self.chunk:
            self.flush()

    def delete(self, kind: str, name: str, namespace: Optional[str]) -> None:
        self._ops.append(
            {
                "verb": "delete",
                "kind": kind,
                "name": name,
                "namespace": namespace,
                "as_user": CONTROLLER_USER,
            }
        )
        if len(self._ops) >= self.chunk:
            self.flush()

    def flush(self) -> None:
        if not self._ops:
            return
        ops, self._ops = self._ops, []
        # as_user doubles as the HTTP audit-line attribution when the
        # store is a ClusterClient (each op carries it for the in-store
        # audit either way)
        res = self.store.bulk(ops, as_user=CONTROLLER_USER)
        self.round_trips += 1
        self.results.extend(res)
        fresh = 0
        for op, r in zip(ops, res):
            if r.get("status") != "ok" and r.get("reason") != "NotFound":
                # NotFound deletes are fine (raced the GC cascade)
                self.errors.append({"op": op, "result": r})
                fresh += 1
        if fresh:
            logger.info(
                "bulk flush had errors",
                n=fresh,
                first=str(self.errors[-fresh]["result"].get("error", ""))[:120],
            )
