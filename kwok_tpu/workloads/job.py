"""Job reconciliation: completions / parallelism / backoffLimit (the
kube-controller-manager job loop; upstream pkg/controller/job —
behavioral reference only; the parity row is PARITY.md:122).

The pod-state model is the same one the stage FSM drives: a job pod
that reaches ``status.phase: Succeeded`` counts toward completions, a
``Failed`` one toward the backoff budget.  One reconcile pass:

1. read the Job; terminating → GC's problem; already finished
   (Complete/Failed condition) → nothing to do,
2. list owned pods, bucket into active/succeeded/failed,
3. terminal states: succeeded ≥ completions ⇒ ``Complete`` (actives
   are torn down through the bulk lane); failed > backoffLimit ⇒
   ``Failed`` (likewise),
4. otherwise converge on parallelism: surplus workers (a reduced
   ``spec.parallelism``) are reaped victims-first, missing ones are
   topped up to min(parallelism, completions - succeeded - active),
   stamped from ``spec.template`` — both through the bulk lane,
5. publish ``status`` (active/succeeded/failed/startTime/
   completionTime/conditions) when changed.

``spec.completions`` unset follows k8s's "any pod succeeding completes
the job" mode with parallelism workers.
"""

from __future__ import annotations

from typing import List, Optional

from kwok_tpu.cluster.store import NotFound
from kwok_tpu.workloads.common import (
    BulkWriter,
    CONTROLLER_USER,
    now_string,
    owned_by,
    pod_is_terminal,
    rank_for_deletion,
    selector_to_string,
    stamp_pod,
)

__all__ = ["JobController"]

DEFAULT_BACKOFF_LIMIT = 6


def _condition(job: dict, ctype: str) -> Optional[dict]:
    for c in (job.get("status") or {}).get("conditions") or []:
        if c.get("type") == ctype and c.get("status") == "True":
            return c
    return None


class JobController:
    def __init__(
        self,
        store,
        recorder=None,
        bulk_chunk: Optional[int] = None,
        now=None,
    ):
        self.store = store
        self.recorder = recorder
        self.bulk_chunk = bulk_chunk
        #: injectable wall-time source (hpa.py carries the same seam):
        #: simulated-time runs stamp startTime/completionTime on the
        #: virtual clock so a seed fully determines the written status
        self._now = now

    def _ts(self) -> str:
        """Status timestamp on the injected time source (wall when
        none): the one place the now-seam is consulted."""
        return now_string(self._now() if self._now else None)

    def _writer(self) -> BulkWriter:
        if self.bulk_chunk:
            return BulkWriter(self.store, chunk=self.bulk_chunk)
        return BulkWriter(self.store)

    def _owned_pods(self, job: dict) -> List[dict]:
        meta = job.get("metadata") or {}
        spec = job.get("spec") or {}
        sel = selector_to_string(spec.get("selector"))
        if sel is None:
            # jobs usually run selector-less; match by template labels
            # when present, else scan the namespace (owned_by filters)
            sel = selector_to_string(
                {
                    "matchLabels": (
                        (spec.get("template") or {}).get("metadata") or {}
                    ).get("labels")
                    or {}
                }
            )
        pods, _ = self.store.list(
            "Pod",
            namespace=meta.get("namespace") or "default",
            label_selector=sel,
        )
        return [p for p in pods if owned_by(p, job)]

    def reconcile(self, namespace: str, name: str) -> None:
        try:
            job = self.store.get("Job", name, namespace=namespace)
        except NotFound:
            return
        meta = job.get("metadata") or {}
        if meta.get("deletionTimestamp"):
            return
        spec = job.get("spec") or {}
        parallelism = spec.get("parallelism")
        parallelism = 1 if parallelism is None else int(parallelism)
        completions = spec.get("completions")
        completions = None if completions is None else int(completions)
        backoff_limit = spec.get("backoffLimit")
        backoff_limit = (
            DEFAULT_BACKOFF_LIMIT if backoff_limit is None else int(backoff_limit)
        )

        pods = self._owned_pods(job)
        active = [
            p
            for p in pods
            if not pod_is_terminal(p)
            and not (p.get("metadata") or {}).get("deletionTimestamp")
        ]
        succeeded = sum(
            1
            for p in pods
            if (p.get("status") or {}).get("phase") == "Succeeded"
        )
        failed = sum(
            1 for p in pods if (p.get("status") or {}).get("phase") == "Failed"
        )

        finished = _condition(job, "Complete") or _condition(job, "Failed")
        complete = (
            succeeded >= completions
            if completions is not None
            else (succeeded > 0 and not active)
        )
        failed_out = failed > backoff_limit

        writer = self._writer()
        if finished or complete or failed_out:
            # terminal: reap still-running workers through the bulk lane
            for p in active:
                pmeta = p.get("metadata") or {}
                writer.delete("Pod", pmeta.get("name") or "", namespace)
            writer.flush()
            active = []
        elif len(active) > parallelism:
            # parallelism was reduced: reap the surplus workers like
            # upstream (victims-first ranking, through the bulk lane)
            victims = rank_for_deletion(active)[: len(active) - parallelism]
            victim_names = set()
            for p in victims:
                pmeta = p.get("metadata") or {}
                victim_names.add(pmeta.get("name") or "")
                writer.delete("Pod", pmeta.get("name") or "", namespace)
            writer.flush()
            active = [
                p
                for p in active
                if (p.get("metadata") or {}).get("name") not in victim_names
            ]
            if self.recorder is not None and victims:
                self.recorder.event(
                    job,
                    "Normal",
                    "SuccessfulDelete",
                    f"Deleted {len(victims)} surplus pods",
                )
        else:
            if completions is None:
                # "any success completes" mode: keep `parallelism`
                # workers — but once any pod has succeeded, no new pods
                # are created (upstream semantics); the job completes
                # when the remaining actives drain
                missing = 0 if succeeded > 0 else parallelism - len(active)
            else:
                remaining = completions - succeeded - len(active)
                missing = min(parallelism - len(active), remaining)
            if missing > 0:
                template = spec.get("template") or {}
                for _ in range(missing):
                    writer.create(
                        stamp_pod(
                            template,
                            namespace,
                            job,
                            generate_name=f"{name}-",
                        ),
                        namespace=namespace,
                    )
                writer.flush()
                if self.recorder is not None:
                    self.recorder.event(
                        job,
                        "Normal",
                        "SuccessfulCreate",
                        f"Created {missing} pods",
                    )

        self._sync_status(
            job, active, succeeded, failed, complete, failed_out
        )

    def _sync_status(
        self,
        job: dict,
        active: List[dict],
        succeeded: int,
        failed: int,
        complete: bool,
        failed_out: bool,
    ) -> None:
        meta = job.get("metadata") or {}
        cur = job.get("status") or {}
        status = {
            "active": len(active),
            "succeeded": succeeded,
            "failed": failed,
            "startTime": cur.get("startTime") or self._ts(),
        }
        conditions = [
            dict(c)
            for c in cur.get("conditions") or []
            if c.get("type") not in ("Complete", "Failed")
        ]
        if complete and not _condition(job, "Complete"):
            conditions.append(
                {
                    "type": "Complete",
                    "status": "True",
                    "lastTransitionTime": self._ts(),
                }
            )
            status["completionTime"] = cur.get("completionTime") or self._ts()
            if self.recorder is not None:
                self.recorder.event(
                    job, "Normal", "Completed", "Job completed"
                )
        elif _condition(job, "Complete"):
            conditions.append(_condition(job, "Complete"))
            if cur.get("completionTime"):
                status["completionTime"] = cur["completionTime"]
        if failed_out and not _condition(job, "Failed") and not complete:
            conditions.append(
                {
                    "type": "Failed",
                    "status": "True",
                    "reason": "BackoffLimitExceeded",
                    "lastTransitionTime": self._ts(),
                }
            )
            if self.recorder is not None:
                self.recorder.event(
                    job,
                    "Warning",
                    "BackoffLimitExceeded",
                    "Job has reached the specified backoff limit",
                )
        elif _condition(job, "Failed"):
            conditions.append(_condition(job, "Failed"))
        if conditions:
            status["conditions"] = conditions
        if all(cur.get(k) == v for k, v in status.items()):
            return
        try:
            self.store.patch(
                "Job",
                meta.get("name") or "",
                {"status": status},
                patch_type="merge",
                namespace=meta.get("namespace"),
                subresource="status",
                as_user=CONTROLLER_USER,
            )
        except NotFound:
            pass
