"""Deployment reconciliation: ReplicaSet revisions + rolling updates
(the kube-controller-manager deployment loop; upstream
pkg/controller/deployment — behavioral reference only; the parity row
is PARITY.md:122).

Revision model: each distinct ``spec.template`` hashes to a
``pod-template-hash`` (common.pod_template_hash); the Deployment owns
one ReplicaSet per hash, named ``{deployment}-{hash}``, carrying the
``deployment.kubernetes.io/revision`` annotation.  A template edit
creates the next revision's RS and the rolling logic walks replicas
across:

- **RollingUpdate** (default): the new RS may scale up while total
  replicas stay ≤ desired + maxSurge; old RSes scale down while total
  available stays ≥ desired - maxUnavailable (percentages resolve
  ceil/floor against ``spec.replicas``, k8s intstr semantics).  Each
  reconcile moves one step; RS/pod status events re-trigger it until
  the new RS holds all replicas.
- **Recreate**: old RSes drop to 0 first; the new RS scales only once
  no old pods remain.

Old all-zero ReplicaSets beyond ``revisionHistoryLimit`` (default 10)
are deleted.  Deployment deletion is not handled here at all: the GC
cascade (RS ownerReferences → pod ownerReferences) tears the tree
down.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from kwok_tpu.cluster.store import AlreadyExists, NotFound
from kwok_tpu.utils.patch import copy_json
from kwok_tpu.workloads.common import (
    CONTROLLER_USER,
    POD_TEMPLATE_HASH,
    REVISION_ANN,
    owned_by,
    owner_reference,
    pod_template_hash,
    resolve_int_or_percent,
    selector_to_string,
)

__all__ = ["DeploymentController"]

DEFAULT_HISTORY_LIMIT = 10


def _rs_available(rs: dict) -> int:
    return int((rs.get("status") or {}).get("availableReplicas") or 0)


def _rs_spec_replicas(rs: dict) -> int:
    r = (rs.get("spec") or {}).get("replicas")
    return 1 if r is None else int(r)


def _revision(rs: dict) -> int:
    try:
        return int(
            ((rs.get("metadata") or {}).get("annotations") or {}).get(
                REVISION_ANN
            )
            or 0
        )
    except (TypeError, ValueError):
        return 0


class DeploymentController:
    def __init__(self, store, recorder=None):
        self.store = store
        self.recorder = recorder

    # ------------------------------------------------------------- helpers

    def _owned_replicasets(self, deploy: dict) -> List[dict]:
        meta = deploy.get("metadata") or {}
        sel = selector_to_string((deploy.get("spec") or {}).get("selector"))
        items, _ = self.store.list(
            "ReplicaSet",
            namespace=meta.get("namespace") or "default",
            label_selector=sel,
        )
        return [rs for rs in items if owned_by(rs, deploy)]

    def _scale_rs(self, rs: dict, replicas: int) -> None:
        meta = rs.get("metadata") or {}
        if _rs_spec_replicas(rs) == replicas:
            return
        try:
            self.store.patch(
                "ReplicaSet",
                meta.get("name") or "",
                {"spec": {"replicas": replicas}},
                patch_type="merge",
                namespace=meta.get("namespace"),
                as_user=CONTROLLER_USER,
            )
        except NotFound:
            return
        # keep the in-memory view current for this pass's math
        rs.setdefault("spec", {})["replicas"] = replicas
        if self.recorder is not None:
            self.recorder.event(
                rs,
                "Normal",
                "ScalingReplicaSet",
                f"Scaled replica set {meta.get('name')} to {replicas}",
            )

    def _new_replicaset(
        self, deploy: dict, tpl_hash: str, all_rs: List[dict]
    ) -> Optional[dict]:
        """Create (or fetch, on a name race) the revision RS for the
        current template."""
        meta = deploy.get("metadata") or {}
        spec = deploy.get("spec") or {}
        name = f"{meta.get('name')}-{tpl_hash}"
        ns = meta.get("namespace") or "default"
        revision = max([_revision(rs) for rs in all_rs], default=0) + 1
        template = copy_json(spec.get("template") or {})
        tmeta = template.setdefault("metadata", {})
        tmeta.setdefault("labels", {})[POD_TEMPLATE_HASH] = tpl_hash
        selector = copy_json(spec.get("selector") or {"matchLabels": {}})
        selector.setdefault("matchLabels", {})[POD_TEMPLATE_HASH] = tpl_hash
        rs = {
            "apiVersion": "apps/v1",
            "kind": "ReplicaSet",
            "metadata": {
                "name": name,
                "namespace": ns,
                "labels": dict(tmeta["labels"]),
                "annotations": {REVISION_ANN: str(revision)},
                "ownerReferences": [owner_reference(deploy)],
            },
            "spec": {
                "replicas": 0,
                "selector": selector,
                "template": template,
            },
        }
        try:
            return self.store.create(rs, namespace=ns, as_user=CONTROLLER_USER)
        except AlreadyExists:
            try:
                return self.store.get("ReplicaSet", name, namespace=ns)
            except NotFound:
                return None

    @staticmethod
    def _surge_unavailable(spec: dict, desired: int) -> Tuple[int, int]:
        strategy = spec.get("strategy") or {}
        ru = strategy.get("rollingUpdate") or {}
        surge = resolve_int_or_percent(
            ru.get("maxSurge", "25%"), desired, round_up=True
        )
        unavail = resolve_int_or_percent(
            ru.get("maxUnavailable", "25%"), desired, round_up=False
        )
        if surge == 0 and unavail == 0:
            unavail = 1  # k8s validation forbids both zero; stay live
        return surge, unavail

    # ----------------------------------------------------------- reconcile

    def reconcile(self, namespace: str, name: str) -> None:
        try:
            deploy = self.store.get("Deployment", name, namespace=namespace)
        except NotFound:
            return
        meta = deploy.get("metadata") or {}
        if meta.get("deletionTimestamp"):
            return
        spec = deploy.get("spec") or {}
        desired = spec.get("replicas")
        desired = 1 if desired is None else int(desired)
        tpl_hash = pod_template_hash(spec.get("template") or {})

        all_rs = self._owned_replicasets(deploy)
        new_rs = next(
            (
                rs
                for rs in all_rs
                if ((rs.get("metadata") or {}).get("labels") or {}).get(
                    POD_TEMPLATE_HASH
                )
                == tpl_hash
            ),
            None,
        )
        paused = bool(spec.get("paused"))
        if new_rs is None and not paused:
            new_rs = self._new_replicaset(deploy, tpl_hash, all_rs)
            if new_rs is not None:
                all_rs.append(new_rs)
        old_rs = [rs for rs in all_rs if rs is not new_rs]

        if not paused and new_rs is not None:
            strategy_type = (spec.get("strategy") or {}).get(
                "type", "RollingUpdate"
            )
            if strategy_type == "Recreate":
                self._reconcile_recreate(deploy, desired, new_rs, old_rs)
            else:
                self._reconcile_rolling(deploy, desired, new_rs, old_rs)

        self._cleanup_history(spec, old_rs)
        self._sync_status(deploy, desired, new_rs, all_rs)

    def _reconcile_rolling(
        self, deploy: dict, desired: int, new_rs: dict, old_rs: List[dict]
    ) -> None:
        surge, unavail = self._surge_unavailable(
            deploy.get("spec") or {}, desired
        )
        total = _rs_spec_replicas(new_rs) + sum(
            _rs_spec_replicas(rs) for rs in old_rs
        )
        # scale up the new RS within the surge ceiling
        cur_new = _rs_spec_replicas(new_rs)
        if cur_new < desired:
            headroom = desired + surge - total
            if headroom > 0:
                self._scale_rs(
                    new_rs, min(desired, cur_new + headroom)
                )
        elif cur_new > desired:
            # direct downscale (kubectl scale) bypasses the budget:
            # the surplus was never part of availability guarantees
            self._scale_rs(new_rs, desired)

        # scale down old RSes within the availability floor
        live_old = [rs for rs in old_rs if _rs_spec_replicas(rs) > 0]
        if not live_old:
            return
        total_available = _rs_available(new_rs) + sum(
            _rs_available(rs) for rs in live_old
        )
        budget = total_available - (desired - unavail)
        # pods an old RS runs beyond its available count are already
        # unavailable — removing them cannot violate the floor
        for rs in sorted(live_old, key=_revision):
            if budget <= 0:
                break
            cur = _rs_spec_replicas(rs)
            unavailable_here = max(0, cur - _rs_available(rs))
            take = min(cur, budget + unavailable_here)
            if take > 0:
                self._scale_rs(rs, cur - take)
                budget -= max(0, take - unavailable_here)

    def _reconcile_recreate(
        self, deploy: dict, desired: int, new_rs: dict, old_rs: List[dict]
    ) -> None:
        live_old = [rs for rs in old_rs if _rs_spec_replicas(rs) > 0]
        for rs in live_old:
            self._scale_rs(rs, 0)
        old_pods_left = sum(
            int((rs.get("status") or {}).get("replicas") or 0)
            for rs in old_rs
        )
        if not live_old and old_pods_left == 0:
            self._scale_rs(new_rs, desired)

    def _cleanup_history(self, spec: dict, old_rs: List[dict]) -> None:
        limit = spec.get("revisionHistoryLimit")
        limit = DEFAULT_HISTORY_LIMIT if limit is None else int(limit)
        dead = [
            rs
            for rs in old_rs
            if _rs_spec_replicas(rs) == 0
            and int((rs.get("status") or {}).get("replicas") or 0) == 0
        ]
        dead.sort(key=_revision)  # oldest first
        for rs in dead[: max(0, len(dead) - limit)]:
            meta = rs.get("metadata") or {}
            try:
                self.store.delete(
                    "ReplicaSet",
                    meta.get("name") or "",
                    namespace=meta.get("namespace"),
                    as_user=CONTROLLER_USER,
                )
            except NotFound:
                pass

    def _sync_status(
        self,
        deploy: dict,
        desired: int,
        new_rs: Optional[dict],
        all_rs: List[dict],
    ) -> None:
        meta = deploy.get("metadata") or {}
        replicas = sum(
            int((rs.get("status") or {}).get("replicas") or 0) for rs in all_rs
        )
        ready = sum(
            int((rs.get("status") or {}).get("readyReplicas") or 0)
            for rs in all_rs
        )
        available = sum(_rs_available(rs) for rs in all_rs)
        updated = (
            int((new_rs.get("status") or {}).get("replicas") or 0)
            if new_rs is not None
            else 0
        )
        _, unavail = self._surge_unavailable(deploy.get("spec") or {}, desired)
        conditions = [
            {
                "type": "Available",
                "status": (
                    "True" if available >= desired - unavail else "False"
                ),
                "reason": (
                    "MinimumReplicasAvailable"
                    if available >= desired - unavail
                    else "MinimumReplicasUnavailable"
                ),
            },
            {
                "type": "Progressing",
                "status": "True",
                "reason": (
                    "NewReplicaSetAvailable"
                    if updated == desired and available == desired
                    else "ReplicaSetUpdated"
                ),
            },
        ]
        status = {
            "replicas": replicas,
            "updatedReplicas": updated,
            "readyReplicas": ready,
            "availableReplicas": available,
            "unavailableReplicas": max(0, desired - available),
            "observedGeneration": meta.get("generation") or 0,
            "conditions": conditions,
        }
        cur = deploy.get("status") or {}
        if all(cur.get(k) == v for k, v in status.items()):
            return
        try:
            self.store.patch(
                "Deployment",
                meta.get("name") or "",
                {"status": status},
                patch_type="merge",
                namespace=meta.get("namespace"),
                subresource="status",
                as_user=CONTROLLER_USER,
            )
        except NotFound:
            pass
