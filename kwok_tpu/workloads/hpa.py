"""HorizontalPodAutoscaler (autoscaling/v2) reconciliation, driven by
the simulated-usage engine.

The real HPA loop (upstream pkg/controller/podautoscaler — behavioral
reference only; parity row PARITY.md:122) asks the metrics API, which
the metrics-server fills from kubelet scrapes; in this simulator the
source of truth behind all of that is the
ResourceUsage/ClusterResourceUsage CRs evaluated by
``metrics/usage.py`` (reference computation:
metrics_resource_usage.go:36-264).  This controller cuts the middleman and reads
the same engine directly: per reconcile it loads the usage CRs from
the store, builds a :class:`UsageEvaluator` over store getters, and
vector-evaluates the target's pods (``bulk_pod_usage`` — the lowered
column programs, not per-pod CEL).

Supported metric specs (``spec.metrics[]``): ``type: Resource`` with
``target.type: Utilization`` (averageUtilization % of the pod
template's container requests) or ``AverageValue``.  An empty metrics
list defaults to 80% cpu utilization like upstream.  The classic
formula applies with upstream's 10% tolerance::

    desired = ceil(current * metric / target)

clamped to [minReplicas, maxReplicas].  Scale-up is immediate;
scale-down honors ``behavior.scaleDown.stabilizationWindowSeconds``
(default 300 s — the highest recommendation inside the window wins,
upstream's stabilization), with the window configurable for tests.
Scaling writes go through the target's ``scale`` shape: one merge
patch of ``spec.replicas`` on the Deployment/ReplicaSet, which the
deployment/replicaset loops then fan out through the bulk lane.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional, Tuple

from kwok_tpu.api.extra_types import ClusterResourceUsage, ResourceUsage
from kwok_tpu.cluster.k8s_api import SCALABLE_KINDS
from kwok_tpu.cluster.store import NotFound
from kwok_tpu.utils.log import get_logger
from kwok_tpu.utils.cel import parse_quantity
from kwok_tpu.workloads.common import (
    CONTROLLER_USER,
    now_string,
    owned_by,
    pod_is_active,
    selector_to_string,
)

__all__ = ["HPAController"]

_LOG = get_logger("hpa")

#: upstream horizontal-pod-autoscaler tolerance: no scale when the
#: usage ratio is within 10% of 1.0
TOLERANCE = 0.1

DEFAULT_STABILIZATION_S = 300.0


def _sum_requests(pod: dict, resource: str) -> float:
    total = 0.0
    for c in ((pod.get("spec") or {}).get("containers")) or []:
        req = ((c.get("resources") or {}).get("requests")) or {}
        if resource in req:
            try:
                total += parse_quantity(str(req[resource]))
            except Exception:  # noqa: BLE001 — malformed quantity: skip
                pass
    return total


class HPAController:
    def __init__(
        self,
        store,
        recorder=None,
        downscale_stabilization_s: Optional[float] = None,
        now=None,
    ):
        self.store = store
        self.recorder = recorder
        #: override for tests; None → per-HPA behavior or the 300s default
        self.downscale_stabilization_s = downscale_stabilization_s
        self._now = now or time.time
        #: (ns, name) -> [(t, recommendation)] inside the window
        self._recommendations: Dict[Tuple[str, str], List[Tuple[float, int]]] = {}
        #: usage-CR identity+version -> evaluator.  The two list calls
        #: still happen every reconcile (they feed the cache key); what
        #: this skips is re-parsing the CRs and re-lowering their CEL
        #: column programs when nothing changed — the expensive half of
        #: each resync tick
        self._ev_cache: Optional[Tuple[Tuple[Any, Any], Any]] = None

    # ------------------------------------------------------------- usage

    def _evaluator(self):
        from kwok_tpu.metrics.usage import UsageEvaluator

        store = self.store

        def crs(kind: str) -> list:
            try:
                items, _ = store.list(kind)
                return items
            except Exception:  # noqa: BLE001 — kind not registered
                return []

        usages = crs("ResourceUsage")
        cluster_usages = crs("ClusterResourceUsage")
        # the list rv is store-global (bumps on any mutation), so key
        # the cache on the usage CRs' own identity+version instead
        key = tuple(
            ((o.get("metadata") or {}).get("uid"),
             (o.get("metadata") or {}).get("resourceVersion"))
            for o in usages + cluster_usages
        )
        if self._ev_cache is not None and self._ev_cache[0] == key:
            return self._ev_cache[1]

        def pod_getter(ns: str, name: str):
            try:
                return store.get("Pod", name, namespace=ns)
            except NotFound:
                return None

        def node_getter(name: str):
            try:
                return store.get("Node", name)
            except NotFound:
                return None

        def list_pods(node_name: str):
            pods, _ = store.list(
                "Pod", field_selector=f"spec.nodeName={node_name}"
            )
            return pods

        ev = UsageEvaluator(pod_getter, node_getter, list_pods, now=self._now)
        try:
            ev.set_usages([ResourceUsage.from_dict(u) for u in usages])
        except Exception as exc:  # noqa: BLE001 — malformed CR: evaluate without
            _LOG.debug("ignoring malformed ResourceUsage CRs", error=exc)
        try:
            ev.set_cluster_usages(
                [ClusterResourceUsage.from_dict(u) for u in cluster_usages]
            )
        except Exception as exc:  # noqa: BLE001 — malformed CR: evaluate without
            _LOG.debug("ignoring malformed ClusterResourceUsage CRs", error=exc)
        self._ev_cache = (key, ev)
        return ev

    # ---------------------------------------------------------- reconcile

    def reconcile(self, namespace: str, name: str) -> None:
        try:
            hpa = self.store.get(
                "HorizontalPodAutoscaler", name, namespace=namespace
            )
        except NotFound:
            # drop the stabilization history with the HPA, or churn of
            # uniquely-named HPAs grows the cache without bound
            self._recommendations.pop((namespace, name), None)
            return
        meta = hpa.get("metadata") or {}
        if meta.get("deletionTimestamp"):
            self._recommendations.pop((namespace, name), None)
            return
        spec = hpa.get("spec") or {}
        ref = spec.get("scaleTargetRef") or {}
        kind = ref.get("kind") or ""
        if kind not in SCALABLE_KINDS:
            return
        try:
            target = self.store.get(
                kind, ref.get("name") or "", namespace=namespace
            )
        except NotFound:
            return

        tspec = target.get("spec") or {}
        current = tspec.get("replicas")
        current = 1 if current is None else int(current)
        if current == 0:
            # upstream semantics: a deliberately zeroed target means
            # "autoscaling disabled" — never scale it back up
            return
        min_r = int(spec.get("minReplicas") or 1)
        max_r = int(spec.get("maxReplicas") or max(min_r, current))

        pods = self._target_pods(target, namespace)
        metric_status, ratio = self._metric_ratio(spec, pods)
        if ratio is None:
            return
        if current > 0 and abs(ratio - 1.0) > TOLERANCE:
            desired = math.ceil(current * ratio)
        else:
            desired = current
        desired = max(min_r, min(max_r, desired))
        desired = self._stabilize(
            (namespace, name), spec, current, desired
        )
        # re-clamp after stabilization: the window can resurrect a
        # recommendation recorded before min/maxReplicas changed, and
        # upstream normalizes to the live bounds last
        desired = max(min_r, min(max_r, desired))

        if desired != current:
            try:
                self.store.patch(
                    kind,
                    ref.get("name") or "",
                    {"spec": {"replicas": desired}},
                    patch_type="merge",
                    namespace=namespace,
                    as_user=CONTROLLER_USER,
                )
            except NotFound:
                return
            if self.recorder is not None:
                self.recorder.event(
                    hpa,
                    "Normal",
                    "SuccessfulRescale",
                    f"New size: {desired}; reason: metrics ratio "
                    f"{ratio:.2f}",
                )
        self._sync_status(hpa, current, desired, metric_status)

    def _target_pods(self, target: dict, namespace: str) -> List[dict]:
        sel = selector_to_string((target.get("spec") or {}).get("selector"))
        pods, _ = self.store.list(
            "Pod", namespace=namespace, label_selector=sel
        )
        if target.get("kind") == "Deployment":
            # deployment pods are owned by its ReplicaSets; the shared
            # selector already scopes them — just drop foreign owners'
            # terminal leftovers
            return [p for p in pods if pod_is_active(p)]
        return [
            p for p in pods if pod_is_active(p) and owned_by(p, target)
        ]

    def _metric_ratio(self, spec: dict, pods: List[dict]):
        """(currentMetrics entry, usage/target ratio) for the first
        supported metric; (None, None) when nothing is measurable."""
        metrics = spec.get("metrics") or [
            {
                "type": "Resource",
                "resource": {
                    "name": "cpu",
                    "target": {"type": "Utilization", "averageUtilization": 80},
                },
            }
        ]
        if not pods:
            return None, None
        ev = self._evaluator()
        for m in metrics:
            if (m.get("type") or "") != "Resource":
                continue
            res = m.get("resource") or {}
            rname = res.get("name") or "cpu"
            target = res.get("target") or {}
            per_pod = ev.bulk_pod_usage(rname, pods)
            avg_usage = float(per_pod.sum()) / len(pods)
            if target.get("type") == "AverageValue":
                try:
                    want = parse_quantity(str(target.get("averageValue")))
                except Exception:  # noqa: BLE001
                    continue
                if want <= 0:
                    continue
                status = {
                    "type": "Resource",
                    "resource": {
                        "name": rname,
                        "current": {"averageValue": str(avg_usage)},
                    },
                }
                return status, avg_usage / want
            # Utilization (default): % of per-pod requests
            want_util = float(target.get("averageUtilization") or 80)
            req = sum(_sum_requests(p, rname) for p in pods) / len(pods)
            if req <= 0 or want_util <= 0:
                continue
            util = 100.0 * avg_usage / req
            status = {
                "type": "Resource",
                "resource": {
                    "name": rname,
                    "current": {"averageUtilization": int(round(util))},
                },
            }
            return status, util / want_util
        return None, None

    def _stabilize(
        self,
        key: Tuple[str, str],
        spec: dict,
        current: int,
        desired: int,
    ) -> int:
        """Upstream downscale stabilization: remember recommendations,
        scale down only to the window's maximum (scale-up unaffected)."""
        window = self.downscale_stabilization_s
        if window is None:
            behavior = (spec.get("behavior") or {}).get("scaleDown") or {}
            window = float(
                behavior.get("stabilizationWindowSeconds", DEFAULT_STABILIZATION_S)
            )
        now = self._now()
        recs = self._recommendations.setdefault(key, [])
        recs.append((now, desired))
        recs[:] = [(t, r) for t, r in recs if now - t <= window]
        if desired >= current:
            return desired
        return max(desired, max(r for _, r in recs))

    def _sync_status(
        self,
        hpa: dict,
        current: int,
        desired: int,
        metric_status: Optional[dict],
    ) -> None:
        meta = hpa.get("metadata") or {}
        cur = hpa.get("status") or {}
        status = {
            "currentReplicas": current,
            "desiredReplicas": desired,
            "currentMetrics": [metric_status] if metric_status else [],
            "conditions": [
                {
                    "type": "AbleToScale",
                    "status": "True",
                    "reason": "ReadyForNewScale",
                },
                {
                    "type": "ScalingActive",
                    "status": "True" if metric_status else "False",
                    "reason": (
                        "ValidMetricFound"
                        if metric_status
                        else "FailedGetResourceMetric"
                    ),
                },
            ],
        }
        if desired != current:
            status["lastScaleTime"] = now_string(self._now())
        elif cur.get("lastScaleTime"):
            status["lastScaleTime"] = cur["lastScaleTime"]
        if all(cur.get(k) == v for k, v in status.items()):
            return
        try:
            self.store.patch(
                "HorizontalPodAutoscaler",
                meta.get("name") or "",
                {"status": status},
                patch_type="merge",
                namespace=meta.get("namespace"),
                subresource="status",
                as_user=CONTROLLER_USER,
            )
        except NotFound:
            pass
