"""Workload controller subsystem: the app-level control loops a real
cluster gets from kube-controller-manager — ReplicaSet, Deployment
(rolling updates over RS revisions), Job, and HorizontalPodAutoscaler
driven by the simulated-usage engine.  See manager.WorkloadManager for
the composition; every loop is store-duck-typed and reconciles through
the store's bulk-mutation lane.
"""

from kwok_tpu.workloads.common import (
    BULK_CHUNK,
    CONTROLLER_USER,
    POD_TEMPLATE_HASH,
    REVISION_ANN,
    pod_template_hash,
    selector_to_string,
)
from kwok_tpu.workloads.deployment import DeploymentController
from kwok_tpu.workloads.hpa import HPAController
from kwok_tpu.workloads.job import JobController
from kwok_tpu.workloads.manager import WorkloadManager
from kwok_tpu.workloads.replicaset import ReplicaSetController

__all__ = [
    "BULK_CHUNK",
    "CONTROLLER_USER",
    "POD_TEMPLATE_HASH",
    "REVISION_ANN",
    "DeploymentController",
    "HPAController",
    "JobController",
    "ReplicaSetController",
    "WorkloadManager",
    "pod_template_hash",
    "selector_to_string",
]
