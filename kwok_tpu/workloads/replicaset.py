"""ReplicaSet reconciliation (the kube-controller-manager replicaset
loop; upstream pkg/controller/replicaset — behavioral reference only;
the parity row is PARITY.md:122).

One reconcile pass:

1. read the ReplicaSet; a terminating one is left to the GC cascade
   (its pods carry controller ownerReferences, so
   controllers/gc_controller.py reaps them when the RS goes),
2. list its pods by label selector (one indexed store query) and keep
   the ones this RS controls (ownerReference uid),
3. diff against ``spec.replicas``: surplus pods are deleted
   youngest-and-least-ready first, missing pods are stamped from
   ``spec.template`` — both through the bulk-mutation lane, so the
   wave costs O(replicas / BULK_CHUNK) round-trips,
4. publish ``status`` (replicas / fullyLabeledReplicas / readyReplicas
   / availableReplicas / observedGeneration), only when it changed.
"""

from __future__ import annotations

from typing import List, Optional

from kwok_tpu.cluster.store import NotFound
from kwok_tpu.workloads.common import (
    BulkWriter,
    CONTROLLER_USER,
    owned_by,
    pod_is_active,
    pod_is_ready,
    rank_for_deletion,
    selector_to_string,
    stamp_pod,
)

__all__ = ["ReplicaSetController"]


class ReplicaSetController:
    def __init__(self, store, recorder=None, bulk_chunk: Optional[int] = None):
        self.store = store
        self.recorder = recorder
        self.bulk_chunk = bulk_chunk

    def _writer(self) -> BulkWriter:
        if self.bulk_chunk:
            return BulkWriter(self.store, chunk=self.bulk_chunk)
        return BulkWriter(self.store)

    def list_owned_pods(self, owner: dict) -> List[dict]:
        spec = owner.get("spec") or {}
        sel = selector_to_string(spec.get("selector")) or selector_to_string(
            {
                "matchLabels": (
                    (spec.get("template") or {}).get("metadata") or {}
                ).get("labels")
                or {}
            }
        )
        ns = (owner.get("metadata") or {}).get("namespace") or "default"
        pods, _ = self.store.list("Pod", namespace=ns, label_selector=sel)
        return [p for p in pods if owned_by(p, owner)]

    def reconcile(self, namespace: str, name: str) -> None:
        try:
            rs = self.store.get("ReplicaSet", name, namespace=namespace)
        except NotFound:
            return
        meta = rs.get("metadata") or {}
        if meta.get("deletionTimestamp"):
            return
        spec = rs.get("spec") or {}
        desired = spec.get("replicas")
        desired = 1 if desired is None else int(desired)
        pods = self.list_owned_pods(rs)
        active = [p for p in pods if pod_is_active(p)]

        diff = desired - len(active)
        writer = self._writer()
        if diff > 0:
            template = spec.get("template") or {}
            for _ in range(diff):
                writer.create(
                    stamp_pod(
                        template,
                        namespace,
                        rs,
                        generate_name=f"{name}-",
                    ),
                    namespace=namespace,
                )
            writer.flush()
            if self.recorder is not None and writer.round_trips:
                self.recorder.event(
                    rs,
                    "Normal",
                    "SuccessfulCreate",
                    f"Created {diff} pods in {writer.round_trips} bulk "
                    "round-trips",
                )
        elif diff < 0:
            victims = rank_for_deletion(active)[: -diff]
            for pod in victims:
                pmeta = pod.get("metadata") or {}
                writer.delete("Pod", pmeta.get("name") or "", namespace)
            writer.flush()
            if self.recorder is not None and victims:
                self.recorder.event(
                    rs,
                    "Normal",
                    "SuccessfulDelete",
                    f"Deleted {len(victims)} pods in {writer.round_trips} "
                    "bulk round-trips",
                )

        self.sync_status(rs, pods)

    def sync_status(self, rs: dict, pods: List[dict]) -> None:
        meta = rs.get("metadata") or {}
        active = [p for p in pods if pod_is_active(p)]
        ready = sum(1 for p in active if pod_is_ready(p))
        status = {
            "replicas": len(active),
            "fullyLabeledReplicas": len(active),
            "readyReplicas": ready,
            "availableReplicas": ready,
            "observedGeneration": meta.get("generation") or 0,
        }
        cur = rs.get("status") or {}
        if all(cur.get(k) == v for k, v in status.items()):
            return
        try:
            self.store.patch(
                "ReplicaSet",
                meta.get("name") or "",
                {"status": status},
                patch_type="merge",
                namespace=meta.get("namespace"),
                subresource="status",
                as_user=CONTROLLER_USER,
            )
        except NotFound:
            pass
