"""WorkloadManager: the controller-manager loop hosting the workload
controllers (ReplicaSet / Deployment / Job / HorizontalPodAutoscaler;
the parity row is PARITY.md:122 — the reference runs the real kcm
binary instead, SURVEY.md:152).

Shape mirrors the other controller seats in this tree (gc_controller,
scheduler): informers feed one event queue; a mapper turns events into
reconcile keys; a keyed work queue (client-go workqueue semantics —
dedup while queued, serialization while in flight, re-queue when
dirtied during processing) feeds a small worker pool; a deadline-based
resync sweep re-enqueues everything so drift and missed events heal.

Event → key mapping:

- Deployment/ReplicaSet/Job/HPA events reconcile themselves,
- a ReplicaSet event also reconciles its owner Deployment (status
  roll-up + the next rolling step),
- a Pod event reconciles its controller ownerReference (ReplicaSet or
  Job) — at device-drain rates this path is just dict probes and a
  set-dedup insert,
- HPAs additionally reconcile every resync tick (metrics move without
  any object event).

Store-duck-typed: pass a ResourceStore (in-process composition, tests)
or a ClusterClient (the kcm daemon topology).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional, Set, Tuple

from kwok_tpu.cluster.informer import Informer, WatchOptions
from kwok_tpu.cluster.store import EventRecorder
from kwok_tpu.utils.log import get_logger
from kwok_tpu.utils.queue import Queue
from kwok_tpu.workloads.deployment import DeploymentController
from kwok_tpu.workloads.hpa import HPAController
from kwok_tpu.workloads.job import JobController
from kwok_tpu.workloads.replicaset import ReplicaSetController

__all__ = ["WorkloadManager"]

logger = get_logger("workloads")

Key = Tuple[str, str, str]  # (kind, namespace, name)

_WATCHED = ("Deployment", "ReplicaSet", "Job", "HorizontalPodAutoscaler", "Pod")


class _KeyedQueue:
    """Dedup + in-flight serialization (client-go workqueue): a key is
    queued at most once; while a worker holds it, new adds mark it
    dirty and it re-queues on done()."""

    def __init__(self):
        self._cv = threading.Condition()
        self._ready: deque = deque()
        self._queued: Set[Key] = set()
        self._dirty: Set[Key] = set()
        self._active: Set[Key] = set()
        self._stopped = False

    def add(self, key: Key) -> None:
        with self._cv:
            if key in self._active:
                self._dirty.add(key)
                return
            if key in self._queued:
                return
            self._queued.add(key)
            self._ready.append(key)
            self._cv.notify()

    def get(self, timeout: float = 0.2) -> Optional[Key]:
        with self._cv:
            if not self._ready:
                self._cv.wait(timeout)
            if not self._ready or self._stopped:
                return None
            key = self._ready.popleft()
            self._queued.discard(key)
            self._active.add(key)
            return key

    def done(self, key: Key) -> None:
        with self._cv:
            self._active.discard(key)
            if key in self._dirty:
                self._dirty.discard(key)
                if key not in self._queued:
                    self._queued.add(key)
                    self._ready.append(key)
                    self._cv.notify()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()


class WorkloadManager:
    """Runs the four workload reconcilers over one store/client."""

    RESYNC_S = 5.0

    def __init__(
        self,
        store,
        resync_s: Optional[float] = None,
        workers: int = 2,
        recorder: Optional[EventRecorder] = None,
        bulk_chunk: Optional[int] = None,
        hpa_downscale_stabilization_s: Optional[float] = None,
        active=None,
        clock=None,
    ):
        self.store = store
        #: leadership gate (cluster/election.py LeaderElector.is_leader
        #: duck type): every reconcile round re-checks it, so a deposed
        #: kcm replica stops mutating before teardown.  None = always
        #: active.
        self._active = active
        #: injectable time source (utils.clock Clock duck type) threaded
        #: into the time-stamping sub-controllers (HPA stabilization
        #: windows, Job start/completion times) so a simulated-time run
        #: is seed-deterministic; None keeps wall time.
        now = clock.now if clock is not None else None
        self.resync_s = resync_s if resync_s is not None else self.RESYNC_S
        self.recorder = recorder or EventRecorder(
            store, source="workload-controller"
        )
        self.replicasets = ReplicaSetController(
            store, recorder=self.recorder, bulk_chunk=bulk_chunk
        )
        self.deployments = DeploymentController(store, recorder=self.recorder)
        self.jobs = JobController(
            store, recorder=self.recorder, bulk_chunk=bulk_chunk, now=now
        )
        self.hpas = HPAController(
            store,
            recorder=self.recorder,
            downscale_stabilization_s=hpa_downscale_stabilization_s,
            now=now,
        )
        self._dispatch: Dict[str, object] = {
            "Deployment": self.deployments,
            "ReplicaSet": self.replicasets,
            "Job": self.jobs,
            "HorizontalPodAutoscaler": self.hpas,
        }
        self._events: Queue = Queue()
        self._queue = _KeyedQueue()
        #: reconcile key -> causing write's span context (latest event
        #: wins; popped when the key is reconciled, so the map stays
        #: bounded by queued keys).  The reconcile span continues/links
        #: it — the kcm half of the watch-boundary stitch.
        self._key_ctx: Dict[Key, tuple] = {}
        self._ctx_mut = threading.Lock()
        self._done = threading.Event()
        self._threads = []
        self._workers = max(1, workers)
        self.reconciles = 0  # observability

    # -------------------------------------------------------------- wiring

    def start(self) -> "WorkloadManager":
        for kind in _WATCHED:
            inf = Informer(self.store, kind)
            inf.watch(WatchOptions(), self._events, done=self._done)
        t = threading.Thread(
            target=self._mapper_loop, daemon=True, name="workloads-mapper"
        )
        t.start()
        self._threads.append(t)
        for i in range(self._workers):
            t = threading.Thread(
                target=self._worker_loop,
                daemon=True,
                name=f"workloads-worker-{i}",
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._done.set()
        self._queue.stop()
        for t in self._threads:
            t.join(timeout=2.0)

    # -------------------------------------------------------------- mapping

    def _map_event(self, obj: dict, ctx=None) -> None:
        kind = obj.get("kind") or ""
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        name = meta.get("name") or ""

        def enqueue(key: Key) -> None:
            if ctx is not None:
                with self._ctx_mut:
                    self._key_ctx[key] = ctx
            self._queue.add(key)

        if kind == "Pod":
            for ref in meta.get("ownerReferences") or []:
                rkind = ref.get("kind")
                if rkind in ("ReplicaSet", "Job"):
                    enqueue((rkind, ns, ref.get("name") or ""))
            return
        if kind in self._dispatch:
            enqueue((kind, ns, name))
            if kind == "ReplicaSet":
                for ref in meta.get("ownerReferences") or []:
                    if ref.get("kind") == "Deployment":
                        enqueue(("Deployment", ns, ref.get("name") or ""))

    def _resync(self) -> None:
        for kind in ("Deployment", "ReplicaSet", "Job", "HorizontalPodAutoscaler"):
            try:
                items, _ = self.store.list(kind)
            except Exception:  # noqa: BLE001 — apiserver hiccup; next tick
                continue
            for obj in items:
                meta = obj.get("metadata") or {}
                self._queue.add(
                    (kind, meta.get("namespace") or "default", meta.get("name") or "")
                )

    def _mapper_loop(self) -> None:
        import time as _time

        next_resync = _time.monotonic()  # first pass adopts existing objects
        while not self._done.is_set():
            ev, ok = self._events.get_or_wait(timeout=0.2, done=self._done)
            if ok and ev is not None:
                try:
                    self._map_event(ev.object, ctx=getattr(ev, "ctx", None))
                except Exception:  # noqa: BLE001 — one event must not kill it
                    import traceback

                    traceback.print_exc()
            if _time.monotonic() < next_resync:
                continue
            next_resync = _time.monotonic() + self.resync_s
            try:
                self._resync()
            except Exception:  # noqa: BLE001
                import traceback

                traceback.print_exc()

    # -------------------------------------------------------------- workers

    def _reconcile_one(self, key: Key) -> None:
        """Dispatch one queued key (leadership re-checked), never
        letting a bad object kill the caller — shared by the worker
        threads and the synchronous drain."""
        kind, ns, name = key
        with self._ctx_mut:
            ctx = self._key_ctx.pop(key, None)
        try:
            ctrl = self._dispatch.get(kind)
            if ctrl is not None and not (
                self._active is not None and not self._active()
            ):
                from kwok_tpu.utils.trace import get_tracer

                tracer = get_tracer()
                if tracer.enabled:
                    # continuation of the causing write's trace (ctx
                    # stitched across the watch boundary; resync keys
                    # open fresh roots)
                    tid, pid = ctx if ctx else (None, None)
                    with tracer.span(
                        "workloads.reconcile", trace_id=tid, parent_id=pid
                    ) as sp:
                        if ctx:
                            sp.add_link(*ctx)
                        sp.set("object", f"{kind}:{ns}/{name}")
                        ctrl.reconcile(ns, name)
                else:
                    ctrl.reconcile(ns, name)
                self.reconciles += 1
        except Exception as exc:  # noqa: BLE001 — a bad object must not kill
            from kwok_tpu.cluster.client import ApiUnavailable
            from kwok_tpu.cluster.store import Conflict, StorageDegraded

            if isinstance(exc, (ApiUnavailable, Conflict, StorageDegraded)):
                # transient: an outage/shed defers to the resync sweep,
                # a Conflict is either an rv race or a stale leader
                # fence (this replica is about to be deposed — e.g.
                # after a lossy storage recovery rolled the Lease back),
                # and StorageDegraded is the read-only window (full
                # disk) — the resync sweep retries once writes re-arm;
                # a full traceback per deferred key is just noise
                logger.info("reconcile deferred", key=f"{kind}/{ns}/{name}", err=str(exc))
            else:
                import traceback

                traceback.print_exc()
        finally:
            self._queue.done(key)

    def _worker_loop(self) -> None:
        while not self._done.is_set():
            key = self._queue.get(timeout=0.2)
            if key is None:
                continue
            self._reconcile_one(key)

    # ------------------------------------------------------ synchronous seams
    # (the DST harness — kwok_tpu.dst — drives these directly, no threads)

    def map_event(self, obj: dict) -> None:
        """Public seam: enqueue the reconcile keys one object event
        implies (the mapper-loop body)."""
        self._map_event(obj)

    def resync_once(self) -> None:
        """Public seam: one full resync sweep (enqueue every workload
        object)."""
        self._resync()

    def drain_queue(self, budget: Optional[int] = None) -> int:
        """Public seam: synchronously reconcile everything queued (the
        worker-loop body without the threads); returns how many keys
        were processed."""
        n = 0
        while budget is None or n < budget:
            key = self._queue.get(timeout=0.0)
            if key is None:
                return n
            self._reconcile_one(key)
            n += 1
        return n
