"""Per-tenant APF priority levels, generated as a FlowConfiguration.

Every fleet tenant gets its OWN priority level named by its tenant id,
so the apiserver's flow controller gives each tenant private seats and
private fair queues: a flooded tenant saturates only its own level —
its 429s are its own, and its backlog can never occupy a neighbor's
(or the system level's) queue capacity.  This is the per-tenant level
derivation ROADMAP open item 2 asked for, built entirely out of the
existing PR 4 machinery: we emit a plain ``FlowConfiguration`` dict and
feed it through :meth:`FlowConfig.from_dict` — no new admission code.

The sizing trick: tenant levels declare ``shares: 0``.  The seat
formula (``max(1, round(max_inflight * shares / total_shares))``,
cluster/flowcontrol.py) floors every level at one seat without letting
a thousand tenant levels dilute the default levels' shares — system /
controllers / workloads keep exactly the seat split they'd have in a
fleet-less cluster, and each tenant holds a guaranteed-minimum seat.
That keeps the config sound at ``--clusters 1000`` on one apiserver.

Reference: kube-apiserver APF expresses the same idea as one
PriorityLevelConfiguration per isolation domain (reference
runtime/binary/cluster.go:316-728 carries the inflight flags this
module partitions).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from kwok_tpu.cluster.flowcontrol import FlowConfig

__all__ = ["fleet_flow_config", "tenant_client_id", "fleet_flow_dict"]

#: queue shape of one tenant level: tiny on purpose — a tenant's
#: backlog bound is (queues * queueLimit) requests and one queue-wait of
#: latency, so a flood sheds fast instead of building a deep queue
_TENANT_QUEUES = 2
_TENANT_QUEUE_WAIT_S = 0.5
_TENANT_QUEUE_LIMIT = 64


def tenant_client_id(tenant: str) -> str:
    """The ``X-Kwok-Client`` identity a tenant's traffic classifies
    under (exact-match flow rule → O(1) classification)."""
    return f"tenant:{tenant}"


def fleet_flow_dict(
    tenants: Sequence[str],
    max_inflight: Optional[int] = None,
    queue_wait_s: float = _TENANT_QUEUE_WAIT_S,
    queue_limit: int = _TENANT_QUEUE_LIMIT,
) -> Dict[str, object]:
    """The generated ``FlowConfiguration`` document (kind +
    levels + flows) for a fleet — the YAML-equivalent form, so it can
    be dumped, diffed, or shipped through ``--flow-config`` unchanged."""
    levels: List[dict] = [
        {
            "name": t,
            "shares": 0,  # guaranteed-minimum seat; see module docstring
            "queues": _TENANT_QUEUES,
            "queueWaitSeconds": queue_wait_s,
            "queueLimit": queue_limit,
        }
        for t in tenants
    ]
    flows: List[dict] = [
        {"level": t, "clients": [tenant_client_id(t)]} for t in tenants
    ]
    doc: Dict[str, object] = {
        "kind": "FlowConfiguration",
        "levels": levels,
        "flows": flows,
    }
    if max_inflight is not None:
        doc["maxInflight"] = int(max_inflight)
    return doc


def fleet_flow_config(
    tenants: Sequence[str],
    max_inflight: Optional[int] = None,
    queue_wait_s: float = _TENANT_QUEUE_WAIT_S,
    queue_limit: int = _TENANT_QUEUE_LIMIT,
) -> FlowConfig:
    """Parsed :class:`FlowConfig` with one priority level per tenant on
    top of the default system/controllers/workloads/best-effort split.
    Feed straight to :class:`FlowController`."""
    return FlowConfig.from_dict(
        fleet_flow_dict(
            tenants,
            max_inflight=max_inflight,
            queue_wait_s=queue_wait_s,
            queue_limit=queue_limit,
        )
    )
