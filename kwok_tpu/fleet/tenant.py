"""Tenant object spaces and the fleet lifecycle registry.

A tenant is one virtual control plane: its API objects live in the
shared store under namespaces prefixed ``<tenant>--`` (the separator is
:data:`kwok_tpu.cluster.sharding.router.TENANT_SEP`, which the
placement hash truncates at — so every namespace of one tenant lands on
one shard and the tenant's transactions stay single-shard).
:class:`TenantStore` is the namespace-prefixing proxy that builds the
virtual-cluster illusion — duck-typed to :class:`ResourceStore` exactly
like ``ClusterClient`` is, so every apiserver handler (and the k8s
wire-protocol facade) works unchanged on top of it.

:class:`FleetRegistry` owns tenant lifecycle: a tenant is *cold* until
its first request (no binding, no memory), *warm* while requests keep
arriving, *idle* after ``idle_after_s`` without one, and back to *cold*
(binding dropped — scale-to-zero; durable state stays in the store)
after ``cold_after_s``.  All lifecycle arithmetic runs on the injected
clock (:mod:`kwok_tpu.utils.clock`), so FakeClock tests and the DST
virtual clock drive it without a single sleep.

Reference: kwokctl's multi-cluster surface manages one runtime dir per
cluster (reference pkg/kwokctl/cmd/create/cluster/cluster.go:60,
pkg/kwokctl/cmd/get/clusters/clusters.go:40); a fleet collapses those
clusters into tenants of one store.
"""

from __future__ import annotations

import inspect
import time
from typing import Dict, List, Optional, Tuple

from kwok_tpu.cluster.sharding.router import TENANT_SEP, shard_of
from kwok_tpu.cluster.store import AlreadyExists, NotFound
from kwok_tpu.utils.clock import Clock, MonotonicClock
from kwok_tpu.utils.locks import guarded, make_lock

__all__ = [
    "TENANT_HEADER",
    "COLD",
    "WARM",
    "IDLE",
    "FleetRegistry",
    "TenantStore",
    "TenantWatcher",
    "UnknownTenant",
    "fleet_tenant_ids",
]

#: request header naming the tenant; the path dialect
#: ``/fleet/t/<tenant>/...`` is equivalent (cluster/apiserver.py)
TENANT_HEADER = "X-Kwok-Tenant"

#: lifecycle states (computed, never stored — state is a pure function
#: of ``clock.now() - last_seen``)
COLD = "cold"
WARM = "warm"
IDLE = "idle"


class UnknownTenant(NotFound):
    """Request named a tenant outside the fleet's fixed set (404 — the
    set is pinned at fleet creation so APF levels stay bounded)."""


def fleet_tenant_ids(n: int) -> List[str]:
    """The fleet's tenant id set: ``t000..t{n-1}`` (zero-padded to the
    fleet's width so ids sort, tabulate, and label consistently).  Ids
    are the APF level names and metric label values — fixed at create
    time, which is what keeps both sets bounded."""
    n = max(0, int(n))
    width = max(3, len(str(max(0, n - 1))))
    return [f"t{i:0{width}d}" for i in range(n)]


def _map_ns(tenant: str, namespace: Optional[str]) -> str:
    return f"{tenant}{TENANT_SEP}{namespace or 'default'}"


def _strip_ns(tenant: str, namespace: str) -> str:
    prefix = tenant + TENANT_SEP
    return namespace[len(prefix):] if namespace.startswith(prefix) else namespace


class TenantWatcher:
    """Filtering/stripping wrapper over a store :class:`Watcher`.

    Used for a tenant's all-namespaces watches: the inner watcher sees
    the whole kind, this wrapper delivers only the tenant's objects
    (namespace — or Namespace-kind name — carries the tenant prefix)
    with the prefix stripped, so the consumer sees its virtual cluster
    and nothing else.  Duck-typed to the Watcher surface the watch
    servers drive (``drain``/``next``/``stop``/``stopped``)."""

    def __init__(self, inner, tenant: str, namespace_kind: bool = False):
        self._inner = inner
        self._tenant = tenant
        self._prefix = tenant + TENANT_SEP
        self._namespace_kind = namespace_kind

    # ----------------------------------------------------------- filtering

    def _match(self, obj: dict) -> bool:
        meta = (obj or {}).get("metadata") or {}
        field = meta.get("name") if self._namespace_kind else meta.get("namespace")
        return bool(field) and str(field).startswith(self._prefix)

    def _wrap(self, ev):
        return ev.__class__(
            ev.type,
            _strip_object(self._tenant, ev.object, self._namespace_kind),
            ev.rv,
        )

    # ------------------------------------------------------------- surface

    def drain(self) -> list:
        return [self._wrap(e) for e in self._inner.drain() if self._match(e.object)]

    def next(self, timeout: Optional[float] = 0.5):
        deadline = (
            None if timeout is None else time.monotonic() + max(0.0, timeout)
        )
        while True:
            left = (
                timeout
                if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            ev = self._inner.next(timeout=left)
            if ev is None:
                return None
            if self._match(ev.object):
                return self._wrap(ev)
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def __iter__(self):
        for ev in self._inner:
            if self._match(ev.object):
                yield self._wrap(ev)

    def stop(self) -> None:
        self._inner.stop()

    @property
    def stopped(self) -> bool:
        return self._inner.stopped

    @property
    def evicted(self) -> bool:
        return bool(getattr(self._inner, "evicted", False))


def _strip_object(tenant: str, obj: dict, namespace_kind: bool) -> dict:
    """Shallow-copied view of ``obj`` with the tenant prefix removed
    from its namespace (or its name, for the Namespace kind).  Stored
    instances are never mutated — watch rings and copy=False lists hand
    out shared references."""
    if not isinstance(obj, dict):
        return obj
    meta = obj.get("metadata")
    if not isinstance(meta, dict):
        return obj
    prefix = tenant + TENANT_SEP
    field = "name" if namespace_kind else "namespace"
    val = meta.get(field)
    if not (isinstance(val, str) and val.startswith(prefix)):
        return obj
    out = dict(obj)
    m = dict(meta)
    m[field] = val[len(prefix):]
    out["metadata"] = m
    return out


class TenantStore:
    """Namespace-prefixing store proxy — one tenant's virtual cluster.

    Mapping rules (the whole isolation contract lives here):

    - **namespaced kinds**: the effective namespace maps to
      ``<tenant>--<ns or default>`` on the way in and strips on the way
      out; an all-namespaces list/watch is restricted to the tenant's
      prefix.
    - **the Namespace kind**: cluster-scoped, but its *name* is a
      namespace — so the name maps/strips the same way, and lists show
      only the tenant's namespaces.  The virtual cluster looks complete.
    - **other cluster-scoped kinds** (Nodes, ...): shared pass-through —
      the fleet shares its simulated infrastructure pool, exactly the
      kwok posture (tenants own workloads, the host owns the substrate).

    Anything not overridden delegates to the inner store, so the proxy
    keeps working over :class:`ClusterClient` too (the duck-typing
    convention of this repo)."""

    def __init__(self, store, tenant: str):
        self._store = store
        self.tenant = tenant
        self._prefix = tenant + TENANT_SEP
        # the store duck varies: ResourceStore.list takes copy=, the
        # sharded router and the REST client do not — forward it only
        # where it exists (everything here strips via shallow copies
        # anyway, so copy=False is purely a hot-path hint)
        try:
            self._list_copy_kw = (
                "copy" in inspect.signature(type(store).list).parameters
            )
        except (AttributeError, TypeError, ValueError):
            self._list_copy_kw = False

    def __getattr__(self, name):
        return getattr(self._store, name)

    def _list(self, kind: str, copy: bool = True, **kw):
        if self._list_copy_kw:
            kw["copy"] = copy
        return self._store.list(kind, **kw)

    # ---------------------------------------------------------- ns helpers

    def _rt(self, kind: str):
        return self._store.resource_type(kind)

    def _is_ns_kind(self, kind: str) -> bool:
        try:
            return self._rt(kind).kind == "Namespace"
        except Exception:  # noqa: BLE001 — unknown kinds resolve downstream
            return False

    def _namespaced(self, kind: str) -> bool:
        try:
            return bool(self._rt(kind).namespaced)
        except Exception:  # noqa: BLE001
            return True

    def _strip(self, kind: str, obj):
        if obj is None:
            return None
        return _strip_object(self.tenant, obj, self._is_ns_kind(kind))

    # --------------------------------------------------------------- reads

    def get(self, kind: str, name: str, namespace: Optional[str] = None) -> dict:
        if self._is_ns_kind(kind):
            return self._strip(
                kind, self._store.get(kind, _map_ns(self.tenant, name))
            )
        if self._namespaced(kind):
            return self._strip(
                kind,
                self._store.get(kind, name, namespace=_map_ns(self.tenant, namespace)),
            )
        return self._store.get(kind, name, namespace=namespace)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector=None,
        field_selector=None,
        copy: bool = True,
    ) -> Tuple[List[dict], int]:
        if self._is_ns_kind(kind):
            items, rv = self._list(
                kind, copy, label_selector=label_selector,
                field_selector=field_selector,
            )
            mine = [
                _strip_object(self.tenant, o, True)
                for o in items
                if str((o.get("metadata") or {}).get("name") or "").startswith(
                    self._prefix
                )
            ]
            return mine, rv
        if not self._namespaced(kind):
            return self._list(
                kind, copy, namespace=namespace, label_selector=label_selector,
                field_selector=field_selector,
            )
        if namespace is not None:
            items, rv = self._list(
                kind,
                copy,
                namespace=_map_ns(self.tenant, namespace),
                label_selector=label_selector,
                field_selector=field_selector,
            )
            return [_strip_object(self.tenant, o, False) for o in items], rv
        items, rv = self._list(
            kind, copy, label_selector=label_selector,
            field_selector=field_selector,
        )
        mine = [
            _strip_object(self.tenant, o, False)
            for o in items
            if str((o.get("metadata") or {}).get("namespace") or "").startswith(
                self._prefix
            )
        ]
        return mine, rv

    def list_page(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector=None,
        field_selector=None,
        limit: int = 0,
        continue_from=None,
    ):
        # continue tokens stay store-global; pages filter to the tenant
        # afterwards (a page may come back short — the token still
        # advances, so pagination terminates correctly)
        ns = (
            _map_ns(self.tenant, namespace)
            if namespace is not None and self._namespaced(kind)
            and not self._is_ns_kind(kind)
            else namespace
        )
        items, rv, nxt = self._store.list_page(
            kind,
            namespace=ns,
            label_selector=label_selector,
            field_selector=field_selector,
            limit=limit,
            continue_from=continue_from,
        )
        if self._is_ns_kind(kind):
            items = [
                _strip_object(self.tenant, o, True)
                for o in items
                if str((o.get("metadata") or {}).get("name") or "").startswith(
                    self._prefix
                )
            ]
        elif self._namespaced(kind) and namespace is None:
            items = [
                _strip_object(self.tenant, o, False)
                for o in items
                if str((o.get("metadata") or {}).get("namespace") or "").startswith(
                    self._prefix
                )
            ]
        elif self._namespaced(kind):
            items = [_strip_object(self.tenant, o, False) for o in items]
        return items, rv, nxt

    def count(self, kind: str) -> int:
        if self._is_ns_kind(kind) or self._namespaced(kind):
            return len(self.list(kind, copy=False)[0])
        return self._store.count(kind)

    def watch(
        self,
        kind: str,
        namespace: Optional[str] = None,
        since_rv: Optional[int] = None,
        label_selector=None,
        field_selector=None,
        status_interest: bool = True,
    ):
        if self._is_ns_kind(kind):
            w = self._store.watch(
                kind, since_rv=since_rv, label_selector=label_selector,
                field_selector=field_selector, status_interest=status_interest,
            )
            return TenantWatcher(w, self.tenant, namespace_kind=True)
        if not self._namespaced(kind):
            return self._store.watch(
                kind, namespace=namespace, since_rv=since_rv,
                label_selector=label_selector, field_selector=field_selector,
                status_interest=status_interest,
            )
        if namespace is not None:
            w = self._store.watch(
                kind,
                namespace=_map_ns(self.tenant, namespace),
                since_rv=since_rv,
                label_selector=label_selector,
                field_selector=field_selector,
                status_interest=status_interest,
            )
            # exact-namespace watch needs no filtering, only stripping;
            # TenantWatcher's match passes everything the inner filter
            # admitted (all carry the tenant prefix)
            return TenantWatcher(w, self.tenant)
        w = self._store.watch(
            kind, since_rv=since_rv, label_selector=label_selector,
            field_selector=field_selector, status_interest=status_interest,
        )
        return TenantWatcher(w, self.tenant)

    # -------------------------------------------------------------- writes

    def _map_obj_in(self, obj: dict, namespace: Optional[str]) -> dict:
        """Inbound copy of ``obj`` with its effective namespace (or
        Namespace-kind name) mapped into the tenant prefix."""
        kind = (obj or {}).get("kind") or ""
        out = dict(obj)
        meta = dict(out.get("metadata") or {})
        if self._is_ns_kind(kind) if kind else False:
            if meta.get("name"):
                meta["name"] = _map_ns(self.tenant, meta["name"])
        elif not kind or self._namespaced(kind):
            meta["namespace"] = _map_ns(
                self.tenant, meta.get("namespace") or namespace
            )
        out["metadata"] = meta
        return out

    def create(
        self,
        obj: dict,
        namespace: Optional[str] = None,
        as_user: Optional[str] = None,
        copy_result: bool = True,
    ) -> dict:
        kind = (obj or {}).get("kind") or ""
        if kind and not self._namespaced(kind) and not self._is_ns_kind(kind):
            return self._store.create(
                obj, namespace=namespace, as_user=as_user, copy_result=copy_result
            )
        mapped = self._map_obj_in(obj, namespace)
        return self._strip(
            kind,
            self._store.create(mapped, as_user=as_user, copy_result=copy_result),
        )

    def update(
        self,
        obj: dict,
        subresource: str = "",
        as_user: Optional[str] = None,
    ) -> dict:
        kind = (obj or {}).get("kind") or ""
        if kind and not self._namespaced(kind) and not self._is_ns_kind(kind):
            return self._store.update(obj, subresource=subresource, as_user=as_user)
        mapped = self._map_obj_in(obj, None)
        return self._strip(
            kind,
            self._store.update(mapped, subresource=subresource, as_user=as_user),
        )

    def patch(
        self,
        kind: str,
        name: str,
        data,
        patch_type: str = "merge",
        namespace: Optional[str] = None,
        subresource: str = "",
        as_user: Optional[str] = None,
        **kw,
    ) -> dict:
        if self._is_ns_kind(kind):
            return self._strip(
                kind,
                self._store.patch(
                    kind, _map_ns(self.tenant, name), data, patch_type,
                    subresource=subresource, as_user=as_user, **kw,
                ),
            )
        if self._namespaced(kind):
            return self._strip(
                kind,
                self._store.patch(
                    kind, name, data, patch_type,
                    namespace=_map_ns(self.tenant, namespace),
                    subresource=subresource, as_user=as_user, **kw,
                ),
            )
        return self._store.patch(
            kind, name, data, patch_type, namespace=namespace,
            subresource=subresource, as_user=as_user, **kw,
        )

    def apply(
        self,
        kind: str,
        name: str,
        applied: dict,
        field_manager: str,
        force: bool = False,
        namespace: Optional[str] = None,
        as_user: Optional[str] = None,
    ):
        if self._is_ns_kind(kind):
            obj, created = self._store.apply(
                kind, _map_ns(self.tenant, name),
                self._map_obj_in(applied, None), field_manager,
                force=force, as_user=as_user,
            )
            return self._strip(kind, obj), created
        if self._namespaced(kind):
            obj, created = self._store.apply(
                kind, name, self._map_obj_in(applied, namespace),
                field_manager, force=force,
                namespace=_map_ns(self.tenant, namespace), as_user=as_user,
            )
            return self._strip(kind, obj), created
        return self._store.apply(
            kind, name, applied, field_manager, force=force,
            namespace=namespace, as_user=as_user,
        )

    def delete(
        self,
        kind: str,
        name: str,
        namespace: Optional[str] = None,
        as_user: Optional[str] = None,
        copy_result: bool = True,
    ):
        if self._is_ns_kind(kind):
            return self._strip(
                kind,
                self._store.delete(
                    kind, _map_ns(self.tenant, name),
                    as_user=as_user, copy_result=copy_result,
                ),
            )
        if self._namespaced(kind):
            return self._strip(
                kind,
                self._store.delete(
                    kind, name, namespace=_map_ns(self.tenant, namespace),
                    as_user=as_user, copy_result=copy_result,
                ),
            )
        return self._store.delete(
            kind, name, namespace=namespace, as_user=as_user,
            copy_result=copy_result,
        )

    def _map_op(self, op: dict) -> dict:
        kind = str(op.get("kind") or "")
        out = dict(op)
        if self._is_ns_kind(kind):
            if out.get("name"):
                out["name"] = _map_ns(self.tenant, out["name"])
            if isinstance(out.get("data"), dict):
                out["data"] = self._map_obj_in(out["data"], None)
        elif self._namespaced(kind):
            out["namespace"] = _map_ns(self.tenant, out.get("namespace"))
            data = out.get("data")
            if op.get("verb") == "create" and isinstance(data, dict):
                out["data"] = self._map_obj_in(data, out["namespace"] and None)
        return out

    def bulk(self, ops: List[dict], copy_results: bool = True, as_user=None):
        mapped = [self._map_op(op) for op in ops]
        res = self._store.bulk(mapped, copy_results=copy_results, as_user=as_user)
        return [
            self._strip(str(op.get("kind") or ""), r) if isinstance(r, dict) else r
            for op, r in zip(ops, res)
        ]

    def transact(self, ops: List[dict], as_user=None, copy_results: bool = True):
        # namespace-affinity after mapping: every op's namespace shares
        # the tenant prefix, and the placement hash truncates at the
        # separator — so a tenant txn is single-shard by construction
        mapped = [self._map_op(op) for op in ops]
        res = self._store.transact(mapped, as_user=as_user, copy_results=copy_results)
        return [
            self._strip(str(op.get("kind") or ""), r) if isinstance(r, dict) else r
            for op, r in zip(ops, res)
        ]

    # ------------------------------------------------------- host surfaces

    def dump_state(self, *a, **kw):
        raise NotFound("state dump is a fleet-host surface, not a tenant one")

    def restore_state(self, *a, **kw):
        raise NotFound("state restore is a fleet-host surface, not a tenant one")


class _Binding:
    """One warm tenant's in-memory materialization: the prefixing store
    proxy plus its k8s wire-protocol facade.  Dropped whole on
    scale-to-zero — durable state lives in the shared store."""

    __slots__ = ("store", "k8s")

    def __init__(self, store: TenantStore, k8s) -> None:
        self.store = store
        self.k8s = k8s


class FleetRegistry:
    """Lifecycle + routing authority for a fixed tenant set.

    State machine per tenant, computed from ``clock.now() - last_seen``
    (never stored, never ticked by a thread):

    - ``cold``: no binding (never seen, or swept after
      ``cold_after_s``); the first request cold-starts it.
    - ``warm``: a request arrived within ``idle_after_s``.
    - ``idle``: quiet past ``idle_after_s`` but not yet past
      ``cold_after_s``; the binding survives, so the next request is
      still warm-path.

    The sweep that drops cold bindings is opportunistic and
    rate-limited (piggybacks on ``touch``/``snapshot`` at most once per
    ``SWEEP_EVERY_S`` of the injected clock) — no background thread, no
    sleeps, fully deterministic under FakeClock/VirtualClock."""

    SWEEP_EVERY_S = 1.0

    def __init__(
        self,
        store,
        tenants: List[str],
        clock: Optional[Clock] = None,
        idle_after_s: float = 300.0,
        cold_after_s: float = 900.0,
        kubelet_url: Optional[str] = None,
    ):
        self._store = store
        self._ids = list(tenants)
        self._set = frozenset(self._ids)
        self._clock = clock or MonotonicClock()
        self.idle_after_s = float(idle_after_s)
        self.cold_after_s = max(float(cold_after_s), self.idle_after_s)
        self._kubelet_url = kubelet_url
        self._mut = make_lock("fleet.tenant.FleetRegistry._mut")
        self._bindings: Dict[str, _Binding] = {}
        # request threads + the lifecycle sweep share the binding map —
        # declared to the runtime race sentinel (KWOK_RACE_SENTINEL=1)
        guarded(self, "_bindings", "fleet.tenant.FleetRegistry._mut")
        self._last_seen: Dict[str, float] = {}
        self._cold_starts: Dict[str, int] = {t: 0 for t in self._ids}
        self._requests: Dict[str, int] = {t: 0 for t in self._ids}
        self._next_sweep = self._clock.now()
        n = int(getattr(store, "shard_count", 1) or 1)
        #: tenant -> pinned shard (stable: crc32 of the tenant segment)
        self.shards: Dict[str, int] = {
            t: shard_of(True, "Pod", _map_ns(t, "default"), n) for t in self._ids
        }

    # ------------------------------------------------------------- routing

    def tenants(self) -> List[str]:
        return list(self._ids)

    def is_tenant(self, tenant: str) -> bool:
        return tenant in self._set

    @staticmethod
    def level_for(tenant: str) -> str:
        """The tenant's APF priority level name IS its id (bounded:
        the fleet's tenant set is fixed at creation)."""
        return tenant

    # ----------------------------------------------------------- lifecycle

    def touch(self, tenant: str) -> Tuple[_Binding, bool]:
        """Route one request: returns the tenant's binding, cold-
        starting it if needed, and whether this request cold-started
        it.  Raises :class:`UnknownTenant` outside the fixed set."""
        if tenant not in self._set:
            raise UnknownTenant(f"unknown fleet tenant {tenant!r}")
        now = self._clock.now()
        cold_started = False
        t0 = time.monotonic()
        with self._mut:
            binding = self._bindings.get(tenant)
            if binding is None:
                binding = self._bind(tenant)
                self._bindings[tenant] = binding
                cold_started = True
                self._cold_starts[tenant] += 1
            self._last_seen[tenant] = now
            self._requests[tenant] += 1
        if cold_started:
            # first request materializes the virtual cluster's bootstrap
            # namespaces (default/kube-system, tenant-prefixed in the
            # shared store) — outside the registry lock, the store has
            # its own
            self._ensure_bootstrap(binding)
            from kwok_tpu.fleet import views

            views.observe_cold_start(time.monotonic() - t0)
        self.sweep(now=now)
        return binding, cold_started

    def _bind(self, tenant: str) -> _Binding:
        from kwok_tpu.cluster.k8s_api import K8sFacade

        ts = TenantStore(self._store, tenant)
        return _Binding(ts, K8sFacade(ts, kubelet_url=self._kubelet_url))

    def _ensure_bootstrap(self, binding: _Binding) -> None:
        ensure = getattr(binding.k8s, "ensure_namespaces", None)
        if ensure is not None:
            try:
                ensure()
                return
            except AlreadyExists:
                return
            except Exception:  # noqa: BLE001 — degraded storage: serve reads
                return
        try:
            binding.store.create({"kind": "Namespace", "metadata": {"name": "default"}})
        except AlreadyExists:
            pass
        except Exception:  # noqa: BLE001
            pass

    def state_of(self, tenant: str, now: Optional[float] = None) -> str:
        if tenant not in self._set:
            raise UnknownTenant(f"unknown fleet tenant {tenant!r}")
        now = self._clock.now() if now is None else now
        with self._mut:
            return self._state_locked(tenant, now)

    def _state_locked(self, tenant: str, now: float) -> str:
        if tenant not in self._bindings:
            return COLD
        age = now - self._last_seen.get(tenant, now)
        if age >= self.cold_after_s:
            return COLD  # due for the next sweep; already reads cold
        if age >= self.idle_after_s:
            return IDLE
        return WARM

    def sweep(self, now: Optional[float] = None, force: bool = False) -> int:
        """Drop bindings whose tenants went cold (scale-to-zero).
        Rate-limited on the injected clock unless ``force``; returns
        how many bindings were dropped."""
        now = self._clock.now() if now is None else now
        with self._mut:
            if not force and now < self._next_sweep:
                return 0
            self._next_sweep = now + self.SWEEP_EVERY_S
            dead = [
                t
                for t in self._bindings
                if now - self._last_seen.get(t, now) >= self.cold_after_s
            ]
            for t in dead:
                del self._bindings[t]
            return len(dead)

    # --------------------------------------------------------- observation

    @staticmethod
    def observe(tenant: str, seconds: float) -> None:
        """Per-tenant request-duration observation (the apiserver calls
        this through the duck-typed fleet seam so cluster/ never
        imports fleet/)."""
        from kwok_tpu.fleet import views

        views.observe_request(tenant, seconds)

    # ----------------------------------------------------------- reporting

    def snapshot(self) -> Dict[str, int]:
        """Bounded summary for ``/stats`` and ``kwokctl get
        components``: tenant count + cold/warm/idle split."""
        now = self._clock.now()
        self.sweep(now=now)
        counts = {COLD: 0, WARM: 0, IDLE: 0}
        with self._mut:
            for t in self._ids:
                counts[self._state_locked(t, now)] += 1
            return {
                "tenants": len(self._ids),
                "warm": counts[WARM],
                "idle": counts[IDLE],
                "cold": counts[COLD],
                "cold_starts": sum(self._cold_starts.values()),
            }

    def describe(self) -> List[Dict[str, object]]:
        """Per-tenant rows for ``GET /fleet`` / ``kwokctl get fleet``."""
        now = self._clock.now()
        self.sweep(now=now)
        with self._mut:
            return [
                {
                    "tenant": t,
                    "state": self._state_locked(t, now),
                    "shard": self.shards[t],
                    "cold_starts": self._cold_starts[t],
                    "requests": self._requests[t],
                }
                for t in self._ids
            ]

    def report(self) -> Dict[str, object]:
        """The ``GET /fleet`` body: the lifecycle summary plus
        per-tenant rows joined with each tenant's observed latency
        quantiles and the fleet-wide cold-start distribution."""
        from kwok_tpu.fleet import views

        lat = views.latency_summary()
        rows = self.describe()
        for row in rows:
            row["latency"] = lat.get(row["tenant"])
        out: Dict[str, object] = dict(self.snapshot())
        out["cold_start_latency"] = views.cold_start_quantiles()
        out["rows"] = rows
        return out

    def tenant_detail(self, tenant: str) -> Dict[str, object]:
        """One tenant's deep view (``GET /fleet?tenant=``): lifecycle
        row + latency + journey timelines + critical-path budget.
        Raises :class:`UnknownTenant` outside the fleet."""
        from kwok_tpu.fleet import views

        state = self.state_of(tenant)  # raises UnknownTenant
        with self._mut:
            row: Dict[str, object] = {
                "tenant": tenant,
                "state": state,
                "shard": self.shards[tenant],
                "cold_starts": self._cold_starts[tenant],
                "requests": self._requests[tenant],
            }
        row["latency"] = views.tenant_latency(tenant)
        row["journeys"] = views.tenant_journeys(tenant)
        row["critical_path"] = views.tenant_critical_path(tenant)
        return row
