"""Per-tenant observability: latency quantiles, journeys, critical path.

Everything here is a *view* over the bounded instrumentation that
already exists (utils/telemetry histograms + JourneyRecorder) — no new
per-object state, no unbounded labels.  The only label this module ever
attaches is the tenant id, whose set is fixed at fleet creation
(``kwokctl create fleet --clusters N``), so cardinality is bounded by
configuration; ``max_children`` is raised accordingly and the overflow
still folds into ``(other)`` as a backstop.

Per-tenant journeys need no tenant label at all: a tenant's objects
live in ``<tenant>--*`` namespaces, so the journey ring's existing
namespace field IS the tenant attribution — we filter at read time.

Reference: kwokctl renders per-cluster status by iterating runtime dirs
(reference pkg/kwokctl/cmd/get/clusters/clusters.go:40); here the
per-tenant view is one process's telemetry sliced by tenant.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from kwok_tpu.cluster.sharding.router import TENANT_SEP
from kwok_tpu.utils import telemetry as _telemetry

__all__ = [
    "observe_request",
    "observe_cold_start",
    "tenant_latency",
    "latency_summary",
    "cold_start_quantiles",
    "tenant_journeys",
    "tenant_critical_path",
]

#: request duration per tenant.  The "tenant" label is bounded by the
#: fleet's fixed tenant set (never an object name); max_children covers
#: a 1k-tenant fleet with headroom before the (other) fold kicks in.
_H_TENANT_REQ = _telemetry.histogram(
    "kwok_fleet_tenant_request_seconds",
    help="apiserver request duration per fleet tenant",
    labelnames=("tenant",),
    max_children=4096,
)

#: cold-start cost: binding + bootstrap-namespace materialization on a
#: tenant's first request after scale-to-zero (no labels — the
#: distribution is the fleet-wide SLO, per-tenant counts live in
#: FleetRegistry.describe())
_H_COLD_START = _telemetry.histogram(
    "kwok_fleet_cold_start_seconds",
    help="tenant cold-start duration (binding + bootstrap)",
)


def observe_request(tenant: str, seconds: float) -> None:
    _H_TENANT_REQ.observe(seconds, tenant)


def observe_cold_start(seconds: float) -> None:
    _H_COLD_START.observe(seconds)


def _child_quantile(
    counts: Sequence[int], bounds: Sequence[float], q: float
) -> Optional[float]:
    """Cumulative-bucket interpolation over ONE child's counts (the
    family's ``quantile`` aggregates across children — per-tenant views
    need the single-child form)."""
    total = sum(counts)
    if total == 0:
        return None
    target = q * total
    run = 0.0
    for i, n in enumerate(counts):
        prev = run
        run += n
        if run >= target and n:
            if i >= len(bounds):
                return bounds[-1] if bounds else 0.0
            lo = bounds[i - 1] if i else 0.0
            hi = bounds[i]
            return lo + (hi - lo) * ((target - prev) / n)
    return bounds[-1] if bounds else 0.0


def tenant_latency(tenant: str) -> Optional[Dict[str, float]]:
    """One tenant's observed request-latency summary
    (p50/p99/count), or None before its first observation."""
    data = _H_TENANT_REQ.snapshot().get((tenant,))
    if data is None or not data["count"]:
        return None
    bounds = _H_TENANT_REQ.bounds
    return {
        "p50": round(_child_quantile(data["counts"], bounds, 0.50) or 0.0, 6),
        "p99": round(_child_quantile(data["counts"], bounds, 0.99) or 0.0, 6),
        "count": int(data["count"]),
    }


def latency_summary() -> Dict[str, Dict[str, float]]:
    """{tenant: {p50, p99, count}} for every tenant that has traffic
    (the ``kwokctl get fleet`` latency columns)."""
    bounds = _H_TENANT_REQ.bounds
    out: Dict[str, Dict[str, float]] = {}
    for lv, data in _H_TENANT_REQ.snapshot().items():
        if not data["count"]:
            continue
        t = lv[0] if lv else ""
        out[t] = {
            "p50": round(_child_quantile(data["counts"], bounds, 0.50) or 0.0, 6),
            "p99": round(_child_quantile(data["counts"], bounds, 0.99) or 0.0, 6),
            "count": int(data["count"]),
        }
    return out


def cold_start_quantiles() -> Optional[Dict[str, float]]:
    """Fleet-wide cold-start p50/p99 (None before any cold start)."""
    if not _H_COLD_START.total_count():
        return None
    return {
        "p50": round(_H_COLD_START.quantile(0.50) or 0.0, 6),
        "p99": round(_H_COLD_START.quantile(0.99) or 0.0, 6),
        "count": int(_H_COLD_START.total_count()),
    }


def tenant_journeys(
    tenant: str, kind: Optional[str] = None, limit: int = 20
) -> List[Dict[str, object]]:
    """The tenant's slice of the journey ring: timelines whose
    namespace carries the tenant prefix, rendered with the prefix
    stripped so they match what the tenant's own API surface shows."""
    prefix = tenant + TENANT_SEP
    out: List[Dict[str, object]] = []
    # over-fetch: the ring interleaves every tenant's objects
    for j in _telemetry.journey().journeys(kind=kind, limit=max(limit * 8, 64)):
        ns = str(j.get("namespace") or "")
        if not ns.startswith(prefix):
            continue
        j = dict(j)
        j["namespace"] = ns[len(prefix):]
        out.append(j)
        if len(out) >= limit:
            break
    return out


def tenant_critical_path(
    tenant: str, kind: Optional[str] = None, limit: int = 50
) -> Dict[str, object]:
    """The tenant's time budget: per-hop totals aggregated from its
    journey timelines (each inter-hop gap attributed to the later hop,
    the same accounting as the collector's critical-path view) — where
    this tenant's objects actually spend their lifecycle time."""
    budget: Dict[str, float] = {}
    hops_seen = 0
    journeys = tenant_journeys(tenant, kind=kind, limit=limit)
    for j in journeys:
        prev_t: Optional[float] = None
        for hop in j.get("hops") or []:
            t = hop.get("t_mono")
            name = str(hop.get("hop") or "")
            if not name or not isinstance(t, (int, float)):
                continue
            hops_seen += 1
            if prev_t is not None and t >= prev_t:
                budget[name] = budget.get(name, 0.0) + (t - prev_t)
            prev_t = t
    return {
        "tenant": tenant,
        "journeys": len(journeys),
        "hops": hops_seen,
        "budget_s": {k: round(v, 6) for k, v in sorted(budget.items())},
    }
