"""Cluster fleets: many virtual control planes on one apiserver.

The north star is heavy traffic from millions of users — thousands of
cheap clusters, not one big one (ROADMAP open item 2).  A *fleet* makes
a cluster an in-process tenant of a single apiserver: each tenant owns
a namespace-prefixed slice of the shared :class:`ResourceStore`
(``<tenant>--<namespace>``), a lifecycle (cold → warm on first request,
warm → idle → cold again on the injected clock, the scale-to-zero shape
of on-demand Wasm/WASI edge control planes re-expressed over this
substrate — PAPERS.md), a pinned store shard (the placement hash
truncates at the tenant separator, so a tenant's whole object space —
and therefore its transactions — stays single-shard,
``kwok_tpu/cluster/sharding/router.py``), and a dedicated APF priority
level (``level == tenant id``, generated into a ``FlowConfiguration``
with ``shares: 0`` = guaranteed-minimum seats, so one tenant's flood
saturates only its own queues and can never consume a neighbor's — or
the system level's — seats, ``kwok_tpu/cluster/flowcontrol.py``).

Layering: ``fleet`` sits ABOVE ``cluster``/``cluster.sharding`` in the
kwoklint layer map — the apiserver reaches it only through the
duck-typed ``fleet=`` constructor seam (the same pattern the chaos
fault injector uses), never by import.

Reference surface: kwokctl manages many clusters side by side
(reference pkg/kwokctl/cmd/create/cluster + ``kwokctl get clusters``
iterate independent runtime dirs); a fleet is that multi-cluster
surface collapsed into one process.
"""

from kwok_tpu.fleet.flow import fleet_flow_config, tenant_client_id
from kwok_tpu.fleet.tenant import (
    TENANT_HEADER,
    FleetRegistry,
    TenantStore,
    TenantWatcher,
    UnknownTenant,
    fleet_tenant_ids,
)

__all__ = [
    "TENANT_HEADER",
    "FleetRegistry",
    "TenantStore",
    "TenantWatcher",
    "UnknownTenant",
    "fleet_tenant_ids",
    "fleet_flow_config",
    "tenant_client_id",
]
