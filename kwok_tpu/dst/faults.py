"""Fault vocabulary for deterministic-simulation runs.

Re-expresses the chaos subsystem's existing faults in virtual time, at
the in-process store boundary instead of the HTTP one:

- **crash points** arm the store's commit-boundary hook
  (``kwok_tpu/cluster/store.py:606``) and the harness then recovers a
  fresh store from the WAL, exactly like the durability smoke
  (``kwok_tpu/chaos/__main__.py:48``);
- **partitions / 429 shedding / eaten acks** mirror the HTTP
  injector's per-request decisions (``kwok_tpu/chaos/http_faults.py:1``)
  as seeded draws on each store call;
- **leader kills / pauses** depose replicas the way the process driver
  SIGKILLs/SIGSTOPs daemons (``kwok_tpu/chaos/process_faults.py:1``);
- **write fencing** revalidates each mutation's leadership generation
  against the live election Lease, the apiserver's
  ``X-Kwok-Leader-Fence`` check (``kwok_tpu/cluster/apiserver.py:248``)
  replayed in-process.

Every decision draws from one seeded rng, so a fault schedule is a
pure function of the seed.

The schedule is also a first-class *value*: ``FaultTimeline.to_spec``
serializes the constructed windows + point faults to a JSON-able dict
and ``FaultTimeline.from_spec`` rebuilds a timeline from one — the
mutation space of the coverage-guided search
(``kwok_tpu/dst/search.py``).  A from_spec timeline keeps the SAME
seeded rng for the runtime draws (shed probability tests, eaten acks,
fire-time shard targeting), so a (seed, spec) pair replays
byte-identically.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kwok_tpu.cluster.client import ApiUnavailable
from kwok_tpu.cluster.store import Conflict, StorageDegraded

__all__ = ["SimCrash", "FaultTimeline", "ActorStore"]


class SimCrash(BaseException):
    """Simulated process death at a store commit boundary.

    BaseException on purpose: component code catches broad
    ``Exception`` around its loops (a real process would still die),
    so the crash must unwind through all of it to the harness."""

    def __init__(self, phase: str):
        super().__init__(f"simulated crash at {phase}")
        self.phase = phase


@dataclass(frozen=True)
class _Window:
    """One scheduled fault window.  ``target`` names a replica (its
    whole process goes dark — both its ``controller:`` and ``system:``
    client identities), or is empty to cover every below-system client
    (the overload-shed shape)."""

    kind: str  # "partition" | "shed"
    target: str
    at: float
    duration: float
    p: float = 1.0  # per-call probability inside the window

    def covers(self, client_id: str, t: float) -> bool:
        if not (self.at <= t < self.at + self.duration):
            return False
        if self.target:
            return client_id.endswith(f":{self.target}") or client_id == self.target
        return not client_id.startswith("system:")


@dataclass
class _Scheduled:
    """A point fault the harness applies when virtual time reaches it."""

    t: float
    kind: str  # "crash" | "leader-kill" | "pause" | "resume" | "restart"
    params: Dict[str, Any] = field(default_factory=dict)
    fired: bool = False


class FaultTimeline:
    """The seed-derived schedule of every fault in one run."""

    #: probability an acked mutation's response is "eaten" (applied,
    #: ack lost) while inside the active fault window
    ACK_EATEN_P = 0.02

    def __init__(
        self,
        seed: int,
        t0: float,
        window_s: float,
        seats: List[str],
        replica_clients: List[str],
        enable: bool = True,
    ):
        self.seed = seed
        self.rng = random.Random((seed << 1) ^ 0x5F5E5F)
        self.windows: List[_Window] = []
        self.scheduled: List[_Scheduled] = []
        self.ack_window = (t0, t0 + window_s)
        self.enabled = enable
        if not enable:
            return
        rng = self.rng
        # 1-2 partition windows against seeded replicas
        for _ in range(rng.randint(1, 2)):
            target = rng.choice(replica_clients)
            at = t0 + rng.uniform(0.0, window_s * 0.7)
            self.windows.append(
                _Window("partition", target, at, rng.uniform(2.0, 6.0))
            )
        # one overload/shed window against everything below system
        at = t0 + rng.uniform(0.0, window_s * 0.6)
        self.windows.append(
            _Window("shed", "", at, rng.uniform(2.0, 5.0), p=0.3)
        )
        # one store crash
        self.scheduled.append(
            _Scheduled(
                t=t0 + rng.uniform(2.0, window_s * 0.8),
                kind="crash",
                params={
                    "phase": rng.choice(["before-commit", "after-commit"]),
                    # let N commits pass after arming before firing
                    "skip": rng.randint(0, 8),
                },
            )
        )
        # one leader kill (silent death) with a later replica restart
        seat = rng.choice(seats)
        t_kill = t0 + rng.uniform(2.0, window_s * 0.7)
        self.scheduled.append(
            _Scheduled(t=t_kill, kind="leader-kill", params={"seat": seat})
        )
        self.scheduled.append(
            _Scheduled(
                t=t_kill + rng.uniform(6.0, 12.0),
                kind="restart",
                params={"seat": seat},
            )
        )
        # one pause/resume (SIGSTOP/SIGCONT zombie) on a seeded seat
        seat2 = rng.choice(seats)
        t_pause = t0 + rng.uniform(2.0, window_s * 0.8)
        dur = rng.uniform(1.0, 8.0)
        self.scheduled.append(
            _Scheduled(t=t_pause, kind="pause", params={"seat": seat2})
        )
        self.scheduled.append(
            _Scheduled(t=t_pause + dur, kind="resume", params={"seat": seat2})
        )
        # one disk corruption against the store's WAL (the storage
        # fault vocabulary of kwok_tpu.chaos.disk_faults, in virtual
        # time): the harness corrupts the log file at a seeded offset
        # and recovery must be detected + honest (recovery-honesty
        # invariant)
        self.scheduled.append(
            _Scheduled(
                t=t0 + rng.uniform(3.0, window_s * 0.85),
                kind="disk-corrupt",
                params={"mode": rng.choice(["bit-flip", "truncate"])},
            )
        )
        # one storage-exhaustion window (kwok_tpu.chaos.fs_pressure, in
        # virtual time): the WAL's writes are refused for the window;
        # the store must go honestly read-only and re-arm at the end
        # (exhaustion-honesty invariant).  Only the write-path kinds:
        # fsync-error needs a fsync *policy* to trigger, and the DST
        # WAL runs fsync="off" to stay off the wall clock — that shape
        # is covered by --exhaustion-smoke instead.
        p_mode = rng.choice(["disk-full", "quota"])
        t_p = t0 + rng.uniform(3.0, window_s * 0.8)
        p_dur = rng.uniform(1.5, 4.0)
        self.scheduled.append(
            _Scheduled(
                t=t_p,
                kind="pressure-start",
                params={"mode": p_mode, "duration": p_dur},
            )
        )
        self.scheduled.append(
            _Scheduled(
                t=t_p + p_dur, kind="pressure-end", params={"mode": p_mode}
            )
        )
        self.scheduled.sort(key=lambda s: s.t)

    def seal_runtime_rng(self) -> None:
        """Reseed ``self.rng`` onto the runtime draw stream — a pure
        function of the seed, independent of how many draws
        construction consumed.  Both construction paths call this once
        the schedule is final (``seeded_timeline`` after its region-move
        draw; ``from_spec`` after rebuilding), so a timeline built from
        a seed and one rebuilt from any spec under that seed make
        byte-identical runtime draws (shed p-tests, eaten acks,
        fire-time shard targeting).  That is what makes a mutated
        schedule's run a pure function of (seed, spec) — the replay
        contract of the coverage-guided search."""
        self.rng = random.Random((self.seed << 2) ^ 0x0D15EA5E)

    # ----------------------------------------------------- spec round-trip

    def to_spec(self) -> Dict[str, Any]:
        """Serialize the constructed schedule to a JSON-able dict (the
        corpus-entry format of the coverage-guided search).  Captures
        construction-time state only — call before the run consumes
        ``fired`` flags."""
        return {
            "enabled": self.enabled,
            "ack_window": [self.ack_window[0], self.ack_window[1]],
            "windows": [
                {
                    "kind": w.kind,
                    "target": w.target,
                    "at": w.at,
                    "duration": w.duration,
                    "p": w.p,
                }
                for w in self.windows
            ],
            "scheduled": [
                {"t": s.t, "kind": s.kind, "params": dict(s.params)}
                for s in self.scheduled
            ],
        }

    @classmethod
    def from_spec(cls, spec: Dict[str, Any], seed: int) -> "FaultTimeline":
        """Rebuild a timeline from a spec.  Windows and point faults
        come from the spec verbatim; the runtime rng is sealed onto the
        same seed-derived stream ``seeded_timeline`` ends on, so the
        run is a pure function of (seed, spec) and a replayed spec is
        byte-identical to the search's own execution of it."""
        tl = cls.__new__(cls)
        tl.seed = seed
        tl.rng = random.Random((seed << 1) ^ 0x5F5E5F)
        tl.enabled = bool(spec.get("enabled", True))
        tl.ack_window = tuple(spec.get("ack_window") or (0.0, 0.0))
        tl.windows = [
            _Window(
                kind=w["kind"],
                target=w.get("target", ""),
                at=float(w["at"]),
                duration=float(w["duration"]),
                p=float(w.get("p", 1.0)),
            )
            for w in spec.get("windows") or []
        ]
        tl.scheduled = [
            _Scheduled(
                t=float(s["t"]), kind=s["kind"], params=dict(s.get("params") or {})
            )
            for s in spec.get("scheduled") or []
        ]
        tl.scheduled.sort(key=lambda s: s.t)
        tl.seal_runtime_rng()
        return tl

    # ------------------------------------------------------------ queries

    def due(self, t: float) -> List[_Scheduled]:
        out = []
        for s in self.scheduled:
            if not s.fired and s.t <= t:
                s.fired = True
                out.append(s)
        return out

    def next_time(self) -> Optional[float]:
        pending = [s.t for s in self.scheduled if not s.fired]
        return min(pending) if pending else None

    def pressure_end_after(self, t: float) -> float:
        """The earliest unfired pressure-end instant (scenario writes
        refused by the degraded gate reschedule to just past it)."""
        ends = [
            s.t
            for s in self.scheduled
            if s.kind == "pressure-end" and not s.fired and s.t > t
        ]
        return min(ends) if ends else t + 1.0

    def add_region_move(
        self, client_id: str, at: float, duration: float
    ) -> None:
        """Tenant region transfer (kwok_tpu/fleet): the tenant's
        clients go dark for the cutover window — cross-region latency
        taken to its limit on the virtual clock — then resume against
        the same store.  Expressed as a partition window so every
        existing retry/fence seam covers it; the harness records the
        window and the tenant-isolation invariant asserts the tenant
        resumed writes after it (bounded disruption)."""
        self.windows.append(_Window("partition", client_id, at, duration))
        self.scheduled.append(
            _Scheduled(
                t=at,
                kind="tenant-region-move",
                params={"client": client_id, "duration": duration},
            )
        )
        self.scheduled.sort(key=lambda s: s.t)

    def partitioned(self, client_id: str, t: float) -> bool:
        return any(
            w.kind == "partition" and w.covers(client_id, t)
            for w in self.windows
        )

    def shed(self, client_id: str, t: float) -> bool:
        for w in self.windows:
            if w.kind == "shed" and w.covers(client_id, t):
                if self.rng.random() < w.p:
                    return True
        return False

    def ack_eaten(self, t: float) -> bool:
        lo, hi = self.ack_window
        return (
            self.enabled
            and lo <= t < hi
            and self.rng.random() < self.ACK_EATEN_P
        )


class ActorStore:
    """Per-actor store facade — the simulated process/network boundary.

    Duck-typed to ResourceStore like ClusterClient is: reads and writes
    forward to the harness's *current* store (so a crash-recovered
    store is picked up transparently, the way a reconnecting HTTP
    client would), with the fault timeline consulted on every call and
    mutations (a) attributed via ``as_user`` for the audit stream,
    (b) fence-checked against the live election Lease, and (c) traced.
    """

    def __init__(self, sim, actor: str, client_id: str, fence_provider=None):
        self._sim = sim
        self._actor = actor
        self.client_id = client_id
        self.fence_provider = fence_provider

    # ------------------------------------------------------------- gates

    def _now(self) -> float:
        return self._sim.clock.now()

    def _gate(self, mutating: bool) -> None:
        sim = self._sim
        t = self._now()
        if sim.faults.partitioned(self.client_id, t):
            raise ApiUnavailable(f"partitioned ({self.client_id})")
        if sim.faults.shed(self.client_id, t):
            raise ApiUnavailable("shed with 429 Retry-After")
        if mutating and self.fence_provider is not None:
            token = self.fence_provider()
            if token:
                self._check_fence(token)

    def _check_fence(self, token: str) -> None:
        """The apiserver's stale-generation rejection, in-process —
        the SAME validator the HTTP gate runs
        (cluster/election.py validate_fence), so DST verifies exactly
        the contract production enforces."""
        from kwok_tpu.cluster.election import validate_fence

        stale = validate_fence(self._sim.store, token)
        if stale is not None:
            raise Conflict(f"stale leader fence: {stale}")

    # ------------------------------------------------------------- reads

    def get(self, *a, **kw):
        self._gate(False)
        return self._sim.store.get(*a, **kw)

    def list(self, *a, **kw):
        self._gate(False)
        return self._sim.store.list(*a, **kw)

    def list_paged(self, *a, **kw):
        self._gate(False)
        return self._sim.store.list_paged(*a, **kw)

    def list_page(self, *a, **kw):
        self._gate(False)
        return self._sim.store.list_page(*a, **kw)

    def kinds(self):
        self._gate(False)
        return self._sim.store.kinds()

    def count(self, kind):
        self._gate(False)
        return self._sim.store.count(kind)

    def resource_type(self, kind):
        return self._sim.store.resource_type(kind)

    def watch(self, *a, **kw):
        self._gate(False)
        return self._sim.store.watch(*a, **kw)

    # ----------------------------------------------------------- mutators

    def _mutate(self, verb: str, fn, detail_fn, *a, **kw):
        sim = self._sim
        self._gate(True)
        if kw.get("as_user") is None:
            kw["as_user"] = self.client_id
        rv_before = sim.store.resource_version
        try:
            result = fn(*a, **kw)
        except StorageDegraded:
            # degraded read-only mode refused the mutation — a VISIBLE
            # rejection (the exhaustion-honesty invariant counts these
            # against silently-lost acks)
            sim.note_degraded_rejection(self._actor, verb)
            raise
        t = self._now()
        for action, detail in detail_fn(result):
            sim.trace.add(t, self._actor, action, detail)
        if sim.faults.ack_eaten(t):
            # applied, but the caller never learns: NOT an acked write
            sim.trace.add(t, self._actor, "ack-eaten", verb)
            raise ApiUnavailable("response lost after apply")
        # the sim is single-threaded: every rv in (rv_before, now] was
        # committed by THIS call — the acked set the recovery-honesty
        # invariant audits disk-fault recoveries against
        sim.note_ack(rv_before)
        return result

    @staticmethod
    def _obj_detail(verb: str, obj: Optional[dict]) -> List:
        if not isinstance(obj, dict):
            return [(verb, "")]
        kind = obj.get("kind") or ""
        meta = obj.get("metadata") or {}
        key = f"{kind} {meta.get('namespace') or ''}/{meta.get('name') or ''}"
        extra = ""
        if kind == "Pod":
            refs = meta.get("ownerReferences") or []
            if refs:
                extra = f" owner={refs[0].get('kind')}:{refs[0].get('name')}"
        spec = obj.get("spec") or {}
        if kind in ("ReplicaSet", "Deployment") and "replicas" in spec:
            extra = f" replicas={spec.get('replicas')}"
        return [(verb, key + extra)]

    def create(self, obj, **kw):
        return self._mutate(
            "create",
            self._sim.store.create,
            lambda res: self._obj_detail("create", res),
            obj,
            **kw,
        )

    def update(self, obj, **kw):
        return self._mutate(
            "update",
            self._sim.store.update,
            lambda res: self._obj_detail("update", res),
            obj,
            **kw,
        )

    def patch(self, kind, name, data, patch_type="merge", **kw):
        return self._mutate(
            "patch",
            self._sim.store.patch,
            lambda res: self._obj_detail("patch", res),
            kind,
            name,
            data,
            patch_type,
            **kw,
        )

    def delete(self, kind, name, **kw):
        ns = kw.get("namespace") or ""

        def details(_res):
            return [("delete", f"{kind} {ns}/{name}")]

        return self._mutate(
            "delete", self._sim.store.delete, details, kind, name, **kw
        )

    def apply(self, *a, **kw):
        return self._mutate(
            "apply",
            self._sim.store.apply,
            lambda res: self._obj_detail(
                "apply", res[0] if isinstance(res, tuple) else res
            ),
            *a,
            **kw,
        )

    def bulk(self, ops, **kw):
        def details(results):
            out = []
            okn = sum(1 for r in results if r.get("status") == "ok")
            out.append(("bulk", f"{len(ops)} ok={okn}"))
            for op, res in zip(ops, results):
                if res.get("status") != "ok" or not isinstance(op, dict):
                    continue
                verb = op.get("verb")
                if verb == "create":
                    # result object, not op data: generateName pods get
                    # their final name at commit time
                    out.extend(self._obj_detail("create", res.get("object")))
                elif verb == "delete":
                    ns = op.get("namespace") or ""
                    out.append(
                        ("delete", f"{op.get('kind')} {ns}/{op.get('name')}")
                    )
                elif verb == "patch":
                    data = op.get("data") or {}
                    extra = ""
                    if isinstance(data, dict):
                        spec = data.get("spec") or {}
                        if isinstance(spec, dict) and "replicas" in spec:
                            extra = f" replicas={spec.get('replicas')}"
                    ns = op.get("namespace") or ""
                    out.append(
                        (
                            "patch",
                            f"{op.get('kind')} {ns}/{op.get('name')}" + extra,
                        )
                    )
            return out

        return self._mutate("bulk", self._sim.store.bulk, details, ops, **kw)

    def transact(self, ops, **kw):
        """The atomic gang-bind lane (ResourceStore.transact): traced
        as one ``txn`` action plus the per-object details — the
        single-reconciler invariant gates it like any other write, and
        the gang-atomicity probes read the resulting store states."""

        def details(results):
            out = [("txn", f"{len(ops)} ok")]
            for op, res in zip(ops, results):
                verb = op.get("verb") if isinstance(op, dict) else None
                if verb == "create":
                    out.extend(self._obj_detail("create", res))
                elif verb == "delete":
                    ns = op.get("namespace") or ""
                    out.append(
                        ("delete", f"{op.get('kind')} {ns}/{op.get('name')}")
                    )
                elif verb == "patch":
                    out.extend(self._obj_detail("patch", res))
            return out

        return self._mutate(
            "txn", self._sim.store.transact, details, ops, **kw
        )

    # ----------------------------------------------------------- fallback

    def __getattr__(self, name):
        # anything else (audit_log, resource_version, ...) is a
        # harness-side read, not simulated traffic
        return getattr(self._sim.store, name)
