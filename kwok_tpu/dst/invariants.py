"""Kivi-style control-plane invariants, checked over completed runs.

Each checker is a pure function ``check(record) -> [violations]`` over
the finished run's :class:`~kwok_tpu.dst.harness.RunRecord` (trace +
observer streams + crash-recovery probes + final/replayed state), the
trace-level verification PAPERS.md motivates (Kivi finds real cluster
bugs by checking small invariants over event traces) and ROADMAP.md:101
specifies for this repo:

- at most one active reconciler per seat (writes only inside the
  writer's own leadership epoch; lease transitions strictly increase —
  the fencing contract of ``kwok_tpu/cluster/election.py:91``),
- no lost acknowledged write (crash recovery never rolls back below an
  acked resourceVersion, and the final WAL replay reproduces the live
  state byte-identically — the guarantee ``kwok_tpu/cluster/wal.py:67``
  exists to provide),
- no duplicate reconcile (a ReplicaSet's controller never creates
  beyond its current spec.replicas),
- watch resourceVersion monotonicity per stream
  (``kwok_tpu/cluster/store.py:1307`` resume semantics),
- Deployment/HPA convergence once faults stop,
- trace completeness (the audit ring must not have overflowed —
  a truncated trace must fail loudly, never pass vacuously),
- recovery honesty (disk-fault recoveries are detected and the
  recovered state + reported-lost set account for every acked rv —
  the storage-integrity contract of ``kwok_tpu/cluster/wal.py:1``),
- exhaustion honesty (every write acked inside a storage-pressure
  window is durable in the log or was visibly rejected, and writes
  re-arm when the window closes — the degraded read-only contract of
  ``kwok_tpu/chaos/fs_pressure.py:1``),
- gang atomicity (no recovered, final, or WAL-replayed state shows a
  bound strict subset of a PodGroup — the all-or-nothing admission
  contract of ``kwok_tpu/sched/engine.py:1``),
- tenant isolation (no fleet tenant's write surfaces in another
  tenant's scoped watch stream, a flooded tenant's APF level never
  starves a neighbor or the system level, and a region-moved tenant
  resumes inside a bounded window — the enforced-isolation contract
  of ``kwok_tpu/fleet/tenant.py``).

Pluggable: ``INVARIANTS`` maps name → checker; ``run_checks`` runs a
selection and returns ``{name: [violations]}``.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List

__all__ = ["INVARIANTS", "run_checks"]

#: trace actions that are leader-gated controller writes
_WRITE_ACTIONS = {"create", "update", "patch", "delete", "apply", "bulk", "txn"}

_ELECTED_RE = re.compile(r"^(?P<lease>\S+) transitions=(?P<tr>-?\d+)$")


def check_single_reconciler(record) -> List[str]:
    out: List[str] = []
    open_epochs: Dict[str, bool] = {}  # replica name -> leading now
    last_transitions: Dict[str, int] = {}  # lease -> last elected gen
    for ev in record.trace.events:
        if ev.action == "disk-recovered":
            # a lossy storage recovery legitimately rolls Lease state
            # (and its transition counter) back below what was acked —
            # the loss was detected and probed; re-baseline instead of
            # flagging a phantom regression
            last_transitions.clear()
            continue
        if ev.action == "elected":
            m = _ELECTED_RE.match(ev.detail)
            if m:
                lease, tr = m.group("lease"), int(m.group("tr"))
                prev = last_transitions.get(lease)
                if prev is not None and tr < prev:
                    out.append(
                        f"t={ev.t:.3f} lease {lease}: elected generation "
                        f"{tr} after {prev} (transitions regressed)"
                    )
                last_transitions[lease] = tr
            open_epochs[ev.actor] = True
        elif ev.action == "deposed":
            open_epochs[ev.actor] = False
        elif ev.action in _WRITE_ACTIONS:
            # gated_writers maps a write actor ("kcm-0", "kwok-0/pod")
            # to its replica ("kcm-0", "kwok-0"); epochs are per replica
            replica = record.gated_writers.get(ev.actor)
            if replica is None:
                continue  # not a seat-gated writer (scenario, elector)
            if ev.detail.startswith("Lease "):
                continue  # election traffic is its own fence
            if not open_epochs.get(replica):
                out.append(
                    f"t={ev.t:.3f} {ev.actor} wrote outside its "
                    f"leadership epoch: {ev.action} {ev.detail}"
                )
    return out


def check_no_lost_writes(record) -> List[str]:
    out: List[str] = []
    for i, probe in enumerate(record.crash_checks):
        if probe["recovered_rv"] < probe["acked_rv"]:
            out.append(
                f"crash #{i}: recovery rolled back to rv "
                f"{probe['recovered_rv']} below acked rv {probe['acked_rv']}"
            )
    if record.replay_matches is False:
        out.append(
            "final WAL replay diverged from live state "
            f"({record.replay_detail})"
        )
    return out


_POD_RE = re.compile(
    r"^Pod (?P<key>\S+)(?: owner=(?P<okind>\w+):(?P<oname>\S+))?$"
)
_RS_RE = re.compile(r"^ReplicaSet (?P<key>\S+) replicas=(?P<n>\d+)$")


def check_no_duplicate_reconcile(record) -> List[str]:
    """A ReplicaSet's controller creating past its current
    spec.replicas is the classic two-active-reconcilers symptom."""
    out: List[str] = []
    target: Dict[str, int] = {}
    live: Dict[str, set] = {}
    pod_owner: Dict[str, str] = {}
    for ev in record.trace.events:
        if ev.action in ("crash", "disk-recovered"):
            # crash: the crashed operation committed durably but its
            # completion (and trace line) was lost — the one legal
            # applied-but-untraced window.  disk-recovered: a lossy
            # recovery rolled objects back without DELETED traces.
            # Either way, re-derive from scratch: stale knowledge here
            # would be a false positive, and an undercount only
            # weakens detection, never fabricates a violation.
            target.clear()
            live.clear()
            pod_owner.clear()
            continue
        if ev.action in ("create", "patch", "update"):
            m = _RS_RE.match(ev.detail)
            if m:
                target[m.group("key")] = int(m.group("n"))
                continue
        m = _POD_RE.match(ev.detail) if ev.detail.startswith("Pod ") else None
        if m is None:
            continue
        key = m.group("key")
        if ev.action == "create" and m.group("okind") == "ReplicaSet":
            ns = key.rsplit("/", 1)[0]
            rs_key = f"{ns}/{m.group('oname')}"
            bucket = live.setdefault(rs_key, set())
            bucket.add(key)
            pod_owner[key] = rs_key
            want = target.get(rs_key)
            if want is not None and len(bucket) > want:
                out.append(
                    f"t={ev.t:.3f} {ev.actor} over-created for "
                    f"{rs_key}: {len(bucket)} live > replicas={want}"
                )
        elif ev.action == "delete":
            rs_key = pod_owner.pop(key, None)
            if rs_key is not None:
                live.get(rs_key, set()).discard(key)
    return out


def check_watch_rv_monotonic(record) -> List[str]:
    """Watch-stream rv ordering, matched to the store shape: a single
    store delivers a strict global order per stream; a sharded store's
    merged watch promises PER-OBJECT rv ordering only (two objects on
    different shards may interleave either way —
    kwok_tpu/cluster/sharding/fanin.py).  Entries are ``(key, rv)``
    tuples from the observer; bare ints (synthetic traces) check as
    key-less, i.e. globally."""
    out: List[str] = []
    sharded = getattr(record, "store_shards", 1) > 1
    for i, stream in enumerate(record.streams):
        prev_global = None
        prev_by_key: Dict[str, int] = {}
        for item in stream:
            key, rv = item if isinstance(item, tuple) else (None, item)
            if key is not None:
                last = prev_by_key.get(key)
                if last is not None and rv <= last:
                    out.append(
                        f"stream #{i}: {key} rv {rv} after {last} "
                        "(per-object order violated)"
                    )
                    break
                prev_by_key[key] = rv
            if not sharded or key is None:
                if prev_global is not None and rv <= prev_global:
                    out.append(
                        f"stream #{i}: rv {rv} after {prev_global} "
                        "(not strictly increasing)"
                    )
                    break
                prev_global = rv
    return out


def check_convergence(record) -> List[str]:
    if not record.converged:
        return [f"run did not converge: {record.convergence_detail}"]
    return []


def check_recovery_honesty(record) -> List[str]:
    """Disk-fault recoveries must be *detected* and *honest*: the
    recovered state plus the reported-lost set together account for
    every acked resourceVersion (``RunRecord.disk_checks`` probes,
    evaluated at fault time against the storage-integrity layer's
    RecoveryReport — ``kwok_tpu/cluster/store.py:2024``).

    The void-accounting side of the same contract: every rv the
    shared sequence allocated must be durable in the WAL union or
    covered by a ``void`` marker (``ResourceStore._unbump``) — a
    rolled-back write that skips both leaks a hole recovery/fsck can
    only read as a lost record.  Audited at each pressure-window end
    (``RunRecord.exhaustion_checks``), excused when a batch-lane
    refusal (rvs legitimately committed in memory, not yet durable) or
    earlier disk damage (corrupt records legitimately unreadable)
    explains the hole."""
    out: List[str] = []
    for i, probe in enumerate(record.disk_checks):
        if probe["silent_lost"]:
            out.append(
                f"disk fault #{i} ({probe['mode']}): acked rvs "
                f"{probe['silent_lost'][:5]} lost WITHOUT being reported"
            )
        if (
            not probe.get("noop")
            and not probe["corruptions"]
            and not probe["torn_tail"]
        ):
            out.append(
                f"disk fault #{i} ({probe['mode']}): injected corruption "
                "was silently absorbed (no detection signal)"
            )
    for i, probe in enumerate(
        getattr(record, "exhaustion_checks", []) or []
    ):
        holes = probe.get("unaccounted_rvs") or []
        if (
            holes
            and not probe.get("batch_rejections", 0)
            and not probe.get("prior_damage", 0)
        ):
            out.append(
                f"pressure window #{i} ({probe.get('mode')}): allocated "
                f"rvs {holes[:5]} are neither durable in the WAL union "
                "nor voided — continuity hole with no damage to explain "
                "it"
            )
    return out


def check_exhaustion_honesty(record) -> List[str]:
    """Storage-exhaustion windows must degrade *honestly*: every rv
    acked while the disk refused writes is durable in the log
    (reserve-powered) — anything not durable must have been a visible
    rejection, never a silent ack — and writes must re-arm the moment
    the window closes (``RunRecord.exhaustion_checks`` probes, taken at
    each window's end against the live WAL —
    ``kwok_tpu/chaos/fs_pressure.py:1``)."""
    out: List[str] = []
    for i, probe in enumerate(record.exhaustion_checks):
        if probe["silent_lost"]:
            out.append(
                f"pressure window #{i} ({probe['mode']}): acked rvs "
                f"{probe['silent_lost'][:5]} were never made durable "
                "and never rejected"
            )
        if not probe["rearmed"]:
            out.append(
                f"pressure window #{i} ({probe['mode']}): writes did "
                "not re-arm after the window closed"
            )
    return out


def check_gang_atomicity(record) -> List[str]:
    """No store state surviving a crash/failover window — recovered,
    final, or WAL-replayed — may show a bound STRICT SUBSET of a gang:
    the all-or-nothing contract of the atomic bind lane
    (``kwok_tpu/sched/engine.py:1`` commits every gang through
    ``ResourceStore.transact``, one CRC-framed WAL record).  Probes
    are taken by the harness at every recovery and at end of run
    (``RunRecord.gang_checks``)."""
    out: List[str] = []
    for i, probe in enumerate(record.gang_checks):
        bound, present = probe["bound"], probe["present"]
        if 0 < bound < present:
            out.append(
                f"probe #{i} ({probe['at']}, t={probe['t']}): gang "
                f"{probe['gang']} has {bound}/{present} members bound "
                "— a strict subset survived"
            )
    return out


#: fleet writers name their objects ``{tenant}-cm-{seq}`` so ownership
#: is derivable from the name alone, even off a raw (leaked) stream
_TENANT_CM_RE = re.compile(r"^(?P<owner>t\d+)-cm-\d+$")


def check_tenant_isolation(record) -> List[str]:
    """The fleet's enforced-isolation contract
    (``kwok_tpu/fleet/tenant.py``), three probes per run:

    - **streams**: no tenant's scoped watch stream may deliver an
      object owned by a DIFFERENT tenant
      (``RunRecord.tenant_streams`` — the TenantStore/TenantWatcher
      prefix scoping, audited from the consumer side);
    - **flow**: flooding one tenant's APF level to rejection must
      leave a neighbor tenant and the system level admitting
      (``RunRecord.tenant_flow_checks`` — the per-tenant-level seat
      floors of ``kwok_tpu/fleet/flow.py``), and the flood itself
      must have been rejected at least once or the probe is vacuous;
    - **region moves**: a tenant whose clients rode a region-transfer
      window must resume writes after it — disruption is bounded to
      the window (``RunRecord.tenant_region_checks``)."""
    out: List[str] = []
    for tid in sorted(getattr(record, "tenant_streams", {}) or {}):
        for name in record.tenant_streams[tid]:
            m = _TENANT_CM_RE.match(name)
            if m and m.group("owner") != tid:
                out.append(
                    f"tenant {tid} observed {name!r} (owned by "
                    f"{m.group('owner')}) — cross-tenant watch leak"
                )
                break
    for i, probe in enumerate(getattr(record, "tenant_flow_checks", []) or []):
        if probe.get("flood_rejections", 0) <= 0:
            out.append(
                f"flow probe #{i}: flood against {probe.get('flooded')} "
                "was never rejected (probe vacuous — level unbounded?)"
            )
        if not probe.get("victim_ok", True):
            out.append(
                f"flow probe #{i}: flooding {probe.get('flooded')} "
                f"starved neighbor tenant {probe.get('victim')}"
            )
        if not probe.get("system_ok", True):
            out.append(
                f"flow probe #{i}: flooding {probe.get('flooded')} "
                "starved the system level"
            )
    for i, chk in enumerate(
        getattr(record, "tenant_region_checks", []) or []
    ):
        if not chk.get("resumed", False):
            out.append(
                f"region move #{i}: tenant {chk.get('tenant')} never "
                "resumed writes after the transfer window "
                f"(t={chk.get('t')} dur={chk.get('duration')})"
            )
    return out


def check_trace_complete(record) -> List[str]:
    if record.audit_overflow:
        return [
            f"audit ring overflowed {record.audit_overflow} entries — "
            "trace-level checks ran over a truncated window"
        ]
    return []


INVARIANTS: Dict[str, Callable] = {
    "single-reconciler": check_single_reconciler,
    "no-lost-writes": check_no_lost_writes,
    "no-duplicate-reconcile": check_no_duplicate_reconcile,
    "watch-rv-monotonic": check_watch_rv_monotonic,
    "convergence": check_convergence,
    "trace-complete": check_trace_complete,
    "recovery-honesty": check_recovery_honesty,
    "exhaustion-honesty": check_exhaustion_honesty,
    "gang-atomicity": check_gang_atomicity,
    "tenant-isolation": check_tenant_isolation,
}


def run_checks(record, names=None) -> Dict[str, List[str]]:
    """Run the selected invariant checkers (all by default); returns
    only the ones that found violations."""
    selected = INVARIANTS if names is None else {
        n: INVARIANTS[n] for n in names
    }
    results: Dict[str, List[str]] = {}
    for name, fn in selected.items():
        found = fn(record)
        if found:
            results[name] = found
    return results
