"""Simulation actors: the control-plane components driven synchronously.

Each actor wraps one real component through its synchronous seams — the
same state machines the daemons run on threads, stepped by the
simulation scheduler instead:

- electors step ``renew_once``/``try_acquire_or_renew``
  (``kwok_tpu/cluster/election.py:335``, the fake-clock drive mode its
  docstring names);
- the kcm seat drives ``GCController.handle_event``/``sync_once`` and
  ``WorkloadManager.map_event``/``resync_once``/``drain_queue``
  (``kwok_tpu/controllers/gc_controller.py:99``,
  ``kwok_tpu/workloads/manager.py:103``), composed via the daemon's own
  factory (``kwok_tpu/cmd/kcm.py:91``);
- the scheduler seat drives ``Scheduler.handle_event`` and
  ``_retry_pending`` (``kwok_tpu/controllers/scheduler.py:79``);
- the kwok seat replays the stage hot loop — select → delay → play —
  against the compiled Lifecycle, mirroring
  ``kwok_tpu/controllers/base.py:41`` StagePlayer without its queue
  threads;
- watch pumps replay the informer reflector contract
  (``kwok_tpu/cluster/informer.py:133``): list-then-watch, resume at
  the last delivered resourceVersion, full re-list (with synthesized
  DELETEDs) on ``Expired``.
"""

from __future__ import annotations

import datetime
import random
from typing import Callable, Dict, List, Optional, Tuple

from kwok_tpu.cluster.election import LeaderElector
from kwok_tpu.cluster.informer import InformerEvent
from kwok_tpu.cluster.store import (
    ADDED,
    DELETED,
    MODIFIED,
    Conflict,
    EventRecorder,
    Expired,
    NotFound,
)
from kwok_tpu.controllers.utils import should_retry
from kwok_tpu.dst.faults import ActorStore
from kwok_tpu.engine.lifecycle import Lifecycle, to_json_standard
from kwok_tpu.utils.backoff import Backoff
from kwok_tpu.utils.patch import is_noop_patch

__all__ = [
    "Actor",
    "Replica",
    "WatchPump",
    "ElectorActor",
    "KcmActor",
    "SchedulerActor",
    "LifecycleActor",
    "ObserverActor",
    "FleetWriterActor",
    "TenantObserverActor",
]

#: kinds the GC seat pumps (the interesting owner graph; the daemon
#: watches every registered kind, which at sim scale is just overhead)
GC_KINDS = ("Namespace", "Deployment", "ReplicaSet", "Job", "Pod")

#: kinds the workload manager pumps (workloads/manager.py _WATCHED)
WORKLOAD_KINDS = ("Deployment", "ReplicaSet", "Job", "HorizontalPodAutoscaler", "Pod")


class Actor:
    """One schedulable unit: a step function with a jittered cadence."""

    def __init__(self, sim, name: str, replica: Optional["Replica"], period: float):
        self.sim = sim
        self.name = name
        self.replica = replica
        self.period = period
        self.next_due = sim.clock.now()

    def runnable(self) -> bool:
        r = self.replica
        return r is None or (r.alive and not r.paused)

    def schedule_next(self) -> None:
        jitter = 1.0 + 0.2 * self.sim.rng.random()
        self.next_due = self.sim.clock.now() + self.period * jitter

    def step(self) -> None:
        raise NotImplementedError


class Replica:
    """One simulated control-plane process: a seat's elector plus the
    controllers gated on it (the run_elected composition,
    kwok_tpu/cmd/kcm.py:110)."""

    def __init__(self, sim, seat: str, lease_name: str, idx: int, lease_duration: float):
        self.sim = sim
        self.seat = seat
        self.lease_name = lease_name
        self.name = f"{seat}-{idx}"
        self.lease_duration = lease_duration
        self.alive = True
        self.paused = False
        self.leading = False
        self.elector: Optional[LeaderElector] = None
        self.build_elector()

    def build_elector(self) -> None:
        sim = self.sim
        store = ActorStore(sim, f"{self.name}/elector", f"system:{self.name}")

        def on_started() -> None:
            self.leading = True
            sim.trace.add(
                sim.clock.now(),
                self.name,
                "elected",
                f"{self.lease_name} transitions={self.elector.transitions}",
            )

        def on_stopped() -> None:
            self.leading = False
            sim.trace.add(
                sim.clock.now(), self.name, "deposed", self.lease_name
            )

        self.elector = LeaderElector(
            store,
            self.lease_name,
            self.name,
            lease_duration=self.lease_duration,
            clock=sim.clock,
            rng=random.Random(sim.rng.randrange(2**31)),
            record_clock=sim.clock,
            on_started_leading=on_started,
            on_stopped_leading=on_stopped,
        )

    def fence(self) -> Optional[str]:
        return self.elector.fence() if self.elector is not None else None

    def is_leader(self) -> bool:
        return (
            self.alive
            and not self.paused
            and self.elector is not None
            and self.elector.is_leader()
        )

    def kill(self) -> None:
        """Silent death (SIGKILL analog): no release, the lease must
        expire before a standby takes over."""
        self.alive = False
        self.leading = False

    def revive(self) -> None:
        """Process restart: a fresh elector campaigns from scratch."""
        self.alive = True
        self.paused = False
        self.build_elector()


class WatchPump:
    """Synchronous list+watch mirror of the informer reflector
    (cluster/informer.py:133): resume-at-rv across reconnects, full
    re-list with synthesized DELETEDs on Expired, frozen while the
    owner is partitioned.  Single consumer; `drain` returns the events
    since the last call."""

    def __init__(self, sim, kind: str, client_id: str):
        self.sim = sim
        self.kind = kind
        self.client_id = client_id
        self._mirror: Dict[Tuple[str, str], dict] = {}
        self._w = None
        self._rv: Optional[int] = None
        self._gen: Optional[int] = None

    def reset(self) -> None:
        if self._w is not None:
            self._w.stop()
        self._w = None
        self._rv = None
        self._gen = None
        self._mirror.clear()

    @staticmethod
    def _key(obj: dict) -> Tuple[str, str]:
        meta = obj.get("metadata") or {}
        return (meta.get("namespace") or "", meta.get("name") or "")

    def _relist(self, out: List[InformerEvent]) -> None:
        store = self.sim.store
        items, rv = store.list(self.kind)
        fresh = {self._key(o): o for o in items}
        for key, old in list(self._mirror.items()):
            if key not in fresh:
                del self._mirror[key]
                out.append(InformerEvent(DELETED, old))
        for key, obj in fresh.items():
            prev = self._mirror.get(key)
            if prev is not None and (prev.get("metadata") or {}).get(
                "resourceVersion"
            ) == (obj.get("metadata") or {}).get("resourceVersion"):
                continue
            self._mirror[key] = obj
            out.append(InformerEvent(ADDED if prev is None else MODIFIED, obj))
        self._rv = rv

    def _attach(self, out: List[InformerEvent]) -> None:
        sim = self.sim
        self._gen = sim.store_generation
        if self._rv is not None:
            try:
                self._w = sim.store.watch(self.kind, since_rv=self._rv)
                return
            except Expired:
                self._w = None  # gap or rollback: heal via re-list
            except NotFound:
                self._w = None
                return
        self._relist(out)
        try:
            self._w = sim.store.watch(self.kind, since_rv=self._rv)
        except Expired:
            self._w = None

    def drain(self) -> List[InformerEvent]:
        sim = self.sim
        if sim.faults.partitioned(self.client_id, sim.clock.now()):
            return []  # the stream is dark; events buffer server-side
        out: List[InformerEvent] = []
        if (
            self._gen != sim.store_generation
            or self._w is None
            or self._w.stopped
        ):
            self._attach(out)
        if self._w is not None:
            for ev in self._w.drain():
                rv = getattr(ev, "rv", 0) or 0
                if self._rv is None or rv > self._rv:
                    self._rv = rv
                obj = ev.object
                if ev.type == DELETED:
                    self._mirror.pop(self._key(obj), None)
                else:
                    self._mirror[self._key(obj)] = obj
                out.append(InformerEvent(ev.type, obj))
        return out


class ElectorActor(Actor):
    """Steps one replica's election state machine at its retry
    cadence (the elector `_run` loop body)."""

    def __init__(self, sim, replica: Replica):
        period = replica.lease_duration / 3.0
        super().__init__(sim, f"{replica.name}/elector", replica, period)

    def step(self) -> None:
        el = self.replica.elector
        if el is None:
            return
        if el.is_leader():
            el.renew_once()
        else:
            el.try_acquire_or_renew()


class _GatedControllerActor(Actor):
    """Shared leader-gating shell: build the component set on
    acquisition, tear it down (fresh state) on deposition — the
    start_controllers/stop_controllers shape of the daemons."""

    def __init__(self, sim, name, replica, period):
        super().__init__(sim, name, replica, period)
        self._built = False

    def _build(self) -> None:
        raise NotImplementedError

    def _teardown(self) -> None:
        raise NotImplementedError

    def _leader_ok(self) -> bool:
        return self.replica.is_leader()

    def step(self) -> None:
        if not self._leader_ok():
            if self._built:
                self._teardown()
                self._built = False
            return
        if not self._built:
            self._build()
            self._built = True
        self._step_leading()

    def _step_leading(self) -> None:
        raise NotImplementedError


class KcmActor(_GatedControllerActor):
    """The kube-controller-manager seat: gc + workloads, composed via
    the daemon's own factory (cmd/kcm.py build_controller_groups)."""

    RESYNC_S = 2.0

    def __init__(self, sim, replica: Replica, ungated: bool = False):
        super().__init__(sim, replica.name, replica, period=0.8)
        #: deliberate test-only regression ("ungated-writer"): this
        #: replica reconciles even while NOT holding the lease — the
        #: bug class the single-reconciler invariant exists to catch
        self.ungated = ungated
        self.gc = None
        self.mgr = None
        self._gc_pumps: List[WatchPump] = []
        self._wl_pumps: List[WatchPump] = []
        self._next_resync = 0.0

    def _leader_ok(self) -> bool:
        if self.ungated:
            return self.replica.alive and not self.replica.paused
        return super()._leader_ok()

    def _build(self) -> None:
        from kwok_tpu.cmd.kcm import build_controller_groups

        sim = self.sim
        r = self.replica
        store = ActorStore(
            sim, r.name, f"controller:{r.name}", fence_provider=r.fence
        )
        active = None if self.ungated else r.is_leader
        recorder = EventRecorder(
            store, source=r.seat, clock=sim.clock, suffix=sim.next_suffix
        )
        self.gc, self.mgr = build_controller_groups(
            store,
            ("gc", "workloads"),
            active=active,
            clock=sim.clock,
            recorder=recorder,
        )
        cid = f"controller:{r.name}"
        self._gc_pumps = [WatchPump(sim, k, cid) for k in GC_KINDS]
        self._wl_pumps = [WatchPump(sim, k, cid) for k in WORKLOAD_KINDS]
        self._next_resync = sim.clock.now()

    def _teardown(self) -> None:
        for p in self._gc_pumps + self._wl_pumps:
            p.reset()
        self.gc = None
        self.mgr = None

    def _step_leading(self) -> None:
        sim = self.sim
        for pump in self._gc_pumps:
            for ev in pump.drain():
                try:
                    self.gc.handle_event(ev)
                except Exception:  # noqa: BLE001 — partition mid-index
                    pass
        for pump in self._wl_pumps:
            for ev in pump.drain():
                try:
                    self.mgr.map_event(ev.object)
                except Exception:  # noqa: BLE001
                    pass
        now = sim.clock.now()
        if now >= self._next_resync:
            self._next_resync = now + self.RESYNC_S
            self.mgr.resync_once()
            try:
                self.gc.sync_once()
            except Exception:  # noqa: BLE001 — partition mid-sweep
                pass
        self.mgr.drain_queue()


class SchedulerActor(_GatedControllerActor):
    """The scheduler seat (cmd/scheduler.py build_scheduler), fed by
    node/pod pumps instead of informer threads."""

    RETRY_S = 2.0

    def __init__(self, sim, replica: Replica):
        super().__init__(sim, replica.name, replica, period=0.7)
        self.sched = None
        self._node_pump: Optional[WatchPump] = None
        self._pod_pump: Optional[WatchPump] = None
        self._next_retry = 0.0

    def _build(self) -> None:
        from kwok_tpu.cmd.scheduler import build_scheduler

        sim = self.sim
        r = self.replica
        store = ActorStore(
            sim, r.name, f"controller:{r.name}", fence_provider=r.fence
        )
        recorder = EventRecorder(
            store, source=r.seat, clock=sim.clock, suffix=sim.next_suffix
        )
        self.sched = build_scheduler(
            store,
            active=r.is_leader,
            recorder=recorder,
            clock=sim.clock,
            slice_hosts=sim.opts.gang_slice_hosts,
        )
        if self.sched.gang is not None and sim.opts.bug == "partial-gang":
            # test-only injected regression: binds go as individual
            # patches instead of one atomic txn, re-opening the
            # partial-gang crash window the gang-atomicity invariant
            # exists to catch
            self.sched.gang.atomic = False
        cid = f"controller:{r.name}"
        self._node_pump = WatchPump(sim, "Node", cid)
        self._pod_pump = WatchPump(sim, "Pod", cid)
        self._next_retry = sim.clock.now()

    def _teardown(self) -> None:
        for p in (self._node_pump, self._pod_pump):
            if p is not None:
                p.reset()
        self.sched = None

    def _step_leading(self) -> None:
        sim = self.sim
        sched = self.sched
        for ev in self._node_pump.drain():
            # the informer thread would maintain the node cache; the
            # pump stands in for it (same CacheGetter contract)
            sched._nodes._apply(ev.type, ev.object)
            self._safe_handle(ev)
        for ev in self._pod_pump.drain():
            self._safe_handle(ev)
        now = sim.clock.now()
        if now >= self._next_retry:
            self._next_retry = now + self.RETRY_S
            try:
                sched._retry_pending()
            except Exception:  # noqa: BLE001 — partitioned mid-list
                pass

    def _safe_handle(self, ev) -> None:
        try:
            self.sched.handle_event(ev)
        except Exception:  # noqa: BLE001 — a failed bind logs + retries
            pass


class _StageJob:
    __slots__ = ("obj", "rv", "stage", "due", "retries", "ctx")

    def __init__(self, obj, rv, stage, due, retries=0, ctx=None):
        self.obj = obj
        self.rv = rv
        self.stage = stage
        self.due = due
        self.retries = retries
        #: causing write's span context (watch-boundary stitch); None
        #: under the DST's usual tracer-off posture
        self.ctx = ctx


class LifecycleActor(_GatedControllerActor):
    """The kwok-controller seat for one kind: the stage hot loop
    (select → delay → play, controllers/base.py:150 preprocess and
    :220 play_stage) with the delay queue virtualized into a due-time
    map keyed like delayQueueMapping."""

    def __init__(
        self,
        sim,
        replica: Replica,
        kind: str,
        stages,
        funcs_for: Optional[Callable[[dict], Dict[str, Callable]]] = None,
        on_delete: Optional[Callable[[dict], None]] = None,
    ):
        super().__init__(sim, f"{replica.name}/{kind.lower()}", replica, period=0.4)
        self.kind = kind
        self.lc = Lifecycle(stages)
        self.funcs_for = funcs_for or (lambda obj: {})
        self.on_delete = on_delete
        self.rng = random.Random(sim.rng.randrange(2**31))
        self.backoff = Backoff(duration=0.5, cap=8.0)
        self.transitions = 0
        self.store = None
        self.recorder = None
        self._pump: Optional[WatchPump] = None
        self._jobs: Dict[str, _StageJob] = {}

    def _build(self) -> None:
        sim = self.sim
        r = self.replica
        self.store = ActorStore(
            sim, self.name, f"controller:{r.name}", fence_provider=r.fence
        )
        self.recorder = EventRecorder(
            self.store, source=r.seat, clock=sim.clock, suffix=sim.next_suffix
        )
        self._pump = WatchPump(sim, self.kind, f"controller:{r.name}")

    def _teardown(self) -> None:
        if self._pump is not None:
            self._pump.reset()
        self._jobs.clear()
        self.store = None
        self.recorder = None

    # ------------------------------------------------------------ hot loop

    @staticmethod
    def _key(obj: dict) -> str:
        meta = obj.get("metadata") or {}
        return f"{meta.get('namespace', '')}/{meta.get('name', '')}"

    def _now_dt(self) -> datetime.datetime:
        return datetime.datetime.fromtimestamp(
            self.sim.clock.now(), datetime.timezone.utc
        )

    def _now_func(self) -> str:
        return (
            self._now_dt()
            .isoformat(timespec="microseconds")
            .replace("+00:00", "Z")
        )

    def _preprocess(self, obj: dict, ctx=None) -> None:
        key = self._key(obj)
        meta = obj.get("metadata") or {}
        rv = meta.get("resourceVersion")
        cur = self._jobs.get(key)
        if cur is not None and cur.rv == rv:
            return
        data = to_json_standard(obj)
        stage = self.lc.select(
            meta.get("labels") or {},
            meta.get("annotations") or {},
            data,
            rng=self.rng,
        )
        if stage is None:
            self._jobs.pop(key, None)
            return
        delay, _ = stage.delay(data, self._now_dt(), rng=self.rng)
        self._jobs[key] = _StageJob(
            obj, rv, stage, self.sim.clock.now() + delay, ctx=ctx
        )

    def _step_leading(self) -> None:
        now = self.sim.clock.now()
        for ev in self._pump.drain():
            if ev.type == DELETED:
                self._jobs.pop(self._key(ev.object), None)
                if self.on_delete is not None:
                    self.on_delete(ev.object)
                continue
            self._preprocess(ev.object, ctx=getattr(ev, "ctx", None))
        # due jobs, in deterministic key order
        due = sorted(
            (key for key, job in self._jobs.items() if job.due <= now)
        )
        for key in due:
            job = self._jobs.pop(key, None)
            if job is None:
                continue
            try:
                need_retry = self._play(job.obj, job.stage, ctx=job.ctx)
            except Exception:  # noqa: BLE001 — partition/shed mid-play
                need_retry = True
            if need_retry and key not in self._jobs:
                job.retries += 1
                job.due = now + self.backoff.delay(job.retries, self.rng)
                self._jobs[key] = job

    def _play(self, obj: dict, stage, ctx=None) -> bool:
        """One stage application (StagePlayer._play_stage_inner,
        controllers/base.py:234, minus the thread plumbing).  With a
        tracer armed (the digest-neutrality test's posture) the play
        opens the same linked reconcile span the production StagePlayer
        does — spans are side-channel only, so seeds stay byte-identical
        armed vs disarmed."""
        from kwok_tpu.utils.trace import get_tracer

        tracer = get_tracer()
        if tracer.enabled:
            tid, pid = ctx if ctx else (None, None)
            with tracer.span(f"play.{self.kind}", trace_id=tid, parent_id=pid) as sp:
                if ctx:
                    sp.add_link(*ctx)
                sp.set("stage", getattr(stage, "name", ""))
                return self._play_inner(obj, stage)
        return self._play_inner(obj, stage)

    def _play_inner(self, obj: dict, stage) -> bool:
        effects = self.lc.effects(stage)
        if effects is None:
            return False
        meta = obj.get("metadata") or {}
        name = meta.get("name") or ""
        ns = meta.get("namespace")
        result: Optional[dict] = None

        if effects.event is not None and self.recorder is not None:
            ev = effects.event
            self.recorder.event(
                obj, ev.type or "Normal", ev.reason, ev.message
            )

        fin = effects.finalizers_patch(meta.get("finalizers") or [])
        if fin is not None:
            try:
                result = self.store.patch(
                    self.kind, name, fin.data, fin.type, namespace=ns
                )
            except NotFound:
                return False
            except Exception as e:  # noqa: BLE001
                return should_retry(e)

        if effects.delete:
            try:
                self.store.delete(self.kind, name, namespace=ns)
            except NotFound:
                pass
            except Exception as e:  # noqa: BLE001
                return should_retry(e)
            result = None
        else:
            funcs = dict(self.funcs_for(obj))
            funcs.setdefault("Now", self._now_func)
            base = result if result is not None else obj
            for patch in effects.patches(base, funcs):
                if is_noop_patch(base, patch.data, patch.type):
                    continue
                try:
                    result = self.store.patch(
                        self.kind,
                        name,
                        patch.data,
                        patch.type,
                        namespace=ns,
                        subresource=patch.subresource,
                        as_user=patch.impersonation,
                    )
                    base = result
                except NotFound:
                    return False
                except Exception as e:  # noqa: BLE001
                    return should_retry(e)

        self.transitions += 1
        if result is not None and stage.immediate_next_stage:
            self._preprocess(result)
        return False


class FleetWriterActor(Actor):
    """One fleet tenant's client workload: periodic ConfigMap creates
    through the tenant's scoped store view (``kwok_tpu/fleet/tenant.py``
    TenantStore over the actor/network boundary), the simulated form of
    a virtual control plane's traffic.  Object names carry the owning
    tenant (``{tid}-cm-{seq}``) so the tenant-isolation invariant can
    attribute anything that surfaces in a NEIGHBOR's stream.  Not
    leader-gated: tenants are clients, like the scenario operator."""

    def __init__(self, sim, tenant: str):
        super().__init__(sim, f"fleet/{tenant}", None, period=1.1)
        from kwok_tpu.fleet.tenant import TenantStore

        self.tenant = tenant
        self.store = TenantStore(
            ActorStore(sim, f"fleet/{tenant}", f"tenant:{tenant}"), tenant
        )
        self.seq = 0
        #: last virtual instant a write round-tripped (the region-move
        #: probe asserts this advances past every transfer window)
        self.last_ok_t = -1.0
        self._bootstrapped = False

    def step(self) -> None:
        if not self._bootstrapped:
            # the cold-start bootstrap the live FleetRegistry performs:
            # the tenant's default namespace, through the scoped view
            try:
                self.store.create(
                    {
                        "apiVersion": "v1",
                        "kind": "Namespace",
                        "metadata": {"name": "default"},
                    }
                )
            except Conflict:
                pass
            self._bootstrapped = True
            self.last_ok_t = self.sim.clock.now()
            return
        try:
            self.store.create(
                {
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {
                        "name": f"{self.tenant}-cm-{self.seq}",
                        "namespace": "default",
                    },
                    "data": {"seq": str(self.seq)},
                }
            )
        except Conflict:
            # an earlier eaten ack applied this seq; the write IS
            # durable — advance past it
            pass
        self.seq += 1
        self.last_ok_t = self.sim.clock.now()


class TenantObserverActor(Actor):
    """Per-tenant passive watch consumer: the tenant's own informer,
    recording every ConfigMap name its scoped stream delivers
    (``RunRecord.tenant_streams``).  The tenant-isolation invariant
    asserts no recorded name belongs to another tenant.  ``leaky``
    (the ``--dst-bug tenant-leak`` regression) subscribes to the RAW
    store instead of the TenantStore view — the unscoped-watch bug
    class the invariant exists to catch."""

    def __init__(self, sim, tenant: str, leaky: bool = False):
        super().__init__(sim, f"fleet-observer/{tenant}", None, period=0.6)
        self.tenant = tenant
        self.leaky = leaky
        self.names: List[str] = []
        self._w = None
        self._gen: Optional[int] = None
        self._rv: Optional[int] = None

    def _scoped_store(self):
        if self.leaky:
            return self.sim.store
        from kwok_tpu.fleet.tenant import TenantStore

        return TenantStore(self.sim.store, self.tenant)

    def step(self) -> None:
        sim = self.sim
        if (
            self._gen != sim.store_generation
            or self._w is None
            or getattr(self._w, "stopped", False)
        ):
            self._gen = sim.store_generation
            if self._w is not None:
                self._w.stop()
            self._w = None
            store = self._scoped_store()
            if self._rv is not None:
                try:
                    self._w = store.watch("ConfigMap", since_rv=self._rv)
                except Expired:
                    self._w = None  # rollback: heal via re-list
            if self._w is None:
                _items, rv = store.list("ConfigMap")
                self._rv = rv
                self._w = store.watch("ConfigMap", since_rv=rv)
        for ev in self._w.drain():
            rv = getattr(ev, "rv", 0) or 0
            if self._rv is None or rv > self._rv:
                self._rv = rv
            meta = (getattr(ev, "object", None) or {}).get("metadata") or {}
            name = str(meta.get("name") or "")
            if name:
                self.names.append(name)


class ObserverActor(Actor):
    """Passive watch consumer recording per-stream
    ``(object key, resourceVersion)`` sequences for the
    rv-monotonicity invariant; reconnects across crashes like any
    reflector.  A successful resume-at-rv CONTINUES the same logical
    stream — the reflector's cache survives a reconnect, so a resume
    that replays already-delivered events is a real duplicate the
    checker must see, not a fresh start that hides it.  Only a re-list
    (Expired — a rollback legitimately restarts the world) opens a new
    stream.  The key is recorded because a sharded store's merged
    watch promises PER-OBJECT rv ordering, not a global total order
    (kwok_tpu/cluster/sharding/fanin.py) — the checker asserts the
    contract that matches the store shape."""

    def __init__(self, sim, kind: str = "Pod"):
        super().__init__(sim, "observer", None, period=0.5)
        self.kind = kind
        self.streams: List[List[tuple]] = []
        self._w = None
        self._gen: Optional[int] = None
        self._rv: Optional[int] = None

    def step(self) -> None:
        sim = self.sim
        if self._gen != sim.store_generation or self._w is None or self._w.stopped:
            self._gen = sim.store_generation
            self._w = None
            resumed = False
            if self._rv is not None:
                try:
                    self._w = sim.store.watch(self.kind, since_rv=self._rv)
                    resumed = True
                except Expired:
                    self._w = None
            if self._w is None:
                _items, rv = sim.store.list(self.kind)
                self._rv = rv
                self._w = sim.store.watch(self.kind, since_rv=rv)
            if not (resumed and self.streams):
                self.streams.append([])
        for ev in self._w.drain():
            rv = getattr(ev, "rv", 0) or 0
            meta = (getattr(ev, "object", None) or {}).get("metadata") or {}
            key = f"{meta.get('namespace') or ''}/{meta.get('name') or ''}"
            self.streams[-1].append((key, rv))
            if self._rv is None or rv > self._rv:
                self._rv = rv
