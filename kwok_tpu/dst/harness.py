"""Whole-cluster deterministic simulation: one process, virtual time.

Composes the full control plane — WAL-backed store
(``kwok_tpu/cluster/store.py:529``, ``kwok_tpu/cluster/wal.py:67``),
three elected controller seats with hot standbys
(``kwok_tpu/cluster/election.py:91``), the kcm controller groups
(``kwok_tpu/cmd/kcm.py:91``), the scheduler
(``kwok_tpu/cmd/scheduler.py:40``) and the kwok stage machinery
(``kwok_tpu/stages/__init__.py:53`` default stage sets) — onto one
:class:`~kwok_tpu.utils.clock.VirtualClock`, stepped by a seeded
interleaving scheduler that injects the chaos fault vocabulary at
chosen virtual instants (``kwok_tpu/dst/faults.py:1``).  After the
run, Kivi-style invariant checkers replay the trace
(``kwok_tpu/dst/invariants.py:1``).

Everything observable derives from the seed: same seed ⇒ byte-identical
trace (``Trace.digest``), so any violating seed is a reproducible bug
report, not a flake — the ROADMAP.md:101 safety net for the
sharding/fleet refactors.
"""

from __future__ import annotations

import os
import random
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kwok_tpu.cluster.client import ApiUnavailable
from kwok_tpu.cluster.store import (
    Conflict,
    NotFound,
    ResourceStore,
    StorageDegraded,
)
from kwok_tpu.cluster.wal import WriteAheadLog
from kwok_tpu.dst.actors import (
    ElectorActor,
    FleetWriterActor,
    KcmActor,
    LifecycleActor,
    ObserverActor,
    Replica,
    SchedulerActor,
    TenantObserverActor,
)
from kwok_tpu.dst.faults import ActorStore, FaultTimeline, SimCrash
from kwok_tpu.dst.invariants import run_checks
from kwok_tpu.dst.trace import Trace
from kwok_tpu.utils.clock import VirtualClock

__all__ = [
    "SimOptions",
    "RunRecord",
    "Simulation",
    "run_record",
    "run_seed",
    "run_seeds",
    "seeded_timeline",
    "seeded_schedule_spec",
]

#: virtual epoch the simulation starts at (a fixed instant, so every
#: rendered timestamp is seed-stable)
EPOCH = 1_600_000_000.0

#: DST WALs run with NO emergency reserve: a released reserve credits
#: the pressure shim with headroom that absorbs a whole window's
#: writes, so the commit-boundary rollback path (``_unbump`` — where
#: the shared-sequence void accounting lives) would be unreachable in
#: a short virtual window.  Zero reserve is strictly more adversarial:
#: every refused append surfaces at the commit boundary.  The
#: reserve-powered degraded mode keeps its own wall-clock gate
#: (``python -m kwok_tpu.chaos --exhaustion-smoke``)
WAL_RESERVE_BYTES = 0

#: seats: (short name, election lease)
SEATS = (
    ("kwok", "kwok-controller"),
    ("kcm", "kube-controller-manager"),
    ("sched", "kwok-scheduler"),
)


@dataclass
class SimOptions:
    seed: int = 0
    #: virtual seconds of active scenario + faults
    duration: float = 40.0
    #: extra virtual seconds allowed for convergence after the faults
    quiesce: float = 60.0
    #: replicas per seat (leader + standbys)
    replicas: int = 2
    #: election lease duration (virtual seconds)
    lease_duration: float = 6.0
    faults: bool = True
    #: test-only injected regression: "ungated-writer" makes one kcm
    #: standby reconcile without holding the lease; "partial-gang"
    #: un-atomics the gang bind; "cross-shard-txn" makes the shard
    #: router place txn ops per-object and split atomic batches into
    #: per-shard sub-txns (needs store_shards > 1); "tenant-leak"
    #: un-scopes one fleet tenant's watch stream (needs
    #: fleet_tenants > 0); "shard-void-leak" makes a failed sharded
    #: write skip the shared-sequence void accounting — the leaked rv
    #: is a silent hole in the union continuity recovery-honesty
    #: audits (needs store_shards > 1); "fanin-stale-resume" makes the
    #: merged watch fan-in pin a shard that LOOKS idle at the resume
    #: horizon to rv 0, replaying its whole history into a continued
    #: stream — the duplicate delivery watch-rv-monotonic catches
    #: (needs store_shards > 1)
    bug: Optional[str] = None
    #: explicit fault schedule (a ``FaultTimeline.to_spec`` dict) —
    #: the coverage-guided search's injection seam.  None derives the
    #: schedule from the seed as always; a spec replaces the
    #: constructed windows/point-faults verbatim while runtime draws
    #: still come from the seeded rng, so a (seed, schedule) pair is
    #: exactly replayable
    schedule: Optional[dict] = None
    #: store shards (kwok_tpu/cluster/sharding): the default DST run
    #: exercises the sharded composition — per-shard WALs on one
    #: shared rv sequence, recovery through the union continuity
    #: check, the merged watch fan-in under the observer.  1 restores
    #: the single-store composition
    store_shards: int = 2
    nodes: int = 4
    deployment_replicas: int = 6
    scale_to: int = 9
    scale_back: int = 4
    #: gang scheduling scenario: a PodGroup of this many members is
    #: created mid-run (0 disables); members must bind all-or-nothing
    #: through every crash/failover window (gang-atomicity invariant)
    gang_size: int = 3
    #: simulated topology shape for the scenario nodes (hosts/slice)
    gang_slice_hosts: int = 2
    #: fleet tenants co-hosted on the simulated control plane
    #: (kwok_tpu/fleet): each runs a writer + a scoped observer, one
    #: seeded tenant rides a region-move window, and the
    #: tenant-isolation invariant audits the streams + flow probe.
    #: 0 disables the fleet composition entirely
    fleet_tenants: int = 2


def seeded_timeline(opts: SimOptions, fleet_ids: List[str]) -> FaultTimeline:
    """The seed-derived fault schedule — the exact construction
    :class:`Simulation` runs when no explicit spec is given, factored
    out so the coverage-guided search generates fresh corpus entries
    from the same distribution (``seeded_schedule_spec``)."""
    tl = FaultTimeline(
        seed=opts.seed,
        t0=EPOCH + 4.0,
        window_s=max(4.0, opts.duration - 10.0),
        seats=[s for s, _ in SEATS],
        replica_clients=[
            f"{seat}-{i}" for seat, _ in SEATS for i in range(opts.replicas)
        ],
        enable=opts.faults,
    )
    if fleet_ids and opts.faults:
        # one seeded tenant rides a region transfer: its clients go
        # dark for the cutover window (cross-region latency at its
        # limit, on the virtual clock), then must resume — the
        # bounded-disruption probe the tenant-isolation invariant
        # audits
        frng = tl.rng
        moved = fleet_ids[frng.randrange(len(fleet_ids))]
        at = EPOCH + 4.0 + frng.uniform(
            2.0, max(4.0, opts.duration - 10.0) * 0.5
        )
        dur = frng.uniform(2.0, 4.0)
        tl.add_region_move(f"tenant:{moved}", at, dur)
    tl.seal_runtime_rng()
    return tl


def seeded_schedule_spec(seed: int, opts: Optional[SimOptions] = None) -> dict:
    """The seed's fault schedule as a mutable spec (to_spec form) —
    how the search turns a plain seed into a corpus entry."""
    o = SimOptions(
        **{**(opts or SimOptions()).__dict__, "seed": seed, "schedule": None}
    )
    fleet_ids: List[str] = []
    if o.fleet_tenants > 0:
        from kwok_tpu.fleet.tenant import fleet_tenant_ids

        fleet_ids = fleet_tenant_ids(o.fleet_tenants)
    return seeded_timeline(o, fleet_ids).to_spec()


@dataclass
class RunRecord:
    """Everything the invariant checkers see about one finished run."""

    seed: int
    trace: Trace
    streams: List[List[int]] = field(default_factory=list)
    crash_checks: List[dict] = field(default_factory=list)
    #: disk-fault probes: per injected storage corruption, how every
    #: acked rv was accounted for (recovery-honesty invariant)
    disk_checks: List[dict] = field(default_factory=list)
    #: exhaustion probes: per pressure window, every ack inside it
    #: accounted durable-in-log ∪ visibly-rejected, and writes re-armed
    #: at window end (exhaustion-honesty invariant)
    exhaustion_checks: List[dict] = field(default_factory=list)
    #: gang probes: per crash/disk recovery (and at end of run, live +
    #: replayed), how many of each gang's present members were bound —
    #: a bound strict subset surviving a recovery is the atomicity
    #: violation the gang-atomicity invariant flags
    gang_checks: List[dict] = field(default_factory=list)
    #: fleet probes (tenant-isolation invariant): per tenant, every
    #: ConfigMap name its scoped watch stream delivered — a name owned
    #: by a DIFFERENT tenant is the cross-tenant leak
    tenant_streams: Dict[str, List[str]] = field(default_factory=dict)
    #: deterministic APF probes: flooding one tenant's level to
    #: rejection must leave a neighbor tenant and the system level
    #: admitting (the per-tenant-level starvation contract of
    #: kwok_tpu/fleet/flow.py)
    tenant_flow_checks: List[dict] = field(default_factory=list)
    #: region-move probes: per transfer window, did the moved tenant
    #: resume writes after it (bounded disruption)
    tenant_region_checks: List[dict] = field(default_factory=list)
    replay_matches: Optional[bool] = None
    replay_detail: str = ""
    converged: bool = False
    convergence_detail: str = ""
    audit_overflow: int = 0
    #: write-trace actor name -> its replica name (leader-gated actors)
    gated_writers: Dict[str, str] = field(default_factory=dict)
    #: store shards this run composed (the watch-rv checker asserts
    #: per-object ordering for >1, the single store's global order
    #: for 1)
    store_shards: int = 1
    final_counts: Dict[str, int] = field(default_factory=dict)
    steps: int = 0
    virtual_end: float = 0.0


class Simulation:
    """One seeded whole-cluster run on a virtual clock."""

    def __init__(self, opts: SimOptions, wal_dir: str):
        self.opts = opts
        self.clock = VirtualClock(EPOCH)
        self.rng = random.Random(opts.seed)
        self.trace = Trace()
        self.store_generation = 0
        self.max_acked_rv = 0
        #: every rv some actor's mutation was acknowledged at (pruned
        #: to the recovered baseline after a lossy disk recovery —
        #: resourceVersion numbering restarts below the rollback point)
        self.acked_rvs: set = set()
        self.crash_checks: List[dict] = []
        self.disk_checks: List[dict] = []
        self.exhaustion_checks: List[dict] = []
        self.gang_checks: List[dict] = []
        #: live pressure shim (chaos/fs_pressure.py) while a window is
        #: open — reinstalled onto recovered WALs so a crash inside a
        #: window does not silently lift the pressure
        self._active_pressure = None
        self._pressure_probe: Optional[dict] = None
        self._crash_arm: Optional[dict] = None
        self._suffix_n = 0
        self.steps = 0

        # per-run template randomness (sprig rand*/shuffle funcs)
        from kwok_tpu.utils import sprig

        sprig.set_default_rng(random.Random(opts.seed ^ 0x517A1))

        self.n_shards = max(1, int(opts.store_shards))
        if self.n_shards == 1:
            self.wal_paths = [os.path.join(wal_dir, "dst-wal.jsonl")]
            self.wals = [WriteAheadLog(self.wal_paths[0], fsync="off", reserve_bytes=WAL_RESERVE_BYTES)]
            self.store = ResourceStore(clock=self.clock)
            self.store.attach_wal(self.wals[0])
        else:
            # sharded composition: per-shard WALs on one shared rv
            # sequence (kwok_tpu/cluster/sharding) — the default DST
            # shape, so every seed doubles as a split-brain search
            # over the router/fan-in/recovery stack
            from kwok_tpu.cluster.sharding.router import (
                RvSource,
                ShardedStore,
            )

            source = RvSource()
            shards = [
                ResourceStore(
                    clock=self.clock,
                    rv_source=source,
                    uid_start=i,
                    uid_step=self.n_shards,
                )
                for i in range(self.n_shards)
            ]
            self.wal_paths = [
                os.path.join(wal_dir, f"dst-wal-{i}.jsonl")
                for i in range(self.n_shards)
            ]
            self.wals = [
                WriteAheadLog(p, fsync="off", reserve_bytes=WAL_RESERVE_BYTES) for p in self.wal_paths
            ]
            for s, w in zip(shards, self.wals):
                s.attach_wal(w)
            self.store = ShardedStore(shards, source)
            if opts.bug == "cross-shard-txn":
                self.store.unsafe_split_cross_shard_txns = True
            elif opts.bug == "fanin-stale-resume":
                self.store.unsafe_fanin_stale_resume = True
            elif opts.bug == "shard-void-leak":
                for s in shards:
                    s.unsafe_skip_void_accounting = True
        #: shard index an open pressure window targets (0 on a single
        #: store); a crash inside the window reinstalls the shim there
        self._pressure_shard = 0
        self.store.set_crash_hook(self._crash_dispatch)

        # ----- replicas + actors ------------------------------------
        self.seats: Dict[str, List[Replica]] = {}
        self.actors: List = []
        self.record = RunRecord(
            seed=opts.seed, trace=self.trace, store_shards=self.n_shards
        )
        for seat, lease in SEATS:
            reps = [
                Replica(self, seat, lease, i, opts.lease_duration)
                for i in range(opts.replicas)
            ]
            self.seats[seat] = reps
            for i, r in enumerate(reps):
                self.actors.append(ElectorActor(self, r))
                if seat == "kcm":
                    ungated = opts.bug == "ungated-writer" and i == 1
                    self.actors.append(KcmActor(self, r, ungated=ungated))
                    self.record.gated_writers[r.name] = r.name
                elif seat == "sched":
                    self.actors.append(SchedulerActor(self, r))
                    self.record.gated_writers[r.name] = r.name
                elif seat == "kwok":
                    from kwok_tpu.controllers.node_controller import node_funcs
                    from kwok_tpu.controllers.pod_controller import PodEnv
                    from kwok_tpu.stages import (
                        default_node_stages,
                        default_pod_stages,
                    )

                    nf = node_funcs("10.0.0.1", r.name, 10247)
                    env = PodEnv()
                    self.actors.append(
                        LifecycleActor(
                            self,
                            r,
                            "Node",
                            default_node_stages(lease=False),
                            funcs_for=lambda obj, _nf=nf: _nf,
                        )
                    )
                    self.actors.append(
                        LifecycleActor(
                            self,
                            r,
                            "Pod",
                            default_pod_stages(),
                            funcs_for=env.funcs,
                            on_delete=env.release,
                        )
                    )
                    self.record.gated_writers[f"{r.name}/node"] = r.name
                    self.record.gated_writers[f"{r.name}/pod"] = r.name
        self.observer = ObserverActor(self, "Pod")
        self.actors.append(self.observer)

        # ----- fleet tenants (kwok_tpu/fleet) -----------------------
        # each tenant: one writer (its control-plane traffic, through
        # the TenantStore scoping) + one scoped observer (its informer).
        # "tenant-leak" un-scopes the FIRST tenant's observer — the
        # regression the tenant-isolation invariant must catch.
        self.fleet_writers: List[FleetWriterActor] = []
        self.fleet_observers: List[TenantObserverActor] = []
        fleet_ids: List[str] = []
        if opts.fleet_tenants > 0:
            from kwok_tpu.fleet.tenant import fleet_tenant_ids

            fleet_ids = fleet_tenant_ids(opts.fleet_tenants)
            for i, tid in enumerate(fleet_ids):
                w = FleetWriterActor(self, tid)
                self.fleet_writers.append(w)
                self.actors.append(w)
                ob = TenantObserverActor(
                    self, tid, leaky=(opts.bug == "tenant-leak" and i == 0)
                )
                self.fleet_observers.append(ob)
                self.actors.append(ob)

        if opts.schedule is not None:
            self.faults = FaultTimeline.from_spec(opts.schedule, opts.seed)
        else:
            self.faults = seeded_timeline(opts, fleet_ids)
        # region-move probes derive from the schedule itself (seeded
        # or spec'd) so a mutated/minimized schedule keeps — or
        # provably drops — its bounded-disruption probe with the fault
        for s in self.faults.scheduled:
            if s.kind != "tenant-region-move":
                continue
            client = str(s.params.get("client") or "")
            tid = client.split(":", 1)[1] if ":" in client else client
            if tid not in fleet_ids:
                continue
            dur = float(s.params.get("duration") or 0.0)
            self.record.tenant_region_checks.append(
                {
                    "tenant": tid,
                    "t": round(s.t - EPOCH, 3),
                    "t_end": s.t + dur,
                    "duration": round(dur, 3),
                }
            )
        self._killed: Dict[str, Replica] = {}
        self._paused: Dict[str, Replica] = {}
        self._scenario = self._build_scenario()
        # the scenario/operator writes ride the system level, like
        # kwokctl traffic under APF
        self._op_store = ActorStore(self, "scenario", "system:scenario")

    # -------------------------------------------------------------- plumbing

    def next_suffix(self) -> str:
        """Deterministic Event-name uniquifier shared by every
        recorder (the monotonic-ns stand-in)."""
        self._suffix_n += 1
        return f"{self._suffix_n:x}"

    def note_ack(self, rv_before: Optional[int] = None) -> None:
        rv = self.store.resource_version
        self.max_acked_rv = max(self.max_acked_rv, rv)
        if rv_before is not None and rv > rv_before:
            self.acked_rvs.update(range(rv_before + 1, rv + 1))

    def note_degraded_rejection(self, actor: str, verb: str) -> None:
        """A mutation visibly refused by the degraded read-only gate
        (ActorStore records it here + in the trace)."""
        self.trace.add(self.clock.now(), actor, "degraded-rejected", verb)
        if self._pressure_probe is not None:
            self._pressure_probe["rejections"] += 1
            if verb in ("txn", "bulk"):
                # batch lanes refuse the ack WITHOUT rolling back (the
                # ops stay committed in memory, their rvs not yet
                # durable) — a legitimate union-continuity hole, so
                # the void-accounting probe excuses this window
                self._pressure_probe["batch_rejections"] += 1

    def _crash_dispatch(self, phase: str) -> None:
        arm = self._crash_arm
        if arm is None or phase != arm["phase"]:
            return
        if arm["skip"] > 0:
            arm["skip"] -= 1
            return
        self._crash_arm = None
        raise SimCrash(phase)

    def _recover(self):
        """Lose the in-memory store, recover a fresh one from the WAL
        through the tolerant path (recover_wal — a previously-injected
        disk fault must be detected and reported, never crash the
        recovery), and swap it in.  Returns the RecoveryReport."""
        t = self.clock.now()
        for w in self.wals:
            w.close()
        if self.n_shards == 1:
            recovered = ResourceStore(clock=self.clock)
            rep = recovered.recover_wal(self.wal_paths[0])
            self.wals = [WriteAheadLog(self.wal_paths[0], fsync="off", reserve_bytes=WAL_RESERVE_BYTES)]
            recovered.attach_wal(self.wals[0])
        else:
            # per-shard tolerant replay + the union rv-continuity
            # check (kwok_tpu/cluster/sharding/recovery.py)
            from kwok_tpu.cluster.sharding.recovery import recover_sharded

            out = recover_sharded(self.wal_paths, clock=self.clock)
            recovered = out["store"]
            rep = out["report"]
            self.wals = [
                WriteAheadLog(p, fsync="off", reserve_bytes=WAL_RESERVE_BYTES) for p in self.wal_paths
            ]
            for i, w in enumerate(self.wals):
                recovered.shard_lane(i).attach_wal(w)
            if self.opts.bug == "cross-shard-txn":
                recovered.unsafe_split_cross_shard_txns = True
            elif self.opts.bug == "fanin-stale-resume":
                recovered.unsafe_fanin_stale_resume = True
            elif self.opts.bug == "shard-void-leak":
                for i in range(self.n_shards):
                    recovered.shard_lane(
                        i
                    ).unsafe_skip_void_accounting = True
        if self._active_pressure is not None:
            # a crash inside a pressure window: the disk is still full
            # when the process comes back
            self.wals[self._pressure_shard].set_pressure(
                self._active_pressure
            )
        recovered.set_crash_hook(self._crash_dispatch)
        self.store = recovered
        self.store_generation += 1
        self.trace.add(
            t,
            "store",
            "recovered",
            f"rv={recovered.resource_version} records={rep.applied}",
        )
        return rep

    def _restart_store(self, crash: SimCrash) -> None:
        """Simulated store-process death: lose the in-memory state,
        recover from the WAL (the chaos --smoke recovery path, run
        mid-simulation)."""
        self.trace.add(self.clock.now(), "store", "crash", crash.phase)
        rep = self._recover()
        self.crash_checks.append(
            {
                "acked_rv": self.max_acked_rv,
                "recovered_rv": rep.recovered_rv,
                "records": rep.applied,
            }
        )
        self._gang_probe(self.store, "crash")

    def _gang_probe(self, store, at: str) -> None:
        """Gang-atomicity evidence: for every gang present in a
        (recovered) store state, how many of its live members are
        bound.  A bound strict subset is exactly what the atomic txn
        lane makes impossible — the gang-atomicity invariant flags it
        (kwok_tpu/dst/invariants.py)."""
        from kwok_tpu.sched.group import POD_GROUP_ANNOTATION

        try:
            pods, _ = store.list("Pod")
        except Exception:  # noqa: BLE001 — probe only; no Pods yet
            return
        gangs: Dict[str, List[dict]] = {}
        for p in pods:
            meta = p.get("metadata") or {}
            g = (meta.get("annotations") or {}).get(POD_GROUP_ANNOTATION)
            if not g:
                continue
            if (p.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
                continue
            gangs.setdefault(
                f"{meta.get('namespace') or 'default'}/{g}", []
            ).append(p)
        for key in sorted(gangs):
            members = gangs[key]
            bound = sum(
                1 for p in members if (p.get("spec") or {}).get("nodeName")
            )
            self.gang_checks.append(
                {
                    "at": at,
                    "gang": key,
                    "present": len(members),
                    "bound": bound,
                    "t": round(self.clock.now() - EPOCH, 3),
                }
            )

    def _disk_fault(self, mode: str, shard: Optional[int] = None) -> None:
        """Seeded storage corruption against the live WAL, then an
        immediate crash-recovery through the tolerant path.  The probe
        records, at fault time, how every acked rv was accounted for —
        applied, reported lost, or (a violation) silently gone — and
        then prunes the ack bookkeeping to the recovered baseline,
        because resourceVersion numbering restarts below the rollback
        point."""
        from kwok_tpu.chaos import disk_faults

        t = self.clock.now()
        # target shard (always 0 on a single store): damage lands on
        # ONE shard's log, recovery must bound the loss to that
        # shard's slice of the rv sequence.  An explicit shard comes
        # from a mutated schedule spec (the search's retarget
        # operator); otherwise the draw stays at fire time so
        # seed-derived runs are unchanged
        if shard is None:
            shard = (
                self.faults.rng.randrange(self.n_shards)
                if self.n_shards > 1
                else 0
            )
        shard = min(max(int(shard), 0), self.n_shards - 1)
        path = self.wal_paths[shard]
        if mode == "bit-flip":
            info = disk_faults.bit_flip_line(
                path, self.faults.rng, exclude_last=True
            )
        else:
            info = disk_faults.truncate_mid_record(path, self.faults.rng)
        noop = info.get("offset", -1) < 0
        self.trace.add(
            t,
            "faults",
            "disk-corrupt",
            f"{mode} shard={shard} offset={info.get('offset', -1)}",
        )
        rep = self._recover()
        missing = set(rep.missing_rvs)
        # the RecoveryReport's own honesty classification — the same
        # predicate the corruption smoke asserts
        reported, silent = rep.account(self.acked_rvs)
        self.disk_checks.append(
            {
                "mode": mode,
                "noop": noop,
                "reported_lost": reported,
                "silent_lost": silent,
                "recovered_rv": rep.recovered_rv,
                "corruptions": len(rep.corruptions),
                "torn_tail": rep.torn_tail,
            }
        )
        self.trace.add(
            t,
            "store",
            "disk-recovered",
            f"rv={rep.recovered_rv} reported={len(reported)} "
            f"silent={len(silent)}",
        )
        self._gang_probe(self.store, "disk")
        # prune to the post-rollback world: lost rvs were accounted
        # above, and their numbers will be re-issued by new commits
        self.acked_rvs = {
            rv
            for rv in self.acked_rvs
            if rv <= rep.recovered_rv and rv not in missing
        }
        self.max_acked_rv = min(self.max_acked_rv, rep.recovered_rv)

    # -------------------------------------------------------------- scenario

    def _build_scenario(self) -> List[tuple]:
        o = self.opts
        t0 = EPOCH
        steps: List[tuple] = []
        for i in range(o.nodes):
            steps.append((t0 + 0.5, "node", f"node-{i}"))
        steps.append((t0 + 2.0, "deployment", ("web", o.deployment_replicas)))
        steps.append((t0 + o.duration * 0.4, "scale", ("web", o.scale_to)))
        steps.append((t0 + o.duration * 0.7, "scale", ("web", o.scale_back)))
        if o.gang_size > 0:
            # the gang lands mid-faults: PodGroup first, then members
            # staggered so the engine provably waits for minMember
            tg = t0 + o.duration * 0.5
            steps.append((tg, "podgroup", ("train", o.gang_size)))
            for i in range(o.gang_size):
                steps.append((tg + 0.3 * (i + 1), "gang-pod", ("train", i)))
            # operator re-submit after the fault window: a disk fault
            # can honestly roll back (and report) the creates above —
            # including the NODES — and a real operator re-applies;
            # creates tolerate AlreadyExists so this is a no-op on
            # clean runs
            for i in range(o.nodes):
                steps.append((t0 + o.duration - 0.5, "node", f"node-{i}"))
            steps.append((t0 + o.duration, "podgroup", ("train", o.gang_size)))
            for i in range(o.gang_size):
                steps.append(
                    (t0 + o.duration + 0.1 * (i + 1), "gang-pod", ("train", i))
                )
        return steps

    def _apply_scenario(self, kind: str, arg):
        """Returns "degraded" when the write was refused by the
        degraded read-only gate (the run loop reschedules the step to
        just past the pressure window), else None."""
        if kind == "node":
            from kwok_tpu.sched.topology import TopologyModel

            topo = TopologyModel(slice_hosts=self.opts.gang_slice_hosts)
            idx = int(arg.rsplit("-", 1)[-1])
            obj = {
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": {"name": arg, "labels": topo.labels_for(idx)},
                "spec": {},
                "status": {
                    "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"},
                    "capacity": {"cpu": "8", "memory": "16Gi", "pods": "110"},
                },
            }
            return self._must(lambda: self._op_store.create(dict(obj)))
        elif kind == "podgroup":
            name, size = arg
            obj = {
                "apiVersion": "scheduling.kwok.io/v1alpha1",
                "kind": "PodGroup",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"minMember": size, "priority": 10},
            }
            return self._must(lambda: self._op_store.create(dict(obj)))
        elif kind == "gang-pod":
            gname, i = arg
            obj = {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": f"{gname}-{i}",
                    "namespace": "default",
                    "annotations": {"kwok.io/pod-group": gname},
                },
                "spec": {
                    "containers": [
                        {
                            "name": "train",
                            "image": "fake",
                            "resources": {
                                "requests": {"cpu": "1", "memory": "128Mi"}
                            },
                        }
                    ]
                },
            }
            return self._must(lambda: self._op_store.create(dict(obj)))
        elif kind == "deployment":
            name, replicas = arg
            obj = {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {
                    "replicas": replicas,
                    "selector": {"matchLabels": {"app": name}},
                    "template": {
                        "metadata": {"labels": {"app": name}},
                        "spec": {
                            "containers": [
                                {
                                    "name": "app",
                                    "image": "fake",
                                    "resources": {
                                        "requests": {
                                            "cpu": "100m",
                                            "memory": "64Mi",
                                        }
                                    },
                                }
                            ]
                        },
                    },
                },
            }
            return self._must(lambda: self._op_store.create(dict(obj)))
        elif kind == "scale":
            name, replicas = arg
            return self._must(
                lambda: self._op_store.patch(
                    "Deployment",
                    name,
                    {"spec": {"replicas": replicas}},
                    "merge",
                    namespace="default",
                )
            )

    def _must(self, fn):
        """Drive an operator mutation to an acknowledged outcome, the
        chaos-smoke `must` contract: ApiUnavailable may mean applied —
        replay, treating already-applied answers as success.  Returns
        "degraded" when storage is in read-only mode (retrying in-place
        would spin inside one virtual instant; the caller reschedules
        the step past the pressure window instead)."""
        for _ in range(30):
            try:
                fn()
                return None
            except SimCrash as c:
                self._restart_store(c)
            except StorageDegraded:
                return "degraded"
            except ApiUnavailable:
                continue
            except Conflict:
                return None
            except NotFound:
                return None
        self.trace.add(self.clock.now(), "scenario", "gave-up", "")
        return None

    # ------------------------------------------------------------------ faults

    def _apply_fault(self, sched) -> None:
        t = self.clock.now()
        kind, params = sched.kind, sched.params
        if kind == "crash":
            self._crash_arm = dict(params)
            self.trace.add(
                t, "faults", "arm-crash", f"{params['phase']} skip={params['skip']}"
            )
        elif kind == "leader-kill":
            seat = params["seat"]
            reps = self.seats[seat]
            target = next((r for r in reps if r.leading), reps[0])
            target.kill()
            self._killed[seat] = target
            self.trace.add(t, "faults", "leader-kill", target.name)
        elif kind == "restart":
            seat = params["seat"]
            target = self._killed.pop(seat, None)
            if target is not None:
                target.revive()
                self.trace.add(t, "faults", "restart", target.name)
        elif kind == "pause":
            seat = params["seat"]
            reps = self.seats[seat]
            target = next(
                (r for r in reps if r.leading and r.alive),
                next((r for r in reps if r.alive), None),
            )
            if target is not None:
                target.paused = True
                self._paused[seat] = target
                self.trace.add(t, "faults", "pause", target.name)
        elif kind == "resume":
            seat = params["seat"]
            target = self._paused.pop(seat, None)
            if target is not None:
                target.paused = False
                self.trace.add(t, "faults", "resume", target.name)
        elif kind == "tenant-region-move":
            self.trace.add(
                t,
                "faults",
                "tenant-region-move",
                f"{params['client']} dur={params['duration']:.2f}",
            )
        elif kind == "disk-corrupt":
            self._disk_fault(params["mode"], params.get("shard"))
        elif kind == "pressure-start":
            self._pressure_start(params["mode"], params.get("shard"))
        elif kind == "pressure-end":
            self._pressure_end(params["mode"])

    def _pressure_start(self, mode: str, shard: Optional[int] = None) -> None:
        """Open a storage-exhaustion window: the WAL's writes start
        being refused (disk-full/quota semantics, fs_pressure shim);
        the first failing append releases the emergency reserve and
        flips the store into degraded read-only mode."""
        from kwok_tpu.chaos.fs_pressure import FsPressure

        t = self.clock.now()
        shim = FsPressure(mode)
        self._active_pressure = shim
        # target shard: exhaustion degrades ONE shard's writes (the
        # per-shard StorageDegraded story); other shards stay writable
        # through the window.  Explicit shard = mutated-spec retarget;
        # else the fire-time draw, unchanged for seed-derived runs
        if shard is None:
            shard = (
                self.faults.rng.randrange(self.n_shards)
                if self.n_shards > 1
                else 0
            )
        self._pressure_shard = min(max(int(shard), 0), self.n_shards - 1)
        self.wals[self._pressure_shard].set_pressure(shim)
        self._pressure_probe = {
            "mode": mode,
            "start_acked": set(self.acked_rvs),
            "rejections": 0,
            "batch_rejections": 0,
        }
        self.trace.add(
            t,
            "faults",
            "pressure-start",
            f"{mode} shard={self._pressure_shard}",
        )

    def _pressure_end(self, mode: str) -> None:
        """Close the window, force the re-arm probe, and record the
        exhaustion-honesty evidence: every rv acked during the window
        must be present in the log (durable) — anything else was a
        visible rejection, never a silent ack."""
        from kwok_tpu.cluster import wal as walmod

        t = self.clock.now()
        self.wals[self._pressure_shard].set_pressure(None)
        self._active_pressure = None
        rearmed = self.wals[self._pressure_shard].try_rearm()
        probe = self._pressure_probe or {
            "mode": mode, "start_acked": set(), "rejections": 0,
        }
        self._pressure_probe = None
        acked_during = self.acked_rvs - probe["start_acked"]
        # acked rvs may live on ANY shard's log (only one shard was
        # under pressure) — the durability check scans the union.
        # Deliberately NOT include_void: an acked rv that was voided
        # is a lost write, not a covered one
        observed: set = set()
        voided: set = set()
        for path in self.wal_paths:
            for rec in walmod.scan(path).records:
                observed.update(walmod.record_rvs(rec))
                voided.update(walmod.record_rvs(rec, include_void=True))
        voided -= observed
        silent = sorted(rv for rv in acked_during if rv not in observed)
        # void-accounting probe (recovery-honesty): every allocated rv
        # must be durable in the union or voided — a rolled-back write
        # that skips BOTH leaks a hole fsck/recovery can only read as
        # a lost record.  Only checkable when no batch-lane refusal
        # (rvs legitimately committed-in-memory-only) and no earlier
        # disk damage (corrupt records legitimately unreadable) can
        # explain a hole
        top = max(observed | voided, default=0)
        holes = sorted(
            rv
            for rv in range(1, top + 1)
            if rv not in observed and rv not in voided
        )
        self.exhaustion_checks.append(
            {
                "mode": mode,
                "acked_during": len(acked_during),
                "rejections": probe["rejections"],
                "silent_lost": silent,
                "rearmed": bool(rearmed),
                "unaccounted_rvs": holes[:16],
                "batch_rejections": probe.get("batch_rejections", 0),
                "prior_damage": len(self.disk_checks),
            }
        )
        self.trace.add(
            t,
            "store",
            "pressure-end",
            f"{mode} acked={len(acked_during)} "
            f"rejected={probe['rejections']} silent={len(silent)} "
            f"rearmed={int(bool(rearmed))}",
        )

    # ------------------------------------------------------------- main loop

    def run(self) -> RunRecord:
        o = self.opts
        t_end = EPOCH + o.duration
        t_hard = t_end + o.quiesce
        scenario = sorted(self._scenario, key=lambda s: s[0])
        si = 0
        while True:
            now = self.clock.now()
            # next instant anything happens
            times = [a.next_due for a in self.actors if a.runnable()]
            if si < len(scenario):
                times.append(scenario[si][0])
            ft = self.faults.next_time()
            if ft is not None:
                times.append(ft)
            if not times:
                break
            t_next = max(min(times), now)
            if t_next > t_hard:
                break
            self.clock.set(t_next)
            now = self.clock.now()

            while si < len(scenario) and scenario[si][0] <= now:
                _, kind, arg = scenario[si]
                si += 1
                if self._apply_scenario(kind, arg) == "degraded":
                    # storage is read-only: re-run this step just past
                    # the pressure window instead of spinning now
                    import bisect

                    retry_at = self.faults.pressure_end_after(now) + 0.5
                    bisect.insort(
                        scenario, (retry_at, kind, arg), lo=si
                    )
            for sched in self.faults.due(now):
                self._apply_fault(sched)

            due = [
                a
                for a in self.actors
                if a.runnable() and a.next_due <= now
            ]
            self.rng.shuffle(due)
            for actor in due:
                if not actor.runnable():
                    continue  # a fault just killed/paused its replica
                self.steps += 1
                try:
                    actor.step()
                except SimCrash as c:
                    self._restart_store(c)
                # partition/shed/degraded surfacing above a component's
                # own retry seam: the next scheduled step retries it
                # (degraded rejections are already traced by ActorStore)
                except (ApiUnavailable, StorageDegraded):  # kwoklint: disable=swallowed-errors
                    pass
                except Exception as exc:  # noqa: BLE001 — an actor bug
                    # must fail the run loudly, not hang it
                    self.trace.add(
                        now, actor.name, "actor-error", repr(exc)
                    )
                actor.schedule_next()

            if now >= t_end and si >= len(scenario):
                ok, detail = self._converged()
                if ok:
                    break
        return self._finish()

    # ---------------------------------------------------------- verification

    def _converged(self) -> tuple:
        store = self.store
        for seat, reps in self.seats.items():
            if not any(r.is_leader() for r in reps):
                return False, f"seat {seat} has no live leader"
        deps, _ = store.list("Deployment")
        for d in deps:
            name = (d.get("metadata") or {}).get("name")
            want = (d.get("spec") or {}).get("replicas", 1)
            st = d.get("status") or {}
            if (
                st.get("replicas") != want
                or st.get("readyReplicas", 0) != want
                or st.get("updatedReplicas", 0) != want
            ):
                return False, (
                    f"deployment {name}: status {st.get('replicas')}/"
                    f"{st.get('readyReplicas', 0)} ready, want {want}"
                )
        hpas, _ = store.list("HorizontalPodAutoscaler")
        for h in hpas:
            spec = h.get("spec") or {}
            cur = (h.get("status") or {}).get("currentReplicas")
            lo = spec.get("minReplicas", 1)
            hi = spec.get("maxReplicas", lo)
            if cur is None or not (lo <= cur <= hi):
                return False, "hpa outside [min,max]"
        pods, _ = store.list("Pod")
        for p in pods:
            meta = p.get("metadata") or {}
            if meta.get("deletionTimestamp"):
                return False, f"pod {meta.get('name')} still terminating"
            if not (p.get("spec") or {}).get("nodeName"):
                return False, f"pod {meta.get('name')} unbound"
            if (p.get("status") or {}).get("phase") != "Running":
                return False, f"pod {meta.get('name')} not Running"
        return True, ""

    def _fleet_flow_probe(self) -> None:
        """Deterministic APF starvation probe (no HTTP, no threads):
        build the fleet's generated FlowConfiguration with a zero queue
        wait, flood ONE tenant's level to rejection while holding every
        granted seat, then assert a neighbor tenant and the system
        level still admit — the per-tenant-level isolation contract
        (kwok_tpu/fleet/flow.py seat floors) checked in-process, where
        single-threadedness makes the outcome a pure function of the
        config."""
        from kwok_tpu.cluster.flowcontrol import FlowController, FlowRejected
        from kwok_tpu.fleet.flow import fleet_flow_config, tenant_client_id

        tids = [w.tenant for w in self.fleet_writers]
        if len(tids) < 2:
            return
        fc = FlowController(
            fleet_flow_config(tids, queue_wait_s=0.0, queue_limit=2)
        )
        flooded, victim = tids[0], tids[1]
        held = []
        rejections = 0
        for _ in range(64):
            try:
                held.append(
                    fc.admit(
                        tenant_client_id(flooded),
                        "POST",
                        "/r/configmaps",
                        level=flooded,
                    )
                )
            except FlowRejected:
                rejections += 1
                break
        victim_ok = True
        try:
            fc.release(
                fc.admit(
                    tenant_client_id(victim), "POST", "/r/configmaps",
                    level=victim,
                )
            )
        except FlowRejected:
            victim_ok = False
        system_ok = True
        try:
            fc.release(fc.admit("system:probe", "GET", "/r/pods"))
        except FlowRejected:
            system_ok = False
        for t in held:
            fc.release(t)
        self.record.tenant_flow_checks.append(
            {
                "flooded": flooded,
                "victim": victim,
                "flood_rejections": rejections,
                "victim_ok": victim_ok,
                "system_ok": system_ok,
            }
        )

    def _finish(self) -> RunRecord:
        rec = self.record
        rec.converged, rec.convergence_detail = self._converged()
        rec.streams = self.observer.streams
        for ob in self.fleet_observers:
            rec.tenant_streams[ob.tenant] = ob.names
        for chk in rec.tenant_region_checks:
            w = next(
                (w for w in self.fleet_writers if w.tenant == chk["tenant"]),
                None,
            )
            chk["resumed"] = bool(w is not None and w.last_ok_t > chk["t_end"])
        if self.fleet_writers:
            self._fleet_flow_probe()
        rec.crash_checks = self.crash_checks
        rec.disk_checks = self.disk_checks
        rec.exhaustion_checks = self.exhaustion_checks
        self._gang_probe(self.store, "final")
        rec.audit_overflow = self.store.audit_overflow
        rec.steps = self.steps
        rec.virtual_end = self.clock.now() - EPOCH
        for kind in ("Node", "Pod", "Deployment", "ReplicaSet"):
            rec.final_counts[kind] = self.store.count(kind)
        # durability epilogue: the WAL(s) alone must reproduce the live
        # state (the chaos --smoke recovery assertion, end-of-run form).
        # Tolerant recovery: an injected disk fault earlier in the run
        # left detected (and already-probed) damage mid-log — the final
        # replay must deterministically apply the same verifiable set.
        for w in self.wals:
            w.close()
        if self.n_shards == 1:
            replayed = ResourceStore()
            replayed.recover_wal(self.wal_paths[0])
        else:
            from kwok_tpu.cluster.sharding.recovery import recover_sharded

            replayed = recover_sharded(self.wal_paths)["store"]
        self._gang_probe(replayed, "replay")
        self.record.gang_checks = self.gang_checks
        live, fresh = self.store.dump_state(), replayed.dump_state()
        rec.replay_matches = live == fresh
        if not rec.replay_matches:
            rec.replay_detail = (
                f"live rv={live['resourceVersion']} objects="
                f"{len(live['objects'])}; replayed "
                f"rv={fresh['resourceVersion']} objects={len(fresh['objects'])}"
            )
        return rec


def run_record(
    seed: int, opts: Optional[SimOptions] = None
) -> tuple:
    """Run one seeded simulation; returns ``(RunRecord, violations)``
    — the full-evidence form the coverage-guided search extracts its
    feature vector from (``run_seed`` is the JSON-report wrapper)."""
    from kwok_tpu.utils import sprig

    o = opts or SimOptions()
    o = SimOptions(**{**o.__dict__, "seed": seed})
    # Simulation seeds the process-global template rng; scope that to
    # this run so shared-process callers (pytest) are not left with a
    # DST-seeded sprig
    prev_rng = sprig.set_default_rng(random.Random(seed ^ 0x517A1))
    try:
        with tempfile.TemporaryDirectory(prefix="kwok-dst-") as tmp:
            sim = Simulation(o, tmp)
            rec = sim.run()
            violations = run_checks(rec)
    finally:
        sprig.set_default_rng(prev_rng)
    return rec, violations


def run_seed(
    seed: int, opts: Optional[SimOptions] = None
) -> Dict:
    """Run one seeded simulation; returns the JSON-able report
    (violations, trace digest, convergence, counters)."""
    rec, violations = run_record(seed, opts)
    return {
        "seed": rec.seed,
        "trace_digest": rec.trace.digest(),
        "trace_events": len(rec.trace),
        "steps": rec.steps,
        "virtual_s": round(rec.virtual_end, 3),
        "converged": rec.converged,
        "crashes": len(rec.crash_checks),
        "disk_faults": len(rec.disk_checks),
        "pressure_windows": len(rec.exhaustion_checks),
        "gang_probes": len(rec.gang_checks),
        "fleet_tenants": len(rec.tenant_streams),
        "region_moves": len(rec.tenant_region_checks),
        "counts": rec.final_counts,
        "violations": violations,
    }


def run_seeds(
    seeds: int, opts: Optional[SimOptions] = None, start: int = 0
) -> Dict:
    """Explore ``seeds`` consecutive seeds; returns the aggregate
    report (per-seed lines + any violating seeds)."""
    runs = [run_seed(start + i, opts) for i in range(seeds)]
    violating = [r for r in runs if r["violations"]]
    return {
        "seeds": seeds,
        "start": start,
        "violating_seeds": [r["seed"] for r in violating],
        "violations": {r["seed"]: r["violations"] for r in violating},
        "runs": runs,
    }
