"""Deterministic simulation testing (DST) for the kwok-tpu control
plane.

Runs the whole control plane in one process on a
:class:`~kwok_tpu.utils.clock.VirtualClock`, with a seeded interleaving
scheduler injecting the chaos fault vocabulary at virtual instants, and
Kivi-style invariant checkers replaying the trace afterwards — turning
the chaos subsystem from smoke tests into a reproducible bug search
(``python -m kwok_tpu.chaos --dst --seeds N``; ROADMAP.md:101).

Layout: :mod:`~kwok_tpu.dst.harness` owns the simulation loop,
:mod:`~kwok_tpu.dst.actors` the synchronous component drivers,
:mod:`~kwok_tpu.dst.faults` the fault timeline and the per-actor store
boundary, :mod:`~kwok_tpu.dst.invariants` the checkers,
:mod:`~kwok_tpu.dst.trace` the canonical hashable run trace, and
:mod:`~kwok_tpu.dst.search` the coverage-guided fault search over
schedules (``--dst-search`` / ``--dst-replay``).
"""

from kwok_tpu.dst.harness import RunRecord, SimOptions, Simulation, run_seed, run_seeds
from kwok_tpu.dst.invariants import INVARIANTS, run_checks
from kwok_tpu.dst.trace import Trace, TraceEvent

__all__ = [
    "RunRecord",
    "SimOptions",
    "Simulation",
    "run_seed",
    "run_seeds",
    "INVARIANTS",
    "run_checks",
    "Trace",
    "TraceEvent",
]
