"""Coverage-guided fault search over the DST harness.

Plain ``--dst`` walks consecutive seeds — uniform sampling of the
schedule space.  That finds shallow bugs fast (a bug caught on a third
of all seeds appears within the first handful) but is blind to narrow
interleavings: a regression that needs a crash landing inside a
specific two-commit window can hide for hundreds of seeds.  This
module is the greybox-fuzzing answer (AFL's corpus/mutation loop and
coverage signal in PAPERS.md, applied to fault *schedules* instead of
byte inputs; the reference control plane gets the equivalent depth
from etcd's failpoint robustness tests —
``/root/reference/test/e2e/kwokctl_test.go:1`` exercises only the happy
path, which is exactly the gap ROADMAP.md:101 names):

- **signal**: a bounded feature vector extracted from the finished
  run's :class:`~kwok_tpu.dst.harness.RunRecord`
  (:func:`extract_features`) — per-actor action bigrams, fault-kind ×
  actor-state pairs, and log2-bucketed invariant-probe counters.
  Everything feeds off digest-stable content (trace events + probe
  dicts), so arming telemetry/tracing cannot change coverage.
- **corpus**: schedules that light ≥1 never-seen feature are kept as
  ``(seed, spec)`` pairs (``FaultTimeline.to_spec`` form).
- **mutation**: seeded operators over fault *groups* (a pause rides
  with its resume, a pressure window with its end, a region move with
  its partition window — :func:`schedule_groups`): shift a group's
  virtual instant, retarget its seat/replica/shard/tenant, duplicate
  it into overlap, splice two corpus schedules, drop a group.  Every
  draw comes from one ``random.Random(search_seed)`` stream and the
  harness's runtime rng is a pure function of the run seed
  (``FaultTimeline.seal_runtime_rng``), so the whole search is
  replayable from ``--search-seed`` alone.
- **on violation**: delta-debug the schedule to a minimal group set
  (:func:`minimize`, greedy ddmin over groups) and emit a replay
  artifact (:func:`violation_artifact`) that ``--dst-replay FILE``
  re-executes byte-identically — the regression-pinning format.

CLI: ``python -m kwok_tpu.chaos --dst-search [--search-budget N]
[--search-seed S] [--dst-bug B] [--search-out FILE]`` and
``--dst-replay FILE``.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import json
import random
import re
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Set, Tuple

from kwok_tpu.dst.harness import (
    SimOptions,
    run_record,
    seeded_schedule_spec,
)

__all__ = [
    "SearchResult",
    "extract_features",
    "guided_search",
    "minimize",
    "replay_artifact",
    "schedule_groups",
    "spec_digest",
    "violation_artifact",
]

#: SimOptions fields a replay artifact must pin to reproduce the run
#: (everything that shapes the simulation except the schedule itself,
#: which travels separately)
ARTIFACT_OPTS = (
    "duration",
    "quiesce",
    "replicas",
    "lease_duration",
    "faults",
    "bug",
    "store_shards",
    "nodes",
    "deployment_replicas",
    "scale_to",
    "scale_back",
    "gang_size",
    "gang_slice_hosts",
    "fleet_tenants",
)

#: fresh seed-derived schedules executed before mutation starts (the
#: corpus needs something to mutate), and the probability of taking
#: another fresh seed later instead of mutating (keeps exploring the
#: seed distribution so the corpus never inbreeds)
INIT_FRESH = 4
FRESH_P = 0.15


# --------------------------------------------------------------- features


_REPLICA_IDX = re.compile(r"-\d+")
_TENANT_IDX = re.compile(r"\bt\d+$")


def _norm_actor(actor: str) -> str:
    """Collapse replica/tenant indices so the feature space stays
    bounded no matter how many replicas or tenants a run composes:
    ``kcm-1/elector`` -> ``kcm/elector``, ``fleet/t013`` -> ``fleet/t``."""
    return _TENANT_IDX.sub("t", _REPLICA_IDX.sub("", actor))


def _bucket(n: int) -> int:
    """log2 bucket — counters contribute O(log n) features, not O(n)."""
    b = 0
    while n:
        n >>= 1
        b += 1
    return b


def extract_features(record) -> FrozenSet[Tuple]:
    """The bounded coverage signal for one finished run.

    Three families, all derived from digest-stable content (the trace
    and the invariant probes — never telemetry, never wall time):

    - ``("bg", actor, a1, a2)``: consecutive action pairs per
      normalized actor — which *state transitions* each component
      exercised.
    - ``("fs", fault, killed, paused, pressure, armed)``: each injected
      fault tagged with the system state it landed in (how many seats
      dead / paused, a pressure window open, a crash armed) — the
      interleaving context uniform seeding can't target.
    - ``("ct", name, bucket)``: log2-bucketed probe counters (crashes,
      reported/silent losses, degraded rejections, observer streams)
      plus exact small-int gang occupancy pairs — the invariant
      checkers' intermediate states.
    """
    feats: Set[Tuple] = set()
    last_action: Dict[str, str] = {}
    killed: Set[str] = set()
    paused: Set[str] = set()
    pressure = 0
    armed = False
    for ev in record.trace.events:
        actor = _norm_actor(ev.actor)
        prev = last_action.get(actor)
        if prev is not None:
            feats.add(("bg", actor, prev, ev.action))
        last_action[actor] = ev.action
        if ev.actor == "faults":
            feats.add(
                (
                    "fs",
                    ev.action,
                    len(killed),
                    len(paused),
                    pressure > 0,
                    armed,
                )
            )
            seat = ev.detail.split()[0] if ev.detail else ""
            if ev.action == "leader-kill":
                killed.add(seat)
            elif ev.action == "restart":
                killed.discard(seat)
            elif ev.action == "pause":
                paused.add(seat)
            elif ev.action == "resume":
                paused.discard(seat)
            elif ev.action == "pressure-start":
                pressure += 1
            elif ev.action == "arm-crash":
                armed = True
        elif ev.actor == "store":
            if ev.action == "pressure-end":
                pressure = max(0, pressure - 1)
            elif ev.action == "crash":
                armed = False
    feats.add(("ct", "crashes", _bucket(len(record.crash_checks))))
    feats.add(("ct", "disk", _bucket(len(record.disk_checks))))
    reported = sum(len(c.get("reported_lost") or []) for c in record.disk_checks)
    silent = sum(len(c.get("silent_lost") or []) for c in record.disk_checks)
    feats.add(("ct", "reported-lost", _bucket(reported)))
    feats.add(("ct", "silent-lost", _bucket(silent)))
    rej = sum(c.get("rejections", 0) for c in record.exhaustion_checks)
    feats.add(("ct", "rejections", _bucket(rej)))
    brej = sum(c.get("batch_rejections", 0) for c in record.exhaustion_checks)
    feats.add(("ct", "batch-rejections", _bucket(brej)))
    feats.add(("ct", "streams", _bucket(len(record.streams))))
    feats.add(
        ("ct", "region-moves", _bucket(len(record.tenant_region_checks)))
    )
    for g in record.gang_checks:
        # exact small ints: a (bound, present) occupancy pair is the
        # gang engine's intermediate state — (2, 3) mid-recovery is a
        # near-miss of the atomicity violation, worth steering toward
        feats.add(
            (
                "ct",
                f"gang-{g.get('at')}",
                min(int(g.get("bound", 0)), 8),
                min(int(g.get("present", 0)), 8),
            )
        )
    return frozenset(feats)


# ----------------------------------------------------------------- groups


def schedule_groups(spec: dict) -> List[dict]:
    """Partition a schedule spec into fault groups that only make sense
    together: each group is ``{"scheduled": [idx...], "windows":
    [idx...]}``.  Pairing rules mirror construction
    (``FaultTimeline.__init__`` / ``add_region_move``): leader-kill
    with the next restart on the same seat, pause with the next resume
    on the same seat, pressure-start with the next pressure-end of the
    same mode, a tenant-region-move with its partition window; crashes,
    disk corruptions and plain windows stand alone.  Mutators shift /
    retarget / duplicate / drop whole groups, and the minimizer's unit
    of deletion is one group — dropping half a pair would change the
    fault's meaning, not remove it."""
    sched = spec.get("scheduled") or []
    wins = spec.get("windows") or []
    claimed_s: Set[int] = set()
    claimed_w: Set[int] = set()
    groups: List[dict] = []

    def _pair(i: int, kind: str, match: Callable[[dict], bool]) -> List[int]:
        for j in range(len(sched)):
            if (
                j not in claimed_s
                and j != i
                and sched[j]["kind"] == kind
                and sched[j]["t"] >= sched[i]["t"]
                and match(sched[j].get("params") or {})
            ):
                return [i, j]
        return [i]

    for i, s in enumerate(sched):
        if i in claimed_s:
            continue
        params = s.get("params") or {}
        kind = s["kind"]
        if kind == "leader-kill":
            idxs = _pair(i, "restart", lambda p: p.get("seat") == params.get("seat"))
        elif kind == "pause":
            idxs = _pair(i, "resume", lambda p: p.get("seat") == params.get("seat"))
        elif kind == "pressure-start":
            idxs = _pair(
                i, "pressure-end", lambda p: p.get("mode") == params.get("mode")
            )
        else:
            idxs = [i]
        claimed_s.update(idxs)
        widxs: List[int] = []
        if kind == "tenant-region-move":
            for k, w in enumerate(wins):
                if (
                    k not in claimed_w
                    and w.get("target") == params.get("client")
                    and abs(w.get("at", -1) - s["t"]) < 1e-9
                ):
                    widxs = [k]
                    claimed_w.add(k)
                    break
        groups.append({"scheduled": idxs, "windows": widxs})
    for k in range(len(wins)):
        if k not in claimed_w:
            groups.append({"scheduled": [], "windows": [k]})
    return groups


def _drop_group(spec: dict, group: dict) -> dict:
    out = copy.deepcopy(spec)
    out["scheduled"] = [
        s
        for i, s in enumerate(out.get("scheduled") or [])
        if i not in set(group["scheduled"])
    ]
    out["windows"] = [
        w
        for i, w in enumerate(out.get("windows") or [])
        if i not in set(group["windows"])
    ]
    return out


# --------------------------------------------------------------- mutators


def _clamp_t(spec: dict, t: float) -> float:
    lo, hi = spec.get("ack_window") or (t, t)
    return min(max(t, lo), hi)


def _mut_shift(spec: dict, rng: random.Random, ctx: dict) -> dict:
    """Shift one fault group's virtual instant, preserving the group's
    internal spacing (a pause keeps its duration, a pressure window its
    width)."""
    out = copy.deepcopy(spec)
    groups = schedule_groups(out)
    if not groups:
        return out
    g = groups[rng.randrange(len(groups))]
    delta = rng.uniform(-4.0, 4.0)
    for i in g["scheduled"]:
        out["scheduled"][i]["t"] = _clamp_t(out, out["scheduled"][i]["t"] + delta)
    for i in g["windows"]:
        out["windows"][i]["at"] = _clamp_t(out, out["windows"][i]["at"] + delta)
    return out


def _mut_retarget(spec: dict, rng: random.Random, ctx: dict) -> dict:
    """Re-aim one fault group: another seat for kills/pauses, another
    replica client for partitions, an explicit shard for disk faults,
    another tenant for region moves — and for crashes, a fresh
    phase/skip draw (the commit-window targeting knob)."""
    out = copy.deepcopy(spec)
    groups = schedule_groups(out)
    if not groups:
        return out
    g = groups[rng.randrange(len(groups))]
    seats: List[str] = ctx["seats"]
    clients: List[str] = ctx["replica_clients"]
    for i in g["scheduled"]:
        s = out["scheduled"][i]
        p = s.setdefault("params", {})
        if "seat" in p and seats:
            p["seat"] = seats[rng.randrange(len(seats))]
        if s["kind"] == "crash":
            p["phase"] = rng.choice(["before-commit", "after-commit"])
            p["skip"] = rng.randint(0, 8)
        if s["kind"] == "disk-corrupt":
            p["mode"] = rng.choice(["bit-flip", "truncate"])
            if ctx["n_shards"] > 1:
                p["shard"] = rng.randrange(ctx["n_shards"])
        if s["kind"] == "pressure-start" and ctx["n_shards"] > 1:
            p["shard"] = rng.randrange(ctx["n_shards"])
        if s["kind"] == "tenant-region-move" and ctx["fleet_ids"]:
            tid = ctx["fleet_ids"][rng.randrange(len(ctx["fleet_ids"]))]
            old = p.get("client")
            p["client"] = f"tenant:{tid}"
            for w in out.get("windows") or []:
                if w.get("target") == old:
                    w["target"] = p["client"]
    for i in g["windows"]:
        w = out["windows"][i]
        if w.get("kind") == "partition" and not str(
            w.get("target", "")
        ).startswith("tenant:") and clients:
            w["target"] = clients[rng.randrange(len(clients))]
    return out


def _mut_duplicate(spec: dict, rng: random.Random, ctx: dict) -> dict:
    """Copy one fault group to a shifted instant so the original and
    the copy overlap — two crashes bracketing one commit burst, nested
    pressure windows, back-to-back partitions."""
    out = copy.deepcopy(spec)
    groups = schedule_groups(out)
    if not groups:
        return out
    g = groups[rng.randrange(len(groups))]
    delta = rng.uniform(0.5, 6.0) * (1 if rng.random() < 0.5 else -1)
    for i in g["scheduled"]:
        s = copy.deepcopy(out["scheduled"][i])
        s["t"] = _clamp_t(out, s["t"] + delta)
        out["scheduled"].append(s)
    for i in g["windows"]:
        w = copy.deepcopy(out["windows"][i])
        w["at"] = _clamp_t(out, w["at"] + delta)
        out["windows"].append(w)
    return out


def _mut_drop(spec: dict, rng: random.Random, ctx: dict) -> dict:
    """Remove one fault group (never the last one) — less noise around
    whatever feature the schedule lights."""
    groups = schedule_groups(spec)
    if len(groups) <= 1:
        return copy.deepcopy(spec)
    return _drop_group(spec, groups[rng.randrange(len(groups))])


_MUTATORS: List[Tuple[str, Callable]] = [
    ("shift", _mut_shift),
    ("retarget", _mut_retarget),
    ("duplicate", _mut_duplicate),
    ("drop", _mut_drop),
]


def _splice(a: dict, b: dict, rng: random.Random) -> dict:
    """Coin-flip merge of two corpus schedules' fault groups (keeps
    ``a``'s envelope).  The crossover operator: a crash placement that
    lights gang features joined with a pressure window from another
    lineage."""
    out = copy.deepcopy(a)
    out["scheduled"] = []
    out["windows"] = []
    took = 0
    for src in (a, b):
        for g in schedule_groups(src):
            if rng.random() < 0.5:
                for i in g["scheduled"]:
                    out["scheduled"].append(copy.deepcopy(src["scheduled"][i]))
                for i in g["windows"]:
                    out["windows"].append(copy.deepcopy(src["windows"][i]))
                took += 1
    if not took:  # degenerate flip — keep a verbatim
        out["scheduled"] = copy.deepcopy(a.get("scheduled") or [])
        out["windows"] = copy.deepcopy(a.get("windows") or [])
    return out


def spec_digest(seed: int, spec: dict) -> str:
    """Canonical digest of one executed candidate — the determinism
    test compares the full sequence of these across two searches."""
    body = json.dumps({"seed": seed, "spec": spec}, sort_keys=True)
    return hashlib.sha256(body.encode("utf-8")).hexdigest()


# ----------------------------------------------------------------- search


@dataclasses.dataclass
class SearchResult:
    """Outcome of one :func:`guided_search` run."""

    executed: int
    corpus_size: int
    features: int
    #: digest of every executed (seed, spec), in order — the replayable
    #: fingerprint of the whole search
    schedule_digests: List[str]
    #: None, or the violating candidate
    found: Optional[dict] = None
    #: schedules executed when the violation surfaced (1-based)
    time_to_find: Optional[int] = None
    minimized: Optional[dict] = None
    #: extra runs the minimizer spent (not counted against the budget)
    minimize_trials: int = 0

    def stats(self) -> dict:
        out = {
            "schedules": self.executed,
            "corpus": self.corpus_size,
            "features": self.features,
            "time_to_find": self.time_to_find,
            "minimize_trials": self.minimize_trials,
        }
        if self.found is not None:
            out["violations"] = sorted(self.found["violations"])
            out["minimized_groups"] = (
                len(schedule_groups(self.minimized["schedule"]))
                if self.minimized
                else None
            )
        return out


def _mutation_ctx(opts: SimOptions) -> dict:
    from kwok_tpu.dst.harness import SEATS

    fleet_ids: List[str] = []
    if opts.fleet_tenants > 0:
        from kwok_tpu.fleet.tenant import fleet_tenant_ids

        fleet_ids = fleet_tenant_ids(opts.fleet_tenants)
    return {
        "seats": [s for s, _ in SEATS],
        "replica_clients": [
            f"{seat}-{i}" for seat, _ in SEATS for i in range(opts.replicas)
        ],
        "n_shards": opts.store_shards,
        "fleet_ids": fleet_ids,
    }


def _execute(seed: int, opts: SimOptions, spec: dict):
    o = dataclasses.replace(opts, seed=seed, schedule=spec)
    return run_record(seed, o)


def guided_search(
    opts: SimOptions,
    budget: int,
    search_seed: int = 0,
    minimize_found: bool = True,
    log: Optional[Callable[[str], None]] = None,
) -> SearchResult:
    """Run the coverage-guided loop for at most ``budget`` schedule
    executions; stop at the first invariant violation.

    Deterministic by construction: one rng seeded from ``search_seed``
    drives every pick and mutation, fresh corpus entries come from
    consecutive run seeds, and each execution is a pure function of its
    (seed, spec) — same arguments, byte-identical search."""
    rng = random.Random((search_seed << 1) ^ 0x6A1DED)
    seen: Set[Tuple] = set()
    corpus: List[dict] = []
    digests: List[str] = []
    executed = 0
    next_fresh = 0
    found: Optional[dict] = None

    def _run_candidate(seed: int, spec: dict, origin: str):
        nonlocal executed, found
        rec, violations = _execute(seed, opts, spec)
        executed += 1
        digests.append(spec_digest(seed, spec))
        if violations:
            found = {
                "seed": seed,
                "schedule": spec,
                "violations": dict(violations),
                "trace_digest": rec.trace.digest(),
                "origin": origin,
            }
            return
        feats = extract_features(rec)
        novel = feats - seen
        if novel:
            seen.update(novel)
            corpus.append({"seed": seed, "spec": spec})
            if log:
                log(
                    f"[search] +corpus #{len(corpus)} ({origin}, "
                    f"{len(novel)} new features, {executed}/{budget})"
                )

    while executed < budget and found is None:
        fresh = (
            next_fresh < INIT_FRESH
            or not corpus
            or rng.random() < FRESH_P
        )
        if fresh:
            seed = next_fresh
            next_fresh += 1
            _run_candidate(seed, seeded_schedule_spec(seed, opts), "seed")
            continue
        ctx = _mutation_ctx(opts)
        # recency-biased parent pick: newest entries carry the newest
        # features, but the whole corpus stays reachable
        idx = max(rng.randrange(len(corpus)), rng.randrange(len(corpus)))
        parent = corpus[idx]
        if len(corpus) >= 2 and rng.random() < 0.2:
            other = corpus[rng.randrange(len(corpus))]
            spec = _splice(parent["spec"], other["spec"], rng)
            origin = "splice"
        else:
            spec = parent["spec"]
            ops = []
            for _ in range(rng.randint(1, 2)):
                name, fn = _MUTATORS[rng.randrange(len(_MUTATORS))]
                spec = fn(spec, rng, ctx)
                ops.append(name)
            origin = "+".join(ops)
        _run_candidate(parent["seed"], spec, origin)

    result = SearchResult(
        executed=executed,
        corpus_size=len(corpus),
        features=len(seen),
        schedule_digests=digests,
        found=found,
        time_to_find=executed if found is not None else None,
    )
    if found is not None and minimize_found:
        minimized, trials = minimize(
            opts,
            found["seed"],
            found["schedule"],
            set(found["violations"]),
            log=log,
        )
        rec, violations = _execute(found["seed"], opts, minimized)
        result.minimized = {
            "schedule": minimized,
            "violations": dict(violations),
            "trace_digest": rec.trace.digest(),
        }
        result.minimize_trials = trials + 1
    return result


# --------------------------------------------------------------- minimizer


def minimize(
    opts: SimOptions,
    seed: int,
    spec: dict,
    target: Set[str],
    log: Optional[Callable[[str], None]] = None,
) -> Tuple[dict, int]:
    """Greedy delta-debugging over fault groups: repeatedly try
    dropping one group; keep the drop when the run still raises every
    invariant in ``target``.  Deterministic (no rng — groups are tried
    last-first so earlier indices stay stable within a pass) and
    terminates at a 1-minimal schedule: no single remaining group can
    be removed without losing the violation."""
    cur = spec
    trials = 0
    changed = True
    while changed:
        changed = False
        groups = schedule_groups(cur)
        for gi in range(len(groups) - 1, -1, -1):
            cand = _drop_group(cur, groups[gi])
            _, violations = _execute(seed, opts, cand)
            trials += 1
            if target <= set(violations):
                cur = cand
                changed = True
                if log:
                    log(
                        f"[minimize] dropped group {gi} "
                        f"({len(schedule_groups(cur))} left, trial {trials})"
                    )
                break
    return cur, trials


# ---------------------------------------------------------------- artifact


def violation_artifact(opts: SimOptions, found: dict, minimized: dict) -> dict:
    """The regression-pinning format ``--dst-replay`` consumes: the
    minimal violating schedule plus everything needed to re-execute it
    byte-identically and verify the outcome."""
    return {
        "version": 1,
        "seed": found["seed"],
        "opts": {k: getattr(opts, k) for k in ARTIFACT_OPTS},
        "schedule": minimized["schedule"],
        "expect": {
            "trace_digest": minimized["trace_digest"],
            "violations": sorted(minimized["violations"]),
        },
    }


def replay_artifact(doc: dict) -> dict:
    """Re-execute a violation artifact and verify byte-identity: the
    replayed trace digest must equal the recorded one and the same
    invariants must fire.  Returns ``{"ok", "digest_match",
    "violations_match", "trace_digest", "violations"}``."""
    opts = SimOptions(seed=int(doc["seed"]), **dict(doc.get("opts") or {}))
    opts = dataclasses.replace(opts, schedule=doc["schedule"])
    rec, violations = run_record(opts.seed, opts)
    expect = doc.get("expect") or {}
    digest = rec.trace.digest()
    digest_match = digest == expect.get("trace_digest")
    violations_match = sorted(violations) == list(expect.get("violations") or [])
    return {
        "ok": digest_match and violations_match,
        "digest_match": digest_match,
        "violations_match": violations_match,
        "trace_digest": digest,
        "violations": sorted(violations),
    }
