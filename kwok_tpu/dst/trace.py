"""Canonical run trace for deterministic-simulation runs.

Every observable decision of a simulation — writes crossing the
per-actor store boundary, leadership changes, injected faults, crash
recoveries — lands here as one ordered line, and the sha256 of the
canonical rendering is the run's identity: same seed ⇒ byte-identical
trace ⇒ equal digest (the reproducibility contract ROADMAP.md:101-115
assigns the DST harness; the audit-log precedent is
``kwok_tpu/cluster/store.py:575`` — this trace is its cross-component,
crash-surviving twin, kept on the harness side so a simulated process
death cannot lose it).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List


@dataclass(frozen=True)
class TraceEvent:
    """One trace line: virtual time, acting component, what happened."""

    t: float
    actor: str
    action: str
    detail: str = ""

    def render(self) -> str:
        return f"{self.t:.6f} {self.actor} {self.action} {self.detail}"


class Trace:
    """Append-only event list with a canonical digest."""

    def __init__(self) -> None:
        self.events: List[TraceEvent] = []

    def add(self, t: float, actor: str, action: str, detail: str = "") -> None:
        self.events.append(TraceEvent(t=t, actor=actor, action=action, detail=detail))

    def lines(self) -> List[str]:
        return [ev.render() for ev in self.events]

    def digest(self) -> str:
        h = hashlib.sha256()
        for line in self.lines():
            h.update(line.encode("utf-8"))
            h.update(b"\n")
        return h.hexdigest()

    def __len__(self) -> int:
        return len(self.events)
