"""Cross-row render plans: batched stage-patch materialization.

The host drain's per-row cost used to be one full gotpl render + YAML
parse per fired row (~1ms).  For a device-compilable stage set, a
stage's rendered patch depends only on:

- the row's *signature* (spec/labels/annotations equality class — the
  same key the compiler's effect tables use),
- the row's identity (metadata name/namespace/uid),
- env-func outputs (PodIP/NodeIP..., row-stable),
- ``Now`` (per tick), and
- the template-read projection (``CompiledStageSet._read_paths``).

So one render per (stage, sig) with *sentinel* values substituted for
identity/funcs/Now yields a reusable plan: per row, the patch is rebuilt
by replacing sentinel leaves — tens of dict nodes, not a render.  This
is the drain half of SURVEY §7's "render/merge JSON on host without
becoming the bottleneck"; the reference's per-object equivalent is the
template render in pkg/utils/lifecycle/next.go:73-88.

Soundness notes:

- Env funcs are treated as opaque row constants: a template that
  *branches* on a func's output (``{{ if eq PodIP ... }}``) would
  mis-plan.  The device compiler already makes the same assumption (its
  abstract exploration renders with fixed COMPILE_ENV_FUNCS), so the
  fast path inherits, not adds, the constraint.
- Plans are only used when the stage set has no template read paths
  (``cset._read_paths`` empty); otherwise rows fall back to the
  per-row path.  Identity reads (.metadata.name/namespace/uid) are
  handled via sentinels, and spec/labels/annotations reads are covered
  by the signature key.
- Sequential merge patches compose into one template by RFC 7386 patch
  composition; shapes where composition does not hold (scalar patched
  then dict-merged) are rejected to the slow path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from kwok_tpu.utils.patch import apply_merge_patch

#: sentinel token namespace — alphanumeric + dots so YAML keeps plain
#: scalars as strings and quoting never mangles them
_S = "zq9kws"
NOW_S = f"{_S}.now.z"
NAME_S = f"{_S}.nm.z"
NS_S = f"{_S}.ns.z"
UID_S = f"{_S}.uid.z"


def _func_token(i: int) -> str:
    return f"{_S}.f{i}.z"


class PatchPlan:
    """One patch of a stage, as a sentinel template (general form:
    any patch type/subresource — powers the slow path's render)."""

    __slots__ = ("compiled", "template", "type", "subresource", "impersonation")

    def __init__(self, template, ptype, subresource, impersonation):
        self.template = template
        self.type = ptype
        self.subresource = subresource
        self.impersonation = impersonation
        self.compiled = _compile_node(template)

    def build(self, vals: Dict[str, Any]) -> Any:
        if self.compiled is None:
            return self.template
        return build(self.compiled, vals)


class RenderPlan:
    """Compiled per-(stage, sig) patch builder.

    ``patch_plans`` is the general form (one sentinel template per
    stage patch — replaces the per-row gotpl render everywhere).  When
    the stage is *fast-eligible* (merge patches on status only, no
    delete/finalizers, composable), ``fast`` is True and ``template``
    holds the single merged status template for the columnar drain."""

    __slots__ = (
        "compiled",
        "template",
        "calls",
        "has_event",
        "has_null",
        "top_plain",
        "all_top_plain",
        "immediate",
        "fast",
        "patch_plans",
        "_tick_bound",
        "has_now",
    )

    def __init__(self, template, calls, has_event, immediate, fast, patch_plans):
        self._tick_bound = None
        #: template stamps Now somewhere -> a rebuilt patch can never be
        #: a no-op against a status written at an earlier tick (virtual
        #: timestamps strictly increase), so the drain skips the deep
        #: equality check for these plans
        self.has_now = _contains_token(template, NOW_S) if template is not None else False
        self.template = template  # merged status-patch template (sentinels)
        self.calls: List[Tuple[str, Tuple]] = calls  # (func name, args)
        self.has_event = has_event
        self.has_null = _has_null(template) if template is not None else False
        #: top-level keys whose template values are non-dict (replace
        #: wholesale under merge-patch) — lets build() skip the merge
        #: when the current status has no other keys
        self.top_plain = (
            {
                k
                for k, v in template.items()
                if not isinstance(v, dict) and v is not None
            }
            if template is not None
            else set()
        )
        #: every template key replaces wholesale -> the merge collapses
        #: to a top-level dict update
        self.all_top_plain = (
            template is not None and len(self.top_plain) == len(template)
        )
        self.compiled = _compile_node(template) if template is not None else None
        self.immediate = immediate
        self.fast = fast
        self.patch_plans: List[PatchPlan] = patch_plans

    def _vals(self, obj: dict, now_s: str, funcs: Dict[str, Callable]) -> Dict[str, Any]:
        meta = obj.get("metadata") or {}
        vals: Dict[str, Any] = {
            NOW_S: now_s,
            NAME_S: meta.get("name") or "",
            NS_S: meta.get("namespace") or "",
            UID_S: meta.get("uid") or "",
        }
        for i, (fname, args) in enumerate(self.calls):
            f = funcs.get(fname)
            if f is None:
                raise KeyError(f"env func {fname} missing")
            rargs = [_resolve_arg(a, vals) for a in args]
            vals[_func_token(i)] = f(*rargs)
        return vals

    def bind_tick(self, now_s: str):
        """Substitute the tick-constant Now once; returns (bound
        template, row_compiled) where only row-dependent tokens remain.
        row_compiled None means the bound template is fully static —
        shared by every row this tick (heartbeat-style patches).  Cached
        per now_s (one bind per plan per tick)."""
        tb = self._tick_bound
        if tb is None or tb[0] != now_s:
            if self.compiled is None:
                bound, comp = self.template, None
            else:
                bound = _bind(self.compiled, {NOW_S: now_s})
                comp = _compile_node(bound)
            tb = self._tick_bound = (now_s, bound, comp)
        return tb[1], tb[2]

    def row_vals(self, obj: dict, funcs: Dict[str, Callable]) -> Dict[str, Any]:
        """Per-row substitution values (identity + env-func results —
        no Now; bind_tick already resolved it)."""
        meta = obj.get("metadata") or {}
        vals: Dict[str, Any] = {
            NAME_S: meta.get("name") or "",
            NS_S: meta.get("namespace") or "",
            UID_S: meta.get("uid") or "",
        }
        for i, (fname, args) in enumerate(self.calls):
            f = funcs.get(fname)
            if f is None:
                raise KeyError(f"env func {fname} missing")
            rargs = [_resolve_arg(a, vals) for a in args]
            vals[_func_token(i)] = f(*rargs)
        return vals

    def build_patch(self, obj: dict, now_s: str, funcs: Dict[str, Callable]) -> Any:
        """Materialize this row's merged status patch (fast form)."""
        bound, comp = self.bind_tick(now_s)
        if comp is None:
            return bound
        return build(comp, self.row_vals(obj, funcs))

    def build_patches(self, obj: dict, now_s: str, funcs: Dict[str, Callable]):
        """Materialize the stage's patches as lifecycle.Patch objects
        (general form, used by the per-row slow path in place of a
        full gotpl render)."""
        from kwok_tpu.engine.lifecycle import Patch

        vals = self._vals(obj, now_s, funcs)
        return [
            Patch(
                data=pp.build(vals),
                type=pp.type,
                subresource=pp.subresource,
                impersonation=pp.impersonation,
            )
            for pp in self.patch_plans
        ]

    def new_status(self, cur_status: dict, patch: Any) -> dict:
        """Merge the built patch onto the row's current status, skipping
        the recursive merge when every patch key replaces wholesale
        (the steady-churn common case)."""
        if not self.has_null and self.all_top_plain:
            if all(k in self.top_plain for k in cur_status):
                return patch
            out = dict(cur_status)
            out.update(patch)
            return out
        return apply_merge_patch(cur_status, patch)


def _resolve_arg(a: Any, vals: Dict[str, Any]) -> Any:
    if isinstance(a, str) and _S in a:
        return _sub_str(a, vals)
    return a


_TOK_RE = __import__("re").compile(r"zq9kws\.[a-z0-9]+\.z")


def _sub_str(leaf: str, vals: Dict[str, Any]) -> Any:
    """Substitute sentinel tokens in an arbitrary string (func args)."""
    if leaf in vals:
        return vals[leaf]
    for tok in _TOK_RE.findall(leaf):
        leaf = leaf.replace(tok, str(vals.get(tok, tok)))
    return leaf


def _compile_node(node: Any):
    """Pre-walk the template: returns None for sentinel-free (static,
    shareable) subtrees, else a builder spec.  String leaves precompute
    their token list; a leaf that is exactly one token keeps the
    substituted value's type (NodePort stays an int)."""
    if isinstance(node, dict):
        items = []
        for k, v in node.items():
            c = _compile_node(v)
            if c is not None:
                items.append((k, c))
        return ("d", node, items) if items else None
    if isinstance(node, list):
        items = []
        for i, v in enumerate(node):
            c = _compile_node(v)
            if c is not None:
                items.append((i, c))
        return ("l", node, items) if items else None
    if isinstance(node, str) and _S in node:
        toks = _TOK_RE.findall(node)
        if len(toks) == 1 and toks[0] == node:
            return ("x", node, None)  # exact: typed substitution
        return ("s", node, toks)
    return None


def _bind(comp, vals: Dict[str, Any]) -> Any:
    """Like _build, but unknown tokens survive — produces a narrower
    template with only the still-unresolved (row-dependent) leaves."""
    kind, orig, items = comp
    if kind == "x":
        return vals.get(orig, orig)
    if kind == "s":
        for tok in items:
            v = vals.get(tok)
            if v is not None:
                orig = orig.replace(tok, str(v))
        return orig
    if kind == "d":
        out = dict(orig)
        for k, c in items:
            out[k] = _bind(c, vals)
        return out
    out = list(orig)
    for i, c in items:
        out[i] = _bind(c, vals)
    return out


def _build(comp, vals: Dict[str, Any]) -> Any:
    kind, orig, items = comp
    if kind == "x":
        return vals[orig]
    if kind == "s":
        for tok in items:
            orig = orig.replace(tok, str(vals[tok]))
        return orig
    if kind == "d":
        out = dict(orig)
        for k, c in items:
            out[k] = _build(c, vals)
        return out
    out = list(orig)
    for i, c in items:
        out[i] = _build(c, vals)
    return out


def _native_build():
    try:
        from kwok_tpu.native.fastdrain import load

        mod = load()
    except Exception:  # noqa: BLE001 — accelerator only
        return None
    return getattr(mod, "build", None) if mod is not None else None


#: preferred builder: the C extension when available — semantics pinned
#: equal to _build by tests/test_render_plan.py::test_c_python_builder_parity
build = _native_build() or _build


def _contains_token(node: Any, tok: str) -> bool:
    if isinstance(node, str):
        return tok in node
    if isinstance(node, dict):
        return any(_contains_token(v, tok) for v in node.values())
    if isinstance(node, list):
        return any(_contains_token(v, tok) for v in node)
    return False


def _has_null(node: Any) -> bool:
    """Does the template carry RFC 7386 delete markers?  Only nulls
    reachable through pure-dict paths count: a merge patch replaces
    list subtrees atomically, so a ``null`` inside a list (e.g. the
    conditions' ``lastProbeTime: null``) is a literal value."""
    if isinstance(node, dict):
        return any(v is None or _has_null(v) for v in node.values())
    return False


class _Incomposable(Exception):
    pass


def _merge_templates(a: Any, b: Any) -> Any:
    """RFC 7386 composition of two merge-patch *templates* such that
    apply(apply(x, a), b) == apply(x, merge(a, b)).  Raises when the
    law does not hold for the shapes involved."""
    if not isinstance(b, dict):
        return b
    if not isinstance(a, dict):
        # x.k was replaced by scalar a, then dict-merged by b: the
        # composed patch cannot express "clear then merge"
        raise _Incomposable()
    out = dict(a)
    for k, v in b.items():
        if v is None:
            out[k] = None
        elif k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _merge_templates(out[k], v)
        elif k in out and not isinstance(out[k], dict) and isinstance(v, dict):
            raise _Incomposable()
        else:
            out[k] = v
    return out


def compile_plan(lifecycle, cs, obj: dict, func_names) -> Optional[RenderPlan]:
    """Build a RenderPlan for (stage, representative object), or None
    when even the general (per-patch) form cannot be planned — i.e. a
    render that errors on sentinels.  ``plan.fast`` says whether the
    columnar status path applies; otherwise ``plan.build_patches``
    still replaces the slow path's per-row gotpl render."""
    effects = lifecycle.effects(cs)
    if effects is None:
        return RenderPlan({}, [], False, cs.immediate_next_stage, True, [])
    nxt = effects.next

    meta = obj.get("metadata") or {}
    rep = dict(obj)
    rmeta = dict(meta)
    if rmeta.get("name"):
        rmeta["name"] = NAME_S
    if rmeta.get("namespace"):
        rmeta["namespace"] = NS_S
    if rmeta.get("uid"):
        rmeta["uid"] = UID_S
    rep["metadata"] = rmeta

    calls: List[Tuple[str, Tuple]] = []

    def mk(fname: str):
        def f(*args):
            key = (fname, tuple(args))
            try:
                i = calls.index(key)
            except ValueError:
                i = len(calls)
                calls.append(key)
            return _func_token(i)

        return f

    sfuncs: Dict[str, Callable] = {name: mk(name) for name in func_names}
    sfuncs["Now"] = lambda: NOW_S

    try:
        patches = effects.patches(rep, sfuncs)
    except Exception:  # noqa: BLE001 — template not plan-renderable
        return None

    patch_plans = [
        PatchPlan(p.data, p.type or "merge", p.subresource, p.impersonation)
        for p in patches
    ]

    fast = not nxt.delete and nxt.finalizers is None
    merged: Any = {}
    if fast:
        for p in patches:
            if (
                (p.type or "merge") != "merge"
                or p.subresource != "status"
                or p.impersonation
            ):
                fast = False
                break
            data = p.data
            if (
                not isinstance(data, dict)
                or set(data) != {"status"}
                or not isinstance(data["status"], dict)
            ):
                fast = False
                break
            try:
                merged = _merge_templates(merged, data["status"])
            except _Incomposable:
                fast = False
                break
    return RenderPlan(
        merged if fast else None,
        calls,
        nxt.event is not None,
        cs.immediate_next_stage,
        fast,
        patch_plans,
    )
