"""Stage-set compiler: Stage CRDs -> dense tensors for the device kernel.

This is the ahead-of-time counterpart of the reference's per-object
interpretation (reference: pkg/utils/lifecycle/lifecycle.go:194-267
NewStage, Match at lifecycle.go:125-191, Delay at lifecycle.go:313-341,
plus next.go:43-96 Patches). Three artifacts are produced:

1. **Predicates** — every selector becomes rows of (column, mask,
   negate) tests over the bitmask feature columns (features.py).
2. **Scalars** — static weights, delay/jitter milliseconds, delete
   flags, event ids, plus flags for the dynamic delay sources the zoo
   uses (deletionTimestamp deadlines). Per-object annotation overrides
   (weightFrom/durationFrom on `.metadata.annotations[...]`) become
   *override classes*: rows with identical annotation sets share a row
   in the override tables.
3. **Effects** — by *abstract FSM exploration*: for each distinct spec
   signature, a representative object is driven through the host
   lifecycle engine (the parity oracle); each (signature, stage)
   transition's rendered merge-patches are converted to feature-column
   SET/KEEP vectors via the merge-patch path-touch rule. Device
   transitions are therefore derived from the real host renderer, by
   construction.

Anything outside the compilable subset (jq expressions beyond kq,
non-merge patch types, weightFrom/durationFrom on non-annotation
non-deletionTimestamp sources, inconsistent effects across pre-states)
raises ``StageCompileError`` — the controller then routes that resource
class to the host slow path, mirroring how the reference keeps full
generality.

The fallback granularity is deliberately **per kind, not per stage**:
one exotic stage in a set demotes the whole kind to the host backend
(Controller._start_device_controller catches the error and returns
False).  Splitting a kind across backends would need two engines to
agree on weighted-choice PRNG streams and informer dedup for the same
rows — the parity cost outweighs the win, since stage sets are
per-kind artifacts anyway.  ``tests/test_device_backend.py::
test_exotic_stage_demotes_kind_to_host`` pins the behavior.
"""

from __future__ import annotations

import copy
import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from kwok_tpu.api.types import Stage
from kwok_tpu.engine.features import (
    ALL_MASK,
    CompiledCondition,
    FeatureSchema,
    compile_selector,
)
from kwok_tpu.engine.lifecycle import CompiledStage, Lifecycle, to_json_standard
from kwok_tpu.utils.expression import parse_go_duration, parse_rfc3339
from kwok_tpu.utils.kq import Query
from kwok_tpu.utils.patch import apply_patch

SENTINEL = -(2**31)  # "no value" in override tables
IDLE = -1  # no current stage
NEVER = 2**31 - 1  # fire_at for idle rows

MODE_KEEP = 0
MODE_SET = 1

DELETION_TS_EXPR = ".metadata.deletionTimestamp"

# Deterministic env funcs for compile-time template rendering: only the
# *existence* and vocabulary-membership of rendered values reach the
# feature columns, so fixed strings are exact. Now/StartTime are pinned
# so exploration states are render-deterministic (the BFS would never
# terminate on self-loop stages otherwise).
COMPILE_ENV_FUNCS = {
    "NodeIP": lambda: "10.0.0.1",
    "NodeName": lambda: "node",
    "NodePort": lambda: 10250,
    "PodIP": lambda: "10.64.0.1",
    "NodeIPWith": lambda name: "10.0.0.1",
    "PodIPWith": lambda *a: "10.64.0.1",
    "Now": lambda: "2026-01-01T00:00:00.000000Z",
    "StartTime": lambda: "2026-01-01T00:00:00.000000Z",
}

# Safety bound on per-signature exploration (pathological template-
# driven state growth raises StageCompileError instead of spinning).
MAX_EXPLORED_STATES = 4096


class StageCompileError(ValueError):
    """Stage set is outside the device-compilable subset."""


@dataclass
class StageScalars:
    weight: int
    weight_from_annotation: Optional[str]
    duration_ms: int
    duration_from_annotation: Optional[str]
    duration_from_deletion_ts: bool
    has_jitter: bool
    jitter_ms: int
    jitter_from_annotation: Optional[str]
    jitter_from_deletion_ts: bool
    delete: bool
    event_id: int
    immediate: bool


def _annotation_key_of(expr: Optional[str]) -> Tuple[Optional[str], bool, bool]:
    """Classify an expressionFrom source: returns (annotation_key,
    is_deletion_ts, ok)."""
    if expr is None:
        return None, False, True
    if expr == DELETION_TS_EXPR:
        return None, True, True
    # the zoo's override convention: .metadata.annotations["..."]
    prefix = '.metadata.annotations["'
    if expr.startswith(prefix) and expr.endswith('"]'):
        return expr[len(prefix) : -2], False, True
    return None, False, False


class CompiledStageSet:
    """Dense-tensor form of one stage set (one resourceRef)."""

    def __init__(self, stages: List[Stage], max_conditions: int = 8):
        try:
            self.lifecycle = Lifecycle(stages)
        except Exception as e:  # kq compile errors etc. -> host fallback
            raise StageCompileError(f"lifecycle compile failed: {e}") from e
        self.compiled: List[CompiledStage] = self.lifecycle.stages
        self.schema = FeatureSchema()
        self.num_stages = len(self.compiled)
        if self.num_stages == 0:
            raise StageCompileError("no compilable stages (all selector-less?)")

        # --- predicates -----------------------------------------------------
        raw_stages = [s.raw for s in self.compiled]
        conds_per_stage: List[List[CompiledCondition]] = []
        for st in raw_stages:
            try:
                conds_per_stage.append(compile_selector(self.schema, st))
            except Exception as e:
                raise StageCompileError(f"selector of {st.name!r}: {e}") from e
        K = max(max((len(c) for c in conds_per_stage), default=1), 1)
        if K > max_conditions:
            raise StageCompileError(f"too many conditions per stage: {K}")
        S = self.num_stages
        self.cond_col = np.zeros((S, K), np.int32)
        self.cond_mask = np.zeros((S, K), np.int32)
        self.cond_neg = np.zeros((S, K), np.bool_)
        self.cond_valid = np.zeros((S, K), np.bool_)
        for i, conds in enumerate(conds_per_stage):
            for j, c in enumerate(conds):
                self.cond_col[i, j] = c.col
                self.cond_mask[i, j] = np.int32(c.mask & 0xFFFFFFFF) if c.mask < 2**31 else np.int32(c.mask - 2**32)
                self.cond_neg[i, j] = c.negate
                self.cond_valid[i, j] = True

        # --- scalars ---------------------------------------------------------
        self.events: List[Any] = []  # StageEvent objects (see below)
        self.scalars: List[StageScalars] = []
        for cs in self.compiled:
            st = cs.raw
            w_ann, w_dts, ok = _annotation_key_of(
                st.weight_from.expression_from if st.weight_from else None
            )
            if not ok or w_dts:
                raise StageCompileError(f"{st.name}: weightFrom source not compilable")
            d_ann = j_ann = None
            d_dts = j_dts = False
            duration_ms = 0
            jitter_ms = 0
            has_jitter = False
            if st.delay is not None:
                d = st.delay
                duration_ms = d.duration_milliseconds or 0
                d_ann, d_dts, ok = _annotation_key_of(
                    d.duration_from.expression_from if d.duration_from else None
                )
                if not ok:
                    raise StageCompileError(
                        f"{st.name}: durationFrom source not compilable"
                    )
                if d.jitter_duration_milliseconds is not None or d.jitter_duration_from is not None:
                    has_jitter = True
                    jitter_ms = (
                        d.jitter_duration_milliseconds
                        if d.jitter_duration_milliseconds is not None
                        else SENTINEL
                    )
                    j_ann, j_dts, ok = _annotation_key_of(
                        d.jitter_duration_from.expression_from
                        if d.jitter_duration_from
                        else None
                    )
                    if not ok:
                        raise StageCompileError(
                            f"{st.name}: jitterDurationFrom source not compilable"
                        )
            nxt = st.next
            event_id = -1
            if nxt is not None and nxt.event is not None:
                event_id = len(self.events)
                # the StageEvent object itself (attribute access —
                # Transition.event consumers read .type/.reason/.message)
                self.events.append(nxt.event)
            if nxt is not None:
                for p in nxt.patches:
                    if (p.type or "merge") != "merge":
                        raise StageCompileError(
                            f"{st.name}: patch type {p.type!r} not device-compilable"
                        )
            self.scalars.append(
                StageScalars(
                    weight=st.weight,
                    weight_from_annotation=w_ann,
                    duration_ms=duration_ms,
                    duration_from_annotation=d_ann,
                    duration_from_deletion_ts=d_dts,
                    has_jitter=has_jitter,
                    jitter_ms=jitter_ms,
                    jitter_from_annotation=j_ann,
                    jitter_from_deletion_ts=j_dts,
                    delete=bool(nxt.delete) if nxt else False,
                    event_id=event_id,
                    immediate=st.immediate_next_stage,
                )
            )

        self.w_static = np.array([s.weight for s in self.scalars], np.int32)
        self.d_static = np.array([s.duration_ms for s in self.scalars], np.int32)
        self.j_static = np.array(
            [s.jitter_ms if s.has_jitter else SENTINEL for s in self.scalars], np.int32
        )
        self.has_jitter = np.array([s.has_jitter for s in self.scalars], np.bool_)
        self.d_from_del_ts = np.array(
            [s.duration_from_deletion_ts for s in self.scalars], np.bool_
        )
        self.j_from_del_ts = np.array(
            [s.jitter_from_deletion_ts for s in self.scalars], np.bool_
        )
        self.stage_delete = np.array([s.delete for s in self.scalars], np.bool_)
        self.stage_event = np.array([s.event_id for s in self.scalars], np.int32)
        # consumed by the cluster/controller layer, not the tick kernel:
        # on-device rematch is always immediate; non-immediate stages wait
        # for the store round-trip before external visibility.
        self.stage_immediate = np.array([s.immediate for s in self.scalars], np.bool_)

        # --- signatures / effects / override classes -------------------------
        self.C = self.schema.num_columns
        self._sig_ids: Dict[str, int] = {}
        self._sig_effects: List[np.ndarray] = []  # per sig: [S, C] mode
        self._sig_effect_vals: List[np.ndarray] = []  # per sig: [S, C] val
        self._sig_effect_known: List[np.ndarray] = []  # per sig: [S] bool
        # column-wise effect-merge evidence across explored pre-states
        # (a stage lowers iff every column is keep-consistent OR
        # set-consistent — e.g. "add finalizer" is keep from a state
        # that already has it and set(1) from one that doesn't, which
        # merges to set(1)):
        self._sig_keep_ok: List[np.ndarray] = []  # per sig: [S, C] bool
        self._sig_set_ok: List[np.ndarray] = []  # per sig: [S, C] bool
        self._sig_set_val: List[np.ndarray] = []  # per sig: [S, C] int32
        self._ov_ids: Dict[str, int] = {}
        self._ov_rows: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        # per-sig set of exploration-state keys already explored (BFS cache)
        self._explored: Dict[int, set] = {}

        # Template-read analysis: the object paths stage templates read
        # beyond spec/labels/annotations (which are in the signature key).
        # Exploration states are keyed on (features, projection of these
        # paths), so objects that would render differently explore
        # separately and pre-state-dependent effects are detected.
        # metadata name/namespace/uid are excluded: they only feed env
        # funcs (NodeIPWith/PodIPWith) whose values never reach feature
        # columns — selectors on IP *values* are outside the subset.
        self._read_paths: List[Tuple[str, ...]] = []
        seen_paths = set()
        from kwok_tpu.utils.gotpl import Template, template_read_paths

        for cs in self.compiled:
            if cs.next is None:
                continue
            for p in cs.next.patches:
                for path in template_read_paths(Template(p.template)):
                    if not path or path[0] not in ("status", "metadata"):
                        continue
                    if path[:2] in (
                        ("metadata", "name"),
                        ("metadata", "namespace"),
                        ("metadata", "uid"),
                        ("metadata", "labels"),
                        ("metadata", "annotations"),
                    ):
                        continue
                    if path not in seen_paths:
                        seen_paths.add(path)
                        self._read_paths.append(path)
        # bumped whenever signatures/effects/override classes grow, so the
        # simulator knows to re-upload TickParams
        self.version = 0

    # -- signature handling ----------------------------------------------------

    def _signature_key(self, obj: dict) -> str:
        meta = obj.get("metadata") or {}
        key = {
            "spec": obj.get("spec"),
            "labels": meta.get("labels"),
            "annotations": meta.get("annotations"),
            "ownerReferences": meta.get("ownerReferences"),
        }
        return hashlib.sha1(
            json.dumps(key, sort_keys=True, default=str).encode()
        ).hexdigest()

    def signature_for(self, obj: dict) -> int:
        """Signature id for an object, exploring its FSM on first sight."""
        obj = to_json_standard(obj)
        key = self._signature_key(obj)
        sig = self._sig_ids.get(key)
        if sig is None:
            sig = len(self._sig_effects)
            self._sig_ids[key] = sig
            self._sig_effects.append(np.zeros((self.num_stages, self.C), np.int32))
            self._sig_effect_vals.append(np.zeros((self.num_stages, self.C), np.int32))
            self._sig_effect_known.append(np.zeros(self.num_stages, np.bool_))
            self._sig_keep_ok.append(np.ones((self.num_stages, self.C), np.bool_))
            self._sig_set_ok.append(np.ones((self.num_stages, self.C), np.bool_))
            self._sig_set_val.append(np.zeros((self.num_stages, self.C), np.int32))
            self.version += 1
        self._explore(sig, obj)
        return sig

    def override_class_for(self, obj: dict) -> int:
        """Override-class id: rows sharing annotation-derived weight/delay
        overrides share a row in the override tables."""
        meta = obj.get("metadata") or {}
        ann = meta.get("annotations") or {}
        S = self.num_stages
        w = np.full(S, SENTINEL, np.int32)
        d = np.full(S, SENTINEL, np.int32)
        j = np.full(S, SENTINEL, np.int32)
        for i, sc in enumerate(self.scalars):
            if sc.weight_from_annotation and sc.weight_from_annotation in ann:
                v = _parse_int(ann[sc.weight_from_annotation])
                if v is not None:
                    w[i] = v
            if sc.duration_from_annotation and sc.duration_from_annotation in ann:
                ms = _parse_duration_ms(ann[sc.duration_from_annotation])
                if ms is not None:
                    d[i] = ms
            if sc.jitter_from_annotation and sc.jitter_from_annotation in ann:
                ms = _parse_duration_ms(ann[sc.jitter_from_annotation])
                if ms is not None:
                    j[i] = ms
        key = (w.tobytes(), d.tobytes(), j.tobytes())
        skey = hashlib.sha1(b"|".join(key)).hexdigest()
        ovc = self._ov_ids.get(skey)
        if ovc is None:
            ovc = len(self._ov_rows)
            self._ov_ids[skey] = ovc
            self._ov_rows.append((w, d, j))
            self.version += 1
        return ovc

    # -- abstract FSM exploration -----------------------------------------------

    def state_projection(self, obj: dict) -> str:
        """Hash of the template-read path values (see _read_paths)."""
        if not self._read_paths:
            return ""
        proj = []
        for path in self._read_paths:
            cur: Any = obj
            for seg in path:
                if isinstance(cur, dict):
                    cur = cur.get(seg)
                else:
                    cur = None
                    break
            proj.append(cur)
        return hashlib.sha1(
            json.dumps(proj, sort_keys=True, default=str).encode()
        ).hexdigest()

    def _state_key(self, obj: dict) -> Tuple:
        return (
            tuple(self.schema.extract_row(obj)),
            self.state_projection(obj),
        )

    def _explore(self, sig: int, start_obj: dict) -> None:
        """BFS over FSM states reachable from start_obj, recording each
        (stage -> feature effect) discovered along the way. States are
        keyed on (feature row, template-read projection): objects whose
        templates would render differently explore separately, and the
        per-(sig, stage) consistency assertion turns pre-state-dependent
        effects into StageCompileError. The seen-set is cached per
        signature, so admitting many identical objects explores once."""
        seen = self._explored.setdefault(sig, set())
        if self._state_key(start_obj) in seen:
            return
        worklist = [copy.deepcopy(start_obj)]
        while worklist:
            obj = worklist.pop()
            fkey = self._state_key(obj)
            if fkey in seen:
                continue
            if len(seen) >= MAX_EXPLORED_STATES:
                raise StageCompileError(
                    "FSM exploration exceeded "
                    f"{MAX_EXPLORED_STATES} states; stage set not "
                    "device-compilable"
                )
            seen.add(fkey)
            meta = obj.get("metadata") or {}
            matched = self.lifecycle.match(
                meta.get("labels") or {}, meta.get("annotations") or {}, obj
            )
            pre_row = np.array(self.schema.extract_row(obj), np.int32)
            for cs in matched:
                idx = self.compiled.index(cs)
                new_obj, mode, val, deleted = self._apply_stage(obj, cs)
                post = np.where(mode == MODE_SET, val, pre_row)
                known = self._sig_effect_known[sig]
                keep_ok = self._sig_keep_ok[sig][idx]
                set_ok = self._sig_set_ok[sig][idx]
                if not known[idx]:
                    keep_ok[:] = post == pre_row
                    set_ok[:] = True
                    self._sig_set_val[sig][idx] = post
                    known[idx] = True
                else:
                    keep_ok &= post == pre_row
                    set_ok &= post == self._sig_set_val[sig][idx]
                    if not np.all(keep_ok | set_ok):
                        bad = [
                            self.schema.columns[c].key
                            for c in np.nonzero(~(keep_ok | set_ok))[0]
                        ]
                        raise StageCompileError(
                            f"stage {cs.name!r}: effect depends on pre-state "
                            f"(columns {bad}); not device-compilable"
                        )
                # lowering: keep where keep-consistent, else set to the
                # (proven-common) post value
                new_mode = np.where(keep_ok, MODE_KEEP, MODE_SET).astype(np.int32)
                new_val = np.where(
                    new_mode == MODE_SET, self._sig_set_val[sig][idx], 0
                ).astype(np.int32)
                if not (
                    np.array_equal(new_mode, self._sig_effects[sig][idx])
                    and np.array_equal(new_val, self._sig_effect_vals[sig][idx])
                ):
                    self._sig_effects[sig][idx] = new_mode
                    self._sig_effect_vals[sig][idx] = new_val
                    self.version += 1
                if not deleted:
                    worklist.append(new_obj)

    def _apply_stage(self, obj: dict, cs: CompiledStage):
        """Host-render one stage against obj; return (new_obj, mode[C],
        val[C], deleted)."""
        obj = copy.deepcopy(obj)
        effects = self.lifecycle.effects(cs)
        touched_prefixes: List[Tuple[str, ...]] = []
        if effects is None:
            return obj, np.zeros(self.C, np.int32), np.zeros(self.C, np.int32), False

        meta = obj.get("metadata") or {}
        fin = effects.finalizers_patch(meta.get("finalizers") or [])
        if fin is not None:
            obj = apply_patch(obj, fin.data, fin.type)
            touched_prefixes.append(("metadata", "finalizers"))

        if effects.delete:
            mode = np.zeros(self.C, np.int32)
            val = np.zeros(self.C, np.int32)
            return obj, mode, val, True

        for p in effects.patches(obj, COMPILE_ENV_FUNCS):
            obj = apply_patch(obj, p.data, p.type)
            touched_prefixes.extend(_patch_prefix_paths(p.data))

        mode = np.zeros(self.C, np.int32)
        val = np.zeros(self.C, np.int32)
        new_row = self.schema.extract_row(obj)
        for ci, col in enumerate(self.schema.columns):
            if _is_touched(col.path_prefix, touched_prefixes):
                mode[ci] = MODE_SET
                val[ci] = new_row[ci]
        return obj, mode, val, False

    # -- dense tables -----------------------------------------------------------

    def effect_tables(self) -> Tuple[np.ndarray, np.ndarray]:
        """Stacked effect tensors [SIG, S, C] (mode, value)."""
        if not self._sig_effects:
            return (
                np.zeros((1, self.num_stages, self.C), np.int32),
                np.zeros((1, self.num_stages, self.C), np.int32),
            )
        return np.stack(self._sig_effects), np.stack(self._sig_effect_vals)

    def override_tables(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Stacked override tensors [OVC, S] (weight, duration, jitter)."""
        if not self._ov_rows:
            z = np.full((1, self.num_stages), SENTINEL, np.int32)
            return z, z.copy(), z.copy()
        w = np.stack([r[0] for r in self._ov_rows])
        d = np.stack([r[1] for r in self._ov_rows])
        j = np.stack([r[2] for r in self._ov_rows])
        return w, d, j

    def extract_features(self, obj: dict) -> np.ndarray:
        return np.array(self.schema.extract_row(to_json_standard(obj)), np.int32)

    def deletion_ts_ms(self, obj: dict, epoch) -> int:
        """deletionTimestamp as virtual ms (SENTINEL when absent)."""
        meta = obj.get("metadata") or {}
        ts = meta.get("deletionTimestamp")
        if not ts:
            return SENTINEL
        t = parse_rfc3339(ts) if isinstance(ts, str) else ts
        if t is None:
            return SENTINEL
        return int((t - epoch).total_seconds() * 1000)


def _parse_int(s: str) -> Optional[int]:
    try:
        return int(str(s), 0)
    except ValueError:
        return None


def _parse_duration_ms(s: str) -> Optional[int]:
    sec = parse_go_duration(str(s))
    if sec is None:
        return None
    return int(sec * 1000)


def _patch_prefix_paths(data: Any, base: Tuple[str, ...] = ()) -> List[Tuple[str, ...]]:
    """All dict paths a merge patch writes (leaves and replaced subtrees)."""
    if not isinstance(data, dict):
        return [base]
    out: List[Tuple[str, ...]] = []
    for k, v in data.items():
        out.extend(_patch_prefix_paths(v, base + (str(k),)))
    return out


def _is_touched(col_prefix: Tuple[str, ...], touched: List[Tuple[str, ...]]) -> bool:
    """Does any written path overlap the column's read path?
    Overlap = one is a prefix of the other."""
    if not col_prefix:
        return bool(touched)
    for t in touched:
        n = min(len(t), len(col_prefix))
        if t[:n] == col_prefix[:n]:
            return True
    return False
