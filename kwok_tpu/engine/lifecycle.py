"""Host (CPU) Stage lifecycle engine — the reference backend and parity
oracle for the device kernel.

Semantics mirror reference pkg/utils/lifecycle/lifecycle.go:

- ``CompiledStage`` (NewStage:194-267): stages without a selector are
  dropped; matchLabels/matchAnnotations are exact set-selectors; jq
  matchExpressions compile to Requirements; the weight getter always
  has a static fallback (default 0); the delay getter exists only if a
  delay block does, with static duration defaulting to 0ms; the jitter
  getter exists only if either jitter field does.
- ``Lifecycle.match`` (:51-63): all stages whose selectors match.
- ``Lifecycle.select`` (Match:125-191): the weighted-random fallback
  ladder — all-error -> uniform(all); total==0 & no errors ->
  uniform(all); total==0 & some errors -> uniform(non-error);
  else weighted among weight>0.
- ``Lifecycle.list_all_possible`` (:66-122): same ladder without
  randomness, returning the candidate set.
- ``Stage.delay`` (:313-341): duration then jitter; jitter < duration
  returns jitter; else uniform in [duration, jitter).
- ``Next`` effects (next.go:31-96, finalizers.go:32-116).
"""

from __future__ import annotations

import datetime
import json
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from kwok_tpu.api.types import Stage, StageNext
from kwok_tpu.utils.expression import DurationGetter, IntGetter, Requirement
from kwok_tpu.utils.gotpl import Renderer
from kwok_tpu.utils.patch import wrap_json_patch_with_root, wrap_with_root

PATCH_TYPE_CONTENT = {
    "json": "application/json-patch+json",
    "merge": "application/merge-patch+json",
    "strategic": "application/strategic-merge-patch+json",
}


@dataclass
class Patch:
    """A materialized patch (reference next.go Patch struct)."""

    data: Any
    type: str  # json | merge | strategic
    subresource: str = ""
    impersonation: Optional[str] = None

    @property
    def content_type(self) -> str:
        return PATCH_TYPE_CONTENT[self.type]


class CompiledStage:
    """One compiled stage (reference lifecycle.go Stage struct:270-283)."""

    def __init__(self, stage: Stage):
        self.name = stage.name
        self.raw = stage
        sel = stage.selector
        assert sel is not None
        self.match_labels: Optional[Dict[str, str]] = (
            dict(sel.match_labels) if sel.match_labels else None
        )
        self.match_annotations: Optional[Dict[str, str]] = (
            dict(sel.match_annotations) if sel.match_annotations else None
        )
        self.requirements: List[Requirement] = [
            Requirement(e.key, e.operator, e.values) for e in sel.match_expressions
        ]
        self.next: Optional[StageNext] = stage.next
        self.immediate_next_stage = stage.immediate_next_stage

        self.weight_getter = IntGetter(
            stage.weight, stage.weight_from.expression_from if stage.weight_from else None
        )

        self.duration_getter: Optional[DurationGetter] = None
        self.jitter_getter: Optional[DurationGetter] = None
        if stage.delay is not None:
            d = stage.delay
            static = (d.duration_milliseconds or 0) / 1000.0
            self.duration_getter = DurationGetter(
                static, d.duration_from.expression_from if d.duration_from else None
            )
            if d.jitter_duration_milliseconds is not None or d.jitter_duration_from is not None:
                jitter_static = (
                    d.jitter_duration_milliseconds / 1000.0
                    if d.jitter_duration_milliseconds is not None
                    else None
                )
                self.jitter_getter = DurationGetter(
                    jitter_static,
                    d.jitter_duration_from.expression_from if d.jitter_duration_from else None,
                )

    def match(self, labels: Dict[str, str], annotations: Dict[str, str], data: Any) -> bool:
        if self.match_labels is not None:
            for k, v in self.match_labels.items():
                if labels.get(k) != v:
                    return False
        if self.match_annotations is not None:
            for k, v in self.match_annotations.items():
                if annotations.get(k) != v:
                    return False
        for req in self.requirements:
            if not req.matches(data):
                return False
        return True

    def weight(self, data: Any) -> Tuple[int, bool]:
        return self.weight_getter.get(to_json_standard(data))

    def delay(
        self,
        data: Any,
        now: datetime.datetime,
        rng: Optional[random.Random] = None,
    ) -> Tuple[float, bool]:
        """Delay seconds for this transition (lifecycle.go:313-341)."""
        if self.duration_getter is None:
            return 0.0, False
        data = to_json_standard(data)
        duration, ok = self.duration_getter.get(data, now)
        if not ok:
            return 0.0, False
        if self.jitter_getter is None:
            return duration, True
        jitter, ok = self.jitter_getter.get(data, now)
        if not ok:
            return duration, True
        if jitter < duration:
            return jitter, True
        if jitter > duration:
            r = rng.random() if rng is not None else random.random()
            duration += r * (jitter - duration)
        return duration, True


class NextEffects:
    """Materializes a stage's effects (reference next.go:31-96)."""

    def __init__(self, nxt: StageNext, renderer: Renderer):
        self.next = nxt
        self.renderer = renderer

    def finalizers_patch(self, meta_finalizers: List[str]) -> Optional[Patch]:
        """Finalizer add/remove/empty as RFC6902 ops (finalizers.go:32-116)."""
        if self.next.finalizers is None:
            return None
        f = self.next.finalizers
        ops = _finalizers_modify(meta_finalizers, f)
        if not ops:
            return None
        return Patch(data=ops, type="json")

    @property
    def event(self):
        return self.next.event

    @property
    def delete(self) -> bool:
        return self.next.delete

    def patches(self, resource: Any, extra_funcs: Optional[Dict[str, Callable]] = None) -> List[Patch]:
        out: List[Patch] = []
        for p in self.next.patches:
            ptype = p.type or "merge"
            if ptype == "json":
                data = self.renderer.render_to_json(p.template, resource, extra_funcs)
                data = wrap_json_patch_with_root(p.root, data or [])
            else:
                data = self.renderer.render_to_json(p.template, resource, extra_funcs)
                data = wrap_with_root(p.root, data)
            out.append(
                Patch(
                    data=data,
                    type=ptype,
                    subresource=p.subresource,
                    impersonation=p.impersonation.username if p.impersonation else None,
                )
            )
        return out


def _finalizers_modify(meta_finalizers: List[str], f) -> List[Dict[str, Any]]:
    is_empty = False
    ops: List[Dict[str, Any]] = []
    remove_values = [i.value for i in f.remove]
    add_values = [i.value for i in f.add]
    if f.empty:
        is_empty = True
    elif remove_values:
        removed = []
        for i in range(len(meta_finalizers) - 1, -1, -1):
            if meta_finalizers[i] in remove_values:
                removed.append({"op": "remove", "path": f"/metadata/finalizers/{i}"})
        if len(removed) == len(meta_finalizers):
            is_empty = True
        else:
            ops.extend(removed)

    if not is_empty:
        if add_values:
            ops.extend(_finalizers_add(meta_finalizers, add_values))
    else:
        if meta_finalizers:
            ops.append({"op": "remove", "path": "/metadata/finalizers"})
        if add_values:
            ops.extend(_finalizers_add([], add_values))
    return ops


def _finalizers_add(meta_finalizers: List[str], values: List[str]) -> List[Dict[str, Any]]:
    ops: List[Dict[str, Any]] = []
    if meta_finalizers:
        for v in values:
            if v in meta_finalizers:
                continue
            ops.append({"op": "add", "path": "/metadata/finalizers/-", "value": v})
    else:
        ops.append({"op": "add", "path": "/metadata/finalizers", "value": list(values)})
    return ops


class Lifecycle:
    """An ordered, compiled stage list (reference lifecycle.go:33-63)."""

    def __init__(self, stages: List[Stage], renderer: Optional[Renderer] = None):
        self.stages: List[CompiledStage] = []
        for s in stages:
            if s.selector is None:
                continue  # NewStage returns nil for selector-less stages
            self.stages.append(CompiledStage(s))
        self.renderer = renderer or Renderer()

    def match(
        self, labels: Dict[str, str], annotations: Dict[str, str], data: Any
    ) -> List[CompiledStage]:
        data = to_json_standard(data)
        return self._match_std(labels, annotations, data)

    def _match_std(
        self, labels: Dict[str, str], annotations: Dict[str, str], data: Any
    ) -> List[CompiledStage]:
        """match() over already-standardized data (internal fast path)."""
        return [s for s in self.stages if s.match(labels, annotations, data)]

    def select(
        self,
        labels: Dict[str, str],
        annotations: Dict[str, str],
        data: Any,
        rng: Optional[random.Random] = None,
    ) -> Optional[CompiledStage]:
        """Weighted-random choice with the reference fallback ladder
        (lifecycle.go:125-191)."""
        rng = rng or random
        data = to_json_standard(data)
        stages = self._match_std(labels, annotations, data)
        if not stages:
            return None
        if len(stages) == 1:
            return stages[0]

        weights: List[int] = []
        total = 0
        count_error = 0
        for s in stages:
            w, ok = s.weight_getter.get(data)
            if ok:
                total += w
                weights.append(w)
            else:
                weights.append(-1)
                count_error += 1

        if count_error == len(stages):
            return stages[rng.randrange(len(stages))]

        if total == 0:
            if count_error == 0:
                return stages[rng.randrange(len(stages))]
            with_weights = [s for i, s in enumerate(stages) if weights[i] >= 0]
            return with_weights[rng.randrange(len(with_weights))]

        off = rng.randrange(total)
        for i, s in enumerate(stages):
            if weights[i] <= 0:
                continue
            off -= weights[i]
            if off < 0:
                return s
        return stages[-1]

    def list_all_possible(
        self, labels: Dict[str, str], annotations: Dict[str, str], data: Any
    ) -> List[CompiledStage]:
        """Deterministic candidate set (lifecycle.go:66-122)."""
        data = to_json_standard(data)
        stages = self._match_std(labels, annotations, data)
        if len(stages) <= 1:
            return stages

        weights: List[int] = []
        total = 0
        count_error = 0
        for s in stages:
            w, ok = s.weight_getter.get(data)
            if ok:
                total += w
                weights.append(w)
            else:
                weights.append(-1)
                count_error += 1

        if count_error == len(stages):
            return stages
        if total == 0:
            if count_error == 0:
                return stages
            return [s for i, s in enumerate(stages) if weights[i] >= 0]
        return [s for i, s in enumerate(stages) if weights[i] > 0]

    def effects(self, stage: CompiledStage) -> Optional[NextEffects]:
        if stage.next is None:
            return None
        return NextEffects(stage.next, self.renderer)


def to_json_standard(obj: Any) -> Any:
    """Normalize to JSON-standard types (reference query.go:72-88
    ToJSONStandard): datetimes (from YAML timestamp parsing) become
    RFC3339 strings. Returns the original object unchanged (no copy)
    when it is already JSON-standard."""
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if isinstance(obj, datetime.datetime):
        if obj.tzinfo is None:
            obj = obj.replace(tzinfo=datetime.timezone.utc)
        return obj.isoformat().replace("+00:00", "Z")
    if isinstance(obj, datetime.date):
        return obj.isoformat()
    if isinstance(obj, dict):
        out = None
        for k, v in obj.items():
            nv = to_json_standard(v)
            if nv is not v and out is None:
                out = dict(obj)
            if out is not None:
                out[k] = nv
        return out if out is not None else obj
    if isinstance(obj, (list, tuple)):
        out_l = None
        for i, v in enumerate(obj):
            nv = to_json_standard(v)
            if nv is not v and out_l is None:
                out_l = list(obj)
            if out_l is not None:
                out_l[i] = nv
        if out_l is not None:
            return out_l
        return list(obj) if isinstance(obj, tuple) else obj
    return json.loads(json.dumps(obj, default=str))
