"""Feature schema: selector expressions -> dense int32 bitmask columns.

The device kernel cannot run jq over JSON, so the stage compiler maps
every distinct selector matchExpression key (plus matchLabels /
matchAnnotations pairs) in a stage set to one int32 *bitmask column*:

- bit 0: the expression produced at least one output NOT in the
  column's value vocabulary ("other");
- bits 1..30: one bit per vocabulary value (the union of all selector
  values mentioned for that key across the stage set).

With that encoding every reference selector operator
(reference: pkg/utils/expression/selector.go:60-120) becomes a single
masked test on the column value F:

- In(vals)       -> (F & mask(vals)) != 0
- NotIn(vals)    -> (F & mask(vals)) == 0
- Exists         -> F != 0
- DoesNotExist   -> F == 0

i.e. uniformly ``((F & mask) != 0) ^ negate`` with mask=0xFFFFFFFF for
the existence operators.

Host-side extraction runs the real kq query per column (exact parity
with the host engine); on-device, stage effects update columns via the
compiler's abstract-FSM exploration (see compiler.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from kwok_tpu.api.types import Stage
from kwok_tpu.utils.expression import value_as_string
from kwok_tpu.utils.kq import Field as KqField
from kwok_tpu.utils.kq import Iterate, Path, Pipe, Query

OTHER_BIT = 1  # bit 0
MAX_VOCAB = 30

# Mask covering "any output at all" for Exists/DoesNotExist tests.
ALL_MASK = 0xFFFFFFFF


@dataclass
class FeatureColumn:
    """One selector key -> one int32 bitmask column."""

    key: str  # canonical expression source ("label:app=..." for labels)
    query: Optional[Query]  # None for label/annotation columns
    label_key: Optional[str] = None  # matchLabels column
    annotation_key: Optional[str] = None  # matchAnnotations column
    vocab: Dict[str, int] = field(default_factory=dict)  # value -> bit index (>=1)
    path_prefix: Tuple[str, ...] = ()  # dict path read by the query

    def vocab_bit(self, value: str) -> int:
        """Bit for a vocabulary value, allocating if new."""
        if value not in self.vocab:
            if len(self.vocab) >= MAX_VOCAB:
                raise ValueError(
                    f"selector value vocabulary overflow on column {self.key!r}"
                )
            self.vocab[value] = 1 + len(self.vocab)
        return self.vocab[value]

    def mask_for(self, values: Sequence[str]) -> int:
        m = 0
        for v in values:
            m |= 1 << self.vocab_bit(v)
        return m

    def extract(self, obj: Any, labels: Dict[str, str], annotations: Dict[str, str]) -> int:
        """Host-side: evaluate this column's bitmask for one object."""
        if self.label_key is not None:
            v = labels.get(self.label_key)
            outputs = [] if v is None else [v]
        elif self.annotation_key is not None:
            v = annotations.get(self.annotation_key)
            outputs = [] if v is None else [v]
        else:
            out = self.query.execute(obj)
            outputs = out or []
        bits = 0
        for o in outputs:
            s = value_as_string(o)
            if s is not None and s in self.vocab:
                bits |= 1 << self.vocab[s]
            else:
                bits |= OTHER_BIT
        return bits


def query_path_prefix(src: str) -> Tuple[str, ...]:
    """The dict path a query reads, up to the first iterate/filter —
    used by the compiler's merge-patch touch rule."""
    q = Query(src)
    ast = q._ast
    node = ast
    if isinstance(node, Pipe):
        node = node.stages[0]
    if not isinstance(node, Path):
        return ()
    prefix: List[str] = []
    for op in node.ops:
        if isinstance(op, KqField):
            prefix.append(op.name)
        elif isinstance(op, Iterate):
            break
        else:  # pragma: no cover
            break
    return tuple(prefix)


class FeatureSchema:
    """Column registry for one compiled stage set."""

    def __init__(self) -> None:
        self.columns: List[FeatureColumn] = []
        self._by_key: Dict[str, int] = {}

    def column_for_expression(self, src: str) -> int:
        key = f"expr:{src}"
        idx = self._by_key.get(key)
        if idx is None:
            col = FeatureColumn(
                key=key, query=Query(src), path_prefix=query_path_prefix(src)
            )
            idx = len(self.columns)
            self.columns.append(col)
            self._by_key[key] = idx
        return idx

    def column_for_label(self, label_key: str) -> int:
        key = f"label:{label_key}"
        idx = self._by_key.get(key)
        if idx is None:
            col = FeatureColumn(
                key=key,
                query=None,
                label_key=label_key,
                path_prefix=("metadata", "labels", label_key),
            )
            idx = len(self.columns)
            self.columns.append(col)
            self._by_key[key] = idx
        return idx

    def column_for_annotation(self, annotation_key: str) -> int:
        key = f"annotation:{annotation_key}"
        idx = self._by_key.get(key)
        if idx is None:
            col = FeatureColumn(
                key=key,
                query=None,
                annotation_key=annotation_key,
                path_prefix=("metadata", "annotations", annotation_key),
            )
            idx = len(self.columns)
            self.columns.append(col)
            self._by_key[key] = idx
        return idx

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def extract_row(self, obj: Any) -> List[int]:
        """Full feature vector for one JSON-standard object."""
        meta = obj.get("metadata") or {}
        labels = meta.get("labels") or {}
        annotations = meta.get("annotations") or {}
        return [c.extract(obj, labels, annotations) for c in self.columns]


@dataclass(frozen=True)
class CompiledCondition:
    """One matchExpression compiled to a masked column test:
    matches iff ((F[col] & mask) != 0) ^ negate."""

    col: int
    mask: int
    negate: bool


def compile_selector(schema: FeatureSchema, stage: Stage) -> List[CompiledCondition]:
    """Compile a stage's selector to masked column tests."""
    sel = stage.selector
    conds: List[CompiledCondition] = []
    if sel is None:
        return conds
    for k, v in (sel.match_labels or {}).items():
        col = schema.column_for_label(k)
        mask = schema.columns[col].mask_for([v])
        conds.append(CompiledCondition(col, mask, False))
    for k, v in (sel.match_annotations or {}).items():
        col = schema.column_for_annotation(k)
        mask = schema.columns[col].mask_for([v])
        conds.append(CompiledCondition(col, mask, False))
    for e in sel.match_expressions:
        col = schema.column_for_expression(e.key)
        fc = schema.columns[col]
        if e.operator == "In":
            conds.append(CompiledCondition(col, fc.mask_for(e.values), False))
        elif e.operator == "NotIn":
            conds.append(CompiledCondition(col, fc.mask_for(e.values), True))
        elif e.operator == "Exists":
            conds.append(CompiledCondition(col, ALL_MASK, False))
        elif e.operator == "DoesNotExist":
            conds.append(CompiledCondition(col, ALL_MASK, True))
        else:
            raise ValueError(f"operator {e.operator!r} is not supported")
    return conds
