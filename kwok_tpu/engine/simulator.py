"""DeviceSimulator: the TPU execution backend behind the Stage API.

Owns the device-resident SoA and the host-side object mirror. The
division of labor mirrors the Go<->device bridge mandated by the north
star (SURVEY.md:202-218 §2.9, §7): objects are admitted/updated/deleted on the
host (feature extraction + signature/override classing), the tick
kernel advances the FSM on device, and only *dirty rows* come back —
the host then materializes their full JSON status with the same
renderer the CPU backend uses, which is what makes device/host parity
checkable feature-by-feature.

Virtual time: int32 milliseconds since ``epoch`` (a wall-clock
datetime); ~24 days of simulated time per run, which bounds nothing in
practice since runs are restartable from snapshots.
"""

from __future__ import annotations

import datetime
import hashlib
import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kwok_tpu.api.types import Stage
from kwok_tpu.engine.compiler import (
    IDLE,
    NEVER,
    SENTINEL,
    CompiledStageSet,
    StageCompileError,
)
from kwok_tpu.engine.lifecycle import to_json_standard
from kwok_tpu.ops.tick import (
    SoA,
    TickParams,
    params_from_compiled,
    run_ticks_collect,
    scatter_rows,
    tick,
)
from kwok_tpu.utils.patch import apply_patch

DEFAULT_EPOCH = datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)

#: virtual-clock rebase threshold (~12.4 days of simulated ms).  int32
#: virtual time would collide with NEVER/SENTINEL semantics near 2^31
#: (VERDICT r01 weak #6); once ``now`` passes this, the simulator shifts
#: epoch forward and rebases every timer column so long record/replay
#: runs never approach the edge.
REBASE_AT_MS = 2**30


def default_env_funcs() -> Dict[str, Callable]:
    """Deterministic NodeIP/PodIP-style funcs for materialization
    (reference: node_controller.go:521-531, pod_controller.go:559-615
    derive these from the node IP pool; here they are hash-derived)."""

    def node_ip(name: str = "") -> str:
        h = int(hashlib.sha1(name.encode()).hexdigest(), 16)
        return f"10.{(h >> 16) % 256}.{(h >> 8) % 256}.{h % 254 + 1}"

    def pod_ip(*args: Any) -> str:
        h = int(hashlib.sha1(json.dumps([str(a) for a in args]).encode()).hexdigest(), 16)
        return f"10.{64 + (h >> 16) % 64}.{(h >> 8) % 256}.{h % 254 + 1}"

    return {
        "NodeIP": lambda: "10.0.0.1",
        "NodeName": lambda: "kwok-node",
        "NodePort": lambda: 10250,
        "NodeIPWith": node_ip,
        "PodIP": lambda: pod_ip("default"),
        "PodIPWith": pod_ip,
    }


class Transition:
    """One materializable FSM transition drained from the device."""

    __slots__ = ("row", "stage_idx", "stage_name", "t_ms", "deleted", "event")

    def __init__(self, row, stage_idx, stage_name, t_ms, deleted, event):
        self.row = row
        self.stage_idx = stage_idx
        self.stage_name = stage_name
        self.t_ms = t_ms
        self.deleted = deleted
        self.event = event

    def __repr__(self):
        return (
            f"Transition(row={self.row}, stage={self.stage_name!r}, "
            f"t_ms={self.t_ms}, deleted={self.deleted})"
        )


class DeviceSimulator:
    """Vectorized Stage-FSM simulator for one resource class."""

    def __init__(
        self,
        stages: List[Stage],
        capacity: int,
        epoch: datetime.datetime = DEFAULT_EPOCH,
        seed: int = 0,
        env_funcs: Optional[Dict[str, Callable]] = None,
        mesh=None,
    ):
        self.cset = CompiledStageSet(stages)
        #: optional jax.sharding.Mesh: rows sharded across its devices,
        #: stage tensors replicated (SURVEY §2.9/§7 step 7 scale-out).
        #: The tick is row-parallel, so the only collective is the
        #: fired-count psum XLA inserts under the out-shardings.
        self.mesh = mesh
        self._n_shards = 1 if mesh is None else int(mesh.size)
        self._sharded_ticks: Dict[int, Callable] = {}
        if mesh is not None:
            from kwok_tpu.parallel.mesh import pad_rows

            capacity = pad_rows(capacity, self._n_shards)
        self.capacity = capacity
        self.epoch = epoch
        self.env_funcs = dict(env_funcs) if env_funcs is not None else default_env_funcs()
        C = self.cset.C

        # host-side row storage (numpy until to_device)
        self.features = np.zeros((capacity, C), np.int32)
        self.sig = np.zeros(capacity, np.int32)
        self.ovc = np.zeros(capacity, np.int32)
        self.stage = np.full(capacity, IDLE, np.int32)
        self.fire_at = np.full(capacity, NEVER, np.int32)
        self.active = np.zeros(capacity, np.bool_)
        self.rematch = np.zeros(capacity, np.bool_)
        self.del_ts = np.full(capacity, SENTINEL, np.int32)

        self.objects: List[Optional[dict]] = [None] * capacity
        self.num_rows = 0  # high-water mark
        self._free: List[int] = []  # released rows available for reuse
        self._seed = seed
        self._admit_cache: Dict[str, Tuple[int, int, np.ndarray]] = {}
        # The admit fast path caches (sig, ovc, features) by content hash.
        # It is sound only when every feature column reads fields the
        # cache key covers: spec/status plus the well-known metadata
        # fields. A selector on any other metadata field (creationTimestamp,
        # generateName, ...) disables the cache.
        self._cacheable = all(
            c.path_prefix
            and (
                c.path_prefix[0] in ("spec", "status")
                or c.path_prefix[:2]
                in (
                    ("metadata", "labels"),
                    ("metadata", "annotations"),
                    ("metadata", "deletionTimestamp"),
                    ("metadata", "finalizers"),
                    ("metadata", "ownerReferences"),
                )
            )
            for c in self.cset.schema.columns
        )

        self._soa: Optional[SoA] = None
        self._params: Optional[TickParams] = None
        self._params_version = -1
        self._dev_now = None  # preserved virtual clock across re-uploads
        self._dev_key = None  # preserved PRNG state across re-uploads
        self._rematch_pending = False
        self._host_synced = True
        #: host mirror of the device virtual clock — ticks advance it
        #: deterministically, so reading now_ms never costs a device
        #: round-trip (the tunnel TPU makes every blocking read ~RTT)
        self._now_host = 0
        #: rows mutated on host since the last device upload; flushed as
        #: one scatter_rows call instead of a full SoA re-upload
        self._pending: set = set()

    # ------------------------------------------------------------------ host ops

    def _classify(self, obj: dict) -> Tuple[int, int, np.ndarray]:
        """(sig, ovc, features) for an object, via the content-hash
        cache when the stage set's feature columns allow it. Shared by
        admit and refresh_row — the churn steady state revisits the
        same object states cyclically, so the cache turns the per-row
        re-extraction into one json.dumps."""
        cache_key = None
        if self._cacheable:
            meta = obj.get("metadata") or {}
            content = {
                "spec": obj.get("spec"),
                "labels": meta.get("labels"),
                "annotations": meta.get("annotations"),
                "ownerReferences": meta.get("ownerReferences"),
                "status": obj.get("status"),
                "deletionTimestamp": meta.get("deletionTimestamp"),
                "finalizers": meta.get("finalizers"),
                # template-read projection (e.g. creationTimestamp for the
                # node stages): objects differing here must re-explore
                "proj": self.cset.state_projection(obj),
            }
            cache_key = hashlib.sha1(
                json.dumps(content, sort_keys=True, default=str).encode()
            ).hexdigest()
            hit = self._admit_cache.get(cache_key)
            if hit is not None:
                return hit
        sig = self.cset.signature_for(obj)
        ovc = self.cset.override_class_for(obj)
        feats = self.cset.extract_features(obj)
        if cache_key is not None:
            if len(self._admit_cache) >= 4_000_000:
                self._admit_cache.clear()  # coarse bound; keys are
                # per-object-state (podIP makes them per-pod), so the
                # cache is O(pods x FSM states) without it
            self._admit_cache[cache_key] = (sig, ovc, feats)
        return sig, ovc, feats

    def admit(self, obj: dict) -> int:
        """Add an object; returns its row index. Reuses released rows;
        grows the SoA (2x, device re-upload) when full. The row's new
        host values reach the device as part of the next tick's batched
        scatter (see _flush_pending) — no full re-upload."""
        obj = to_json_standard(obj)
        self._pre_mutate()
        if self._free:
            row = self._free.pop()
        else:
            if self.num_rows >= self.capacity:
                self.ensure_capacity(self.num_rows + 1)
            row = self.num_rows
            self.num_rows += 1
        sig, ovc, feats = self._classify(obj)
        self.sig[row] = sig
        self.ovc[row] = ovc
        self.features[row] = feats
        self.stage[row] = IDLE
        self.fire_at[row] = NEVER
        self._finish_admit(row, obj)
        self._mark_pending(row)
        return row

    def admit_bulk(self, obj: dict, count: int) -> range:
        """Admit ``count`` copies of one object as a contiguous row range
        with a single feature extraction (the scale/bench path —
        VERDICT r01 #8). All rows share the same host mirror dict, which
        is sound because every patch path is copy-on-write
        (utils/patch.apply_patch) and per-row divergence replaces
        ``objects[row]``; in-place mutators must copy first (see
        request_delete)."""
        if count <= 0:
            return range(0, 0)
        obj = to_json_standard(obj)
        start = self.num_rows
        self.ensure_capacity(start + count)
        if self._soa is not None:
            # bulk admits are setup-path; a full re-upload beats a
            # giant scatter here
            self._invalidate_device()
        sl = slice(start, start + count)
        self.sig[sl] = self.cset.signature_for(obj)
        self.ovc[sl] = self.cset.override_class_for(obj)
        self.features[sl] = self.cset.extract_features(obj)[None, :]
        self.stage[sl] = IDLE
        self.fire_at[sl] = NEVER
        self.active[sl] = True
        self.rematch[sl] = True
        self.del_ts[sl] = self.cset.deletion_ts_ms(obj, self.epoch)
        self.objects[start : start + count] = [obj] * count
        self.num_rows = start + count
        return range(start, start + count)

    def _finish_admit(self, row: int, obj: dict) -> None:
        self.objects[row] = obj
        self.active[row] = True
        self.rematch[row] = True
        self.del_ts[row] = self.cset.deletion_ts_ms(obj, self.epoch)

    def _pre_mutate(self) -> None:
        """Mesh path only: pull device progress BEFORE host row writes
        (the full re-upload on next to_device would otherwise clobber
        them on sync).  The single-device path instead scatters the
        touched rows after the writes (_mark_pending)."""
        if self._soa is not None and self.mesh is not None:
            self._invalidate_device()

    def _mark_pending(self, row: int) -> None:
        """Record a host-mutated row for the next batched device scatter.
        With no live device SoA the next to_device() uploads everything
        anyway; the mesh path keeps the full re-upload (scatter into
        sharded arrays is not worth the per-shape compile cache there)."""
        if self._soa is not None and self.mesh is None:
            self._pending.add(row)

    def _flush_pending(self) -> None:
        """Scatter pending host rows into the live device SoA (one jit
        call, rows padded to a power of two to bound recompiles)."""
        if not self._pending:
            return
        if self._soa is None:
            self._pending.clear()
            return
        rows = np.fromiter(self._pending, np.int32, len(self._pending))
        self._pending.clear()
        k = len(rows)
        pad = 1 << max(k - 1, 0).bit_length()
        if pad > k:
            # duplicate scatters carry identical values, so padding with
            # a repeated real row is deterministic
            rows = np.concatenate([rows, np.full(pad - k, rows[0], np.int32)])
        self._soa = scatter_rows(
            self._soa,
            jnp.asarray(rows),
            jnp.asarray(self.features[rows]),
            jnp.asarray(self.sig[rows]),
            jnp.asarray(self.ovc[rows]),
            jnp.asarray(self.stage[rows]),
            jnp.asarray(self.fire_at[rows]),
            jnp.asarray(self.active[rows]),
            jnp.asarray(self.rematch[rows]),
            jnp.asarray(self.del_ts[rows]),
        )
        self._rematch_pending = True

    def _invalidate_device(self) -> None:
        """Pull device progress into the host arrays (so a host mutation
        + re-upload does not lose it) and preserve the virtual clock and
        PRNG state across the re-upload."""
        if self._soa is not None:
            self._ensure_synced()
            self._dev_now = self._soa.now
            self._dev_key = self._soa.key
            self._soa = None
        self._pending.clear()

    def release(self, row: int) -> None:
        """Retire a row (object gone from the cluster); the row is
        recycled by the next admit."""
        if self.objects[row] is None and not self.active[row]:
            return
        self._pre_mutate()
        self.objects[row] = None
        self.active[row] = False
        self.stage[row] = IDLE
        self.fire_at[row] = NEVER
        self.rematch[row] = False
        self.del_ts[row] = SENTINEL
        self._free.append(row)
        self._mark_pending(row)

    def ensure_capacity(self, n: int) -> None:
        """Grow the SoA to hold at least n rows (amortized doubling)."""
        if n <= self.capacity:
            return
        new_cap = max(self.capacity * 2, n, 64)
        if self.mesh is not None:
            from kwok_tpu.parallel.mesh import pad_rows

            new_cap = pad_rows(new_cap, self._n_shards)
        self._invalidate_device()
        grow = new_cap - self.capacity

        def pad(arr, fill):
            ext = np.full((grow,) + arr.shape[1:], fill, arr.dtype)
            return np.concatenate([arr, ext], axis=0)

        self.features = pad(self.features, 0)
        self.sig = pad(self.sig, 0)
        self.ovc = pad(self.ovc, 0)
        self.stage = pad(self.stage, IDLE)
        self.fire_at = pad(self.fire_at, NEVER)
        self.active = pad(self.active, False)
        self.rematch = pad(self.rematch, False)
        self.del_ts = pad(self.del_ts, SENTINEL)
        self.objects.extend([None] * grow)
        self.capacity = new_cap

    def request_delete(self, row: int, at_ms: int) -> None:
        """External delete request: set deletionTimestamp and re-evaluate
        (the apiserver's graceful-delete path)."""
        obj = self.objects[row]
        if obj is None:
            return
        ts = self.epoch + datetime.timedelta(milliseconds=int(at_ms))
        # copy-on-write: rows from admit_bulk share one mirror dict
        obj = dict(obj)
        meta = dict(obj.get("metadata") or {})
        meta["deletionTimestamp"] = (
            ts.isoformat(timespec="milliseconds").replace("+00:00", "Z")
        )
        obj["metadata"] = meta
        self.objects[row] = obj
        self.refresh_row(row)

    def refresh_row(self, row: int) -> None:
        """Re-extract features after an external mutation and force
        rematch.  The row's armed timer is reset (stage IDLE, fire_at
        NEVER): the reference re-enqueues a changed object with a fresh
        delay, replacing the old queue entry (pod_controller.go:205-214
        resourceVersion dedup + addStageJob), so a reset, not a carried
        timer, is the parity-correct behavior."""
        self._pre_mutate()
        obj = self.objects[row]
        sig, ovc, feats = self._classify(obj)
        self.features[row] = feats
        self.ovc[row] = ovc
        self.sig[row] = sig
        self.stage[row] = IDLE
        self.fire_at[row] = NEVER
        self.del_ts[row] = self.cset.deletion_ts_ms(obj, self.epoch)
        self.rematch[row] = True
        self._mark_pending(row)

    def confirm_row(self, row: int, obj: dict, ignore_finalizers: bool = False) -> bool:
        """Adopt the store's echo of OUR OWN single status-class patch
        without re-extraction and — critically — without invalidating
        the device SoA (a full re-upload per firing tick breaks the
        "only dirty rows cross the boundary" contract at 1M rows).

        Sound because the tick already applied this (sig, stage)'s
        feature deltas on device, and the effect tables are derived
        from the same host renderer (compiler docstring; parity pinned
        by check_feature_parity tests).  Returns False — caller falls
        back to :meth:`refresh_row` — when the echo differs anywhere
        that feeds signature/override/deadline classification, i.e. a
        writer interleaved with something beyond our status patch.
        External *status* writers are not detected here; in this
        framework status is controller-owned (the reference makes the
        same assumption: kubelet/kwok owns status).

        ``ignore_finalizers``: the caller's op group included its OWN
        finalizer patch — finalizer effects are lowered into feature
        columns by the compiler (finalizer columns exist and effect
        exploration drives the same host engine), so the device already
        reflects the change and the finalizer delta is expected."""
        old = self.objects[row]
        if old is None:
            return False
        om = old.get("metadata") or {}
        nm = obj.get("metadata") or {}
        if (
            old.get("spec") != obj.get("spec")
            or om.get("labels") != nm.get("labels")
            or om.get("annotations") != nm.get("annotations")
            or om.get("ownerReferences") != nm.get("ownerReferences")
            or om.get("deletionTimestamp") != nm.get("deletionTimestamp")
        ):
            return False
        if not ignore_finalizers and om.get("finalizers") != nm.get("finalizers"):
            return False
        self.objects[row] = obj
        return True

    # ---------------------------------------------------------------- device ops

    def to_device(self) -> Tuple[TickParams, SoA]:
        if self._params is None or self._params_version != self.cset.version:
            self._params = params_from_compiled(self.cset)
            self._params_version = self.cset.version
        if self._soa is not None:
            self._flush_pending()
        if self._soa is None:
            self._soa = SoA(
                features=jnp.asarray(self.features),
                sig=jnp.asarray(self.sig),
                ovc=jnp.asarray(self.ovc),
                stage=jnp.asarray(self.stage),
                fire_at=jnp.asarray(self.fire_at),
                active=jnp.asarray(self.active),
                rematch=jnp.asarray(self.rematch),
                del_ts=jnp.asarray(self.del_ts),
                now=self._dev_now if self._dev_now is not None else jnp.int32(0),
                key=(
                    self._dev_key
                    if self._dev_key is not None
                    else jax.random.PRNGKey(self._seed)
                ),
            )
            self._rematch_pending = bool(self.rematch.any())
            if self.mesh is not None:
                from kwok_tpu.parallel.mesh import place

                self._params, self._soa = place(self._params, self._soa, self.mesh)
        return self._params, self._soa

    def _tick_fn(self, dt_ms: int):
        if self.mesh is None:
            return lambda p, s: tick(p, s, dt_ms)
        fn = self._sharded_ticks.get(dt_ms)
        if fn is None:
            from kwok_tpu.parallel.mesh import sharded_tick

            fn = self._sharded_ticks[dt_ms] = sharded_tick(self.mesh, dt_ms)
        return fn

    def tick_many(self, dt_ms: int, n_ticks: int) -> Tuple[np.ndarray, int]:
        """Advance ``n_ticks`` device ticks; returns (fired_stage [K, N]
        int8 with IDLE = not fired, t0_ms = virtual now before the first
        tick).  ONE dispatch + ONE device->host transfer for the whole
        macro-tick — the per-tick blocking reads of the old step() were
        the dominant e2e device cost over the tunnel TPU.  Sub-tick k
        (0-based) fired at virtual time t0_ms + (k+1)*dt_ms; deleted
        rows are stage_delete[fired_stage] (host table).

        Host mirror of device row state is pulled LAZILY: a firing tick
        only marks it stale; the actual full download happens on the
        next _ensure_synced.  Steady-state churn with the fast drain
        moves only this [K, N] int8 across the boundary — "only dirty
        rows come back" at 1M rows."""
        if self.mesh is not None or self.num_stages_over_int8():
            if self.now_ms >= REBASE_AT_MS:
                self._rebase()
            t0_ms = self._now_host
            params, soa = self.to_device()
            # int32 here on purpose: this branch exists (in part)
            # because int8 cannot hold >126 stage indices
            outs = []
            for _ in range(n_ticks):
                soa, out = self._tick_fn(dt_ms)(params, soa)
                outs.append(np.asarray(out.fired_stage))
            self._soa = soa
            stages_np = np.stack(outs) if outs else np.empty((0, 0), np.int32)
            self._now_host = t0_ms + dt_ms * n_ticks
            if (stages_np >= 0).any() or self._rematch_pending:
                self._host_synced = False
                self._rematch_pending = False
            return stages_np, t0_ms
        stages, t0_ms = self.tick_many_async(dt_ms, n_ticks)
        return np.asarray(jax.device_get(stages)), t0_ms

    def num_stages_over_int8(self) -> bool:
        return len(self.cset.compiled) > 126

    def tick_many_async(self, dt_ms: int, n_ticks: int):
        """Like tick_many, but returns the [K, N] fired-stage DEVICE
        array without blocking — the caller overlaps the device compute
        with host work (drain of the previous macro-tick) and fetches
        via jax.device_get when ready.  Single-device path only (the
        caller falls back to tick_many for mesh / >int8 stage sets);
        tick_many's single-device branch is this + the blocking get."""
        assert self.mesh is None and not self.num_stages_over_int8()
        if self.now_ms >= REBASE_AT_MS:
            self._rebase()
        t0_ms = self._now_host
        params, soa = self.to_device()
        new_soa, stages = run_ticks_collect(params, soa, dt_ms, n_ticks)
        self._soa = new_soa
        self._now_host = t0_ms + dt_ms * n_ticks
        # pessimistic: fired rows are not visible until the fetch
        self._host_synced = False
        self._rematch_pending = False
        return stages, t0_ms

    def step(self, dt_ms: int = 100, materialize: bool = True) -> List[Transition]:
        """One tick; drains and (optionally) materializes transitions."""
        stages_np, t0_ms = self.tick_many(dt_ms, 1)
        st = stages_np[0]
        t_ms = t0_ms + dt_ms
        transitions: List[Transition] = []
        for row in np.nonzero(st >= 0)[0]:
            s_idx = int(st[row])
            cs = self.cset.compiled[s_idx]
            event = None
            eid = int(self.cset.stage_event[s_idx])
            if eid >= 0:
                event = self.cset.events[eid]
            tr = Transition(
                row=int(row),
                stage_idx=s_idx,
                stage_name=cs.name,
                t_ms=t_ms,
                deleted=bool(self.cset.stage_delete[s_idx]),
                event=event,
            )
            transitions.append(tr)
            if materialize:
                self.materialize(tr)
        return transitions

    def _rebase(self) -> None:
        """Shift epoch forward by the current virtual now and restart
        the clock at 0, adjusting every timer column (guard against the
        int32 wrap at ~24.8 days; NEVER/SENTINEL rows stay put)."""
        self._invalidate_device()  # pulls device state; stashes now/key
        delta = int(self._dev_now) if self._dev_now is not None else 0
        if delta <= 0:
            return
        self.epoch = self.epoch + datetime.timedelta(milliseconds=delta)
        live = self.fire_at != NEVER
        self.fire_at[live] = self.fire_at[live] - delta
        dl = self.del_ts != SENTINEL
        self.del_ts[dl] = self.del_ts[dl] - delta
        self._dev_now = jnp.int32(0)
        self._now_host = 0

    def _ensure_synced(self) -> None:
        if self._soa is None:
            self._pending.clear()
            return
        # pending host rows must reach the device BEFORE the download,
        # or the download would clobber them with stale device values
        self._flush_pending()
        if self._host_synced:
            return
        soa = self._soa
        # np.array (not asarray): device views are read-only and the host
        # mutates these on refresh_row/admit.
        self.stage = np.array(soa.stage)
        self.fire_at = np.array(soa.fire_at)
        self.active = np.array(soa.active)
        self.features = np.array(soa.features)
        # the true device value, NOT zeros: rows scattered with
        # rematch=True that have not ticked yet must keep the flag
        # across a re-upload or they never arm (found as stuck rows
        # admitted right before a capacity growth)
        self.rematch = np.array(soa.rematch)
        self._host_synced = True

    # ------------------------------------------------------------- materialization

    @property
    def now_ms(self) -> int:
        """Current virtual time in ms (0 before the first tick).  Host
        mirror — never a device read (see tick_many)."""
        return self._now_host

    def now_string(self, t_ms: int) -> str:
        t = self.epoch + datetime.timedelta(milliseconds=int(t_ms))
        return t.isoformat(timespec="microseconds").replace("+00:00", "Z")

    def materialize(self, tr: Transition) -> Optional[dict]:
        """Apply a drained transition to the host mirror object with the
        same renderer the CPU backend uses (virtual-time Now)."""
        obj = self.objects[tr.row]
        if obj is None:
            return None
        cs = self.cset.compiled[tr.stage_idx]
        effects = self.cset.lifecycle.effects(cs)
        if effects is None:
            return obj
        meta = obj.get("metadata") or {}
        fin = effects.finalizers_patch(meta.get("finalizers") or [])
        if fin is not None:
            obj = apply_patch(obj, fin.data, fin.type)
        if tr.deleted or effects.delete:
            self.objects[tr.row] = None
            return None
        funcs = dict(self.env_funcs)
        funcs["Now"] = lambda: self.now_string(tr.t_ms)
        for p in effects.patches(obj, funcs):
            obj = apply_patch(obj, p.data, p.type)
        self.objects[tr.row] = obj
        return obj

    def check_feature_parity(self, rows) -> None:
        """Assert device feature rows == features re-extracted from the
        host-materialized mirror objects (the core parity invariant)."""
        self._ensure_synced()
        for row in rows:
            obj = self.objects[row]
            if obj is None:
                continue
            expect = self.cset.extract_features(obj)
            got = self.features[row]
            if not np.array_equal(expect, got):
                cols = [
                    (c.key, int(expect[i]), int(got[i]))
                    for i, c in enumerate(self.cset.schema.columns)
                    if expect[i] != got[i]
                ]
                raise AssertionError(
                    f"feature parity violation on row {row}: {cols}"
                )

    # --------------------------------------------------------------------- stats

    def phase_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for obj in self.objects[: self.num_rows]:
            if obj is None:
                counts["<deleted>"] = counts.get("<deleted>", 0) + 1
                continue
            phase = (obj.get("status") or {}).get("phase", "<none>")
            counts[phase] = counts.get(phase, 0) + 1
        return counts
