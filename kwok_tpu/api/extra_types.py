"""Non-Stage CRD API types: Metric, ResourceUsage, debug-endpoint configs,
and the ResourcePatch recording action.

Dataclass mirrors of the reference API surface:
- Metric            — pkg/apis/v1alpha1/metric_types.go:61-151
- ResourceUsage     — pkg/apis/v1alpha1/resource_usage_types.go:60-79
- ClusterResourceUsage — pkg/apis/v1alpha1/cluster_resource_usage_types.go
- Logs/ClusterLogs  — pkg/apis/v1alpha1/logs_types.go:50-72
- Attach/ClusterAttach — pkg/apis/v1alpha1/attach_types.go:49-67
- Exec/ClusterExec  — pkg/apis/v1alpha1/exec_types.go:46-101
- PortForward/ClusterPortForward — pkg/apis/v1alpha1/port_forward_types.go:44-87
- ObjectSelector    — pkg/apis/v1alpha1/object_selector.go:20-27
- ResourcePatch     — pkg/apis/action/v1alpha1/resource_patch_types.go:35-77

All types round-trip via ``from_dict``/``to_dict`` and are registered with
the multi-doc config loader by kind.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

API_VERSION = "kwok.x-k8s.io/v1alpha1"
ACTION_API_VERSION = "action.kwok.x-k8s.io/v1alpha1"


def _meta_from(d: Dict[str, Any]) -> Dict[str, Any]:
    return dict(d.get("metadata") or {})


# ---------------------------------------------------------------------------
# ObjectSelector — shared by every Cluster* config kind
# ---------------------------------------------------------------------------


@dataclass
class ObjectSelector:
    """Namespace/name filter for Cluster-scoped debug configs."""

    match_namespaces: List[str] = field(default_factory=list)
    match_names: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ObjectSelector":
        d = d or {}
        return cls(
            match_namespaces=list(d.get("matchNamespaces") or []),
            match_names=list(d.get("matchNames") or []),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.match_namespaces:
            out["matchNamespaces"] = list(self.match_namespaces)
        if self.match_names:
            out["matchNames"] = list(self.match_names)
        return out

    def matches(self, namespace: str, name: str) -> bool:
        if self.match_namespaces and namespace not in self.match_namespaces:
            return False
        if self.match_names and name not in self.match_names:
            return False
        return True


# ---------------------------------------------------------------------------
# Metric
# ---------------------------------------------------------------------------

KIND_COUNTER = "counter"
KIND_GAUGE = "gauge"
KIND_HISTOGRAM = "histogram"

DIMENSION_NODE = "node"
DIMENSION_POD = "pod"
DIMENSION_CONTAINER = "container"


@dataclass
class MetricLabel:
    name: str
    value: str  # CEL expression

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MetricLabel":
        return cls(name=d["name"], value=d["value"])

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "value": self.value}


@dataclass
class MetricBucket:
    le: float
    value: str  # CEL expression
    hidden: bool = False

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MetricBucket":
        return cls(le=float(d["le"]), value=d["value"], hidden=bool(d.get("hidden", False)))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"le": self.le, "value": self.value}
        if self.hidden:
            out["hidden"] = True
        return out


@dataclass
class MetricConfig:
    name: str
    kind: str  # counter | gauge | histogram
    help: str = ""
    labels: List[MetricLabel] = field(default_factory=list)
    value: str = ""  # CEL expression (counter/gauge)
    buckets: List[MetricBucket] = field(default_factory=list)
    dimension: str = DIMENSION_NODE

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MetricConfig":
        if d.get("kind") not in (KIND_COUNTER, KIND_GAUGE, KIND_HISTOGRAM):
            raise ValueError(f"metric {d.get('name')!r}: invalid kind {d.get('kind')!r}")
        return cls(
            name=d["name"],
            kind=d["kind"],
            help=(d.get("help") or "").strip(),
            labels=[MetricLabel.from_dict(x) for x in d.get("labels") or []],
            value=d.get("value") or "",
            buckets=[MetricBucket.from_dict(x) for x in d.get("buckets") or []],
            dimension=d.get("dimension") or DIMENSION_NODE,
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name, "kind": self.kind}
        if self.help:
            out["help"] = self.help
        if self.labels:
            out["labels"] = [x.to_dict() for x in self.labels]
        if self.value:
            out["value"] = self.value
        if self.buckets:
            out["buckets"] = [x.to_dict() for x in self.buckets]
        if self.dimension != DIMENSION_NODE:
            out["dimension"] = self.dimension
        return out


@dataclass
class Metric:
    """A synthetic Prometheus endpoint spec; ``path`` may contain
    ``{nodeName}`` which fans the route out per simulated node."""

    name: str
    path: str
    metrics: List[MetricConfig] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "Metric"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Metric":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        if not spec.get("path"):
            raise ValueError("Metric spec.path is required")
        return cls(
            name=meta.get("name", ""),
            path=spec["path"],
            metrics=[MetricConfig.from_dict(x) for x in spec.get("metrics") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata) or {"name": self.name},
            "spec": {
                "path": self.path,
                "metrics": [m.to_dict() for m in self.metrics],
            },
        }


# ---------------------------------------------------------------------------
# ResourceUsage / ClusterResourceUsage
# ---------------------------------------------------------------------------


@dataclass
class ResourceUsageValue:
    """Either a fixed quantity string or a CEL expression."""

    value: Optional[str] = None
    expression: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceUsageValue":
        return cls(value=d.get("value"), expression=d.get("expression"))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.value is not None:
            out["value"] = self.value
        if self.expression is not None:
            out["expression"] = self.expression
        return out


@dataclass
class ResourceUsageContainer:
    containers: List[str] = field(default_factory=list)
    usage: Dict[str, ResourceUsageValue] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceUsageContainer":
        return cls(
            containers=list(d.get("containers") or []),
            usage={
                k: ResourceUsageValue.from_dict(v) for k, v in (d.get("usage") or {}).items()
            },
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.containers:
            out["containers"] = list(self.containers)
        if self.usage:
            out["usage"] = {k: v.to_dict() for k, v in self.usage.items()}
        return out


@dataclass
class ResourceUsage:
    """Per-pod container resource usage (name/namespace address one pod)."""

    name: str
    namespace: str
    usages: List[ResourceUsageContainer] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "ResourceUsage"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceUsage":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            usages=[ResourceUsageContainer.from_dict(x) for x in spec.get("usages") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata)
            or {"name": self.name, "namespace": self.namespace},
            "spec": {"usages": [u.to_dict() for u in self.usages]},
        }


@dataclass
class ClusterResourceUsage:
    """Cluster-wide usage config, filtered by ObjectSelector."""

    name: str
    selector: ObjectSelector = field(default_factory=ObjectSelector)
    usages: List[ResourceUsageContainer] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "ClusterResourceUsage"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ClusterResourceUsage":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            selector=ObjectSelector.from_dict(spec.get("selector")),
            usages=[ResourceUsageContainer.from_dict(x) for x in spec.get("usages") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {"usages": [u.to_dict() for u in self.usages]}
        sel = self.selector.to_dict()
        if sel:
            spec["selector"] = sel
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata) or {"name": self.name},
            "spec": spec,
        }


# ---------------------------------------------------------------------------
# Debug endpoint configs: Logs / Attach / Exec / PortForward
# ---------------------------------------------------------------------------


@dataclass
class Log:
    containers: List[str] = field(default_factory=list)
    logs_file: Optional[str] = None
    follow: bool = False
    previous_logs_file: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Log":
        return cls(
            containers=list(d.get("containers") or []),
            logs_file=d.get("logsFile"),
            follow=bool(d.get("follow") or False),
            previous_logs_file=d.get("previousLogsFile"),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.containers:
            out["containers"] = list(self.containers)
        if self.logs_file is not None:
            out["logsFile"] = self.logs_file
        if self.follow:
            out["follow"] = True
        if self.previous_logs_file is not None:
            out["previousLogsFile"] = self.previous_logs_file
        return out


def _match_container(entries: List[Any], container: str) -> Optional[Any]:
    """Exact container match wins; else the *first* entry with an empty
    container list is the default — reference rule
    (pkg/kwok/server/debugging_exec.go:131-143 findContainerInExecs)."""
    default = None
    for e in entries:
        if not e.containers:
            if default is None:
                default = e
            continue
        if container in e.containers:
            return e
    return default


@dataclass
class Logs:
    name: str
    namespace: str
    logs: List[Log] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "Logs"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Logs":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            logs=[Log.from_dict(x) for x in spec.get("logs") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata)
            or {"name": self.name, "namespace": self.namespace},
            "spec": {"logs": [x.to_dict() for x in self.logs]},
        }

    def find(self, container: str) -> Optional[Log]:
        return _match_container(self.logs, container)


@dataclass
class ClusterLogs:
    name: str
    selector: ObjectSelector = field(default_factory=ObjectSelector)
    logs: List[Log] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "ClusterLogs"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ClusterLogs":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            selector=ObjectSelector.from_dict(spec.get("selector")),
            logs=[Log.from_dict(x) for x in spec.get("logs") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {"logs": [x.to_dict() for x in self.logs]}
        sel = self.selector.to_dict()
        if sel:
            spec["selector"] = sel
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata) or {"name": self.name},
            "spec": spec,
        }

    def find(self, container: str) -> Optional[Log]:
        return _match_container(self.logs, container)


@dataclass
class AttachConfig:
    containers: List[str] = field(default_factory=list)
    logs_file: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AttachConfig":
        return cls(
            containers=list(d.get("containers") or []),
            logs_file=d.get("logsFile"),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.containers:
            out["containers"] = list(self.containers)
        if self.logs_file is not None:
            out["logsFile"] = self.logs_file
        return out


@dataclass
class Attach:
    name: str
    namespace: str
    attaches: List[AttachConfig] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "Attach"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Attach":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            attaches=[AttachConfig.from_dict(x) for x in spec.get("attaches") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata)
            or {"name": self.name, "namespace": self.namespace},
            "spec": {"attaches": [x.to_dict() for x in self.attaches]},
        }

    def find(self, container: str) -> Optional[AttachConfig]:
        return _match_container(self.attaches, container)


@dataclass
class ClusterAttach:
    name: str
    selector: ObjectSelector = field(default_factory=ObjectSelector)
    attaches: List[AttachConfig] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "ClusterAttach"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ClusterAttach":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            selector=ObjectSelector.from_dict(spec.get("selector")),
            attaches=[AttachConfig.from_dict(x) for x in spec.get("attaches") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {"attaches": [x.to_dict() for x in self.attaches]}
        sel = self.selector.to_dict()
        if sel:
            spec["selector"] = sel
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata) or {"name": self.name},
            "spec": spec,
        }

    def find(self, container: str) -> Optional[AttachConfig]:
        return _match_container(self.attaches, container)


@dataclass
class EnvVar:
    name: str
    value: str = ""

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EnvVar":
        return cls(name=d["name"], value=d.get("value", ""))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name}
        if self.value:
            out["value"] = self.value
        return out


@dataclass
class SecurityContext:
    run_as_user: Optional[int] = None
    run_as_group: Optional[int] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["SecurityContext"]:
        if not d:
            return None
        return cls(run_as_user=d.get("runAsUser"), run_as_group=d.get("runAsGroup"))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.run_as_user is not None:
            out["runAsUser"] = self.run_as_user
        if self.run_as_group is not None:
            out["runAsGroup"] = self.run_as_group
        return out


@dataclass
class ExecTargetLocal:
    work_dir: str = ""
    envs: List[EnvVar] = field(default_factory=list)
    security_context: Optional[SecurityContext] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["ExecTargetLocal"]:
        if d is None:
            return None
        return cls(
            work_dir=d.get("workDir", ""),
            envs=[EnvVar.from_dict(x) for x in d.get("envs") or []],
            security_context=SecurityContext.from_dict(d.get("securityContext")),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.work_dir:
            out["workDir"] = self.work_dir
        if self.envs:
            out["envs"] = [x.to_dict() for x in self.envs]
        if self.security_context is not None:
            out["securityContext"] = self.security_context.to_dict()
        return out


@dataclass
class ExecTarget:
    containers: List[str] = field(default_factory=list)
    local: Optional[ExecTargetLocal] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ExecTarget":
        return cls(
            containers=list(d.get("containers") or []),
            local=ExecTargetLocal.from_dict(d.get("local")),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.containers:
            out["containers"] = list(self.containers)
        if self.local is not None:
            out["local"] = self.local.to_dict()
        return out


@dataclass
class Exec:
    name: str
    namespace: str
    execs: List[ExecTarget] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "Exec"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Exec":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            execs=[ExecTarget.from_dict(x) for x in spec.get("execs") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata)
            or {"name": self.name, "namespace": self.namespace},
            "spec": {"execs": [x.to_dict() for x in self.execs]},
        }

    def find(self, container: str) -> Optional[ExecTarget]:
        return _match_container(self.execs, container)


@dataclass
class ClusterExec:
    name: str
    selector: ObjectSelector = field(default_factory=ObjectSelector)
    execs: List[ExecTarget] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "ClusterExec"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ClusterExec":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            selector=ObjectSelector.from_dict(spec.get("selector")),
            execs=[ExecTarget.from_dict(x) for x in spec.get("execs") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {"execs": [x.to_dict() for x in self.execs]}
        sel = self.selector.to_dict()
        if sel:
            spec["selector"] = sel
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata) or {"name": self.name},
            "spec": spec,
        }

    def find(self, container: str) -> Optional[ExecTarget]:
        return _match_container(self.execs, container)


@dataclass
class ForwardTarget:
    port: int
    address: str

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["ForwardTarget"]:
        if d is None:
            return None
        return cls(port=int(d["port"]), address=d["address"])

    def to_dict(self) -> Dict[str, Any]:
        return {"port": self.port, "address": self.address}


@dataclass
class Forward:
    ports: List[int] = field(default_factory=list)
    target: Optional[ForwardTarget] = None
    command: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Forward":
        return cls(
            ports=[int(p) for p in d.get("ports") or []],
            target=ForwardTarget.from_dict(d.get("target")),
            command=list(d.get("command") or []),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.ports:
            out["ports"] = list(self.ports)
        if self.target is not None:
            out["target"] = self.target.to_dict()
        if self.command:
            out["command"] = list(self.command)
        return out


def _match_port(forwards: List[Forward], port: int) -> Optional[Forward]:
    """Exact port match wins; else the first portless entry is the default —
    same rule as container lookup (debugging_port_forword.go)."""
    default = None
    for f in forwards:
        if not f.ports:
            if default is None:
                default = f
            continue
        if port in f.ports:
            return f
    return default


@dataclass
class PortForward:
    name: str
    namespace: str
    forwards: List[Forward] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "PortForward"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PortForward":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            forwards=[Forward.from_dict(x) for x in spec.get("forwards") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata)
            or {"name": self.name, "namespace": self.namespace},
            "spec": {"forwards": [x.to_dict() for x in self.forwards]},
        }

    def find(self, port: int) -> Optional[Forward]:
        return _match_port(self.forwards, port)


@dataclass
class ClusterPortForward:
    name: str
    selector: ObjectSelector = field(default_factory=ObjectSelector)
    forwards: List[Forward] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)

    KIND = "ClusterPortForward"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ClusterPortForward":
        meta = _meta_from(d)
        spec = d.get("spec") or {}
        return cls(
            name=meta.get("name", ""),
            selector=ObjectSelector.from_dict(spec.get("selector")),
            forwards=[Forward.from_dict(x) for x in spec.get("forwards") or []],
            metadata=meta,
        )

    def to_dict(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {"forwards": [x.to_dict() for x in self.forwards]}
        sel = self.selector.to_dict()
        if sel:
            spec["selector"] = sel
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": dict(self.metadata) or {"name": self.name},
            "spec": spec,
        }

    def find(self, port: int) -> Optional[Forward]:
        return _match_port(self.forwards, port)


# ---------------------------------------------------------------------------
# ResourcePatch — record/replay action format
# ---------------------------------------------------------------------------

PATCH_METHOD_CREATE = "create"
PATCH_METHOD_PATCH = "patch"
PATCH_METHOD_DELETE = "delete"


@dataclass
class GroupVersionResource:
    version: str
    resource: str
    group: str = ""

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GroupVersionResource":
        return cls(
            version=d.get("version", "v1"),
            resource=d["resource"],
            group=d.get("group", ""),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"version": self.version, "resource": self.resource}
        if self.group:
            out["group"] = self.group
        return out


@dataclass
class ResourcePatch:
    """One recorded mutation: ``durationNanosecond`` is the offset from the
    start of the recording; ``template`` is the full object (create) or the
    patch body (patch)."""

    resource: GroupVersionResource
    name: str
    namespace: str
    duration_ns: int
    method: str  # create | patch | delete
    template: Optional[Any] = None

    KIND = "ResourcePatch"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourcePatch":
        target = d.get("target") or {}
        if d.get("method") not in (
            PATCH_METHOD_CREATE,
            PATCH_METHOD_PATCH,
            PATCH_METHOD_DELETE,
        ):
            raise ValueError(f"invalid ResourcePatch method: {d.get('method')!r}")
        return cls(
            resource=GroupVersionResource.from_dict(d.get("resource") or {}),
            name=target.get("name", ""),
            namespace=target.get("namespace", ""),
            duration_ns=int(d.get("durationNanosecond") or 0),
            method=d["method"],
            template=d.get("template"),
        )

    def to_dict(self) -> Dict[str, Any]:
        target: Dict[str, Any] = {"name": self.name}
        if self.namespace:
            target["namespace"] = self.namespace
        out: Dict[str, Any] = {
            "apiVersion": ACTION_API_VERSION,
            "kind": self.KIND,
            "resource": self.resource.to_dict(),
            "target": target,
            "durationNanosecond": self.duration_ns,
            "method": self.method,
        }
        if self.template is not None:
            out["template"] = self.template
        return out


# ---------------------------------------------------------------------------
# Registry for the multi-doc config loader
# ---------------------------------------------------------------------------

CONFIG_KINDS = {
    Metric.KIND: Metric,
    ResourceUsage.KIND: ResourceUsage,
    ClusterResourceUsage.KIND: ClusterResourceUsage,
    Logs.KIND: Logs,
    ClusterLogs.KIND: ClusterLogs,
    Attach.KIND: Attach,
    ClusterAttach.KIND: ClusterAttach,
    Exec.KIND: Exec,
    ClusterExec.KIND: ClusterExec,
    PortForward.KIND: PortForward,
    ClusterPortForward.KIND: ClusterPortForward,
    ResourcePatch.KIND: ResourcePatch,
}


def from_document(d: Dict[str, Any]) -> Any:
    """Instantiate the typed config for one YAML document by ``kind``."""
    kind = d.get("kind")
    cls = CONFIG_KINDS.get(kind or "")
    if cls is None:
        raise ValueError(f"unknown config kind: {kind!r}")
    return cls.from_dict(d)
