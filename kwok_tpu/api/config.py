"""KwokConfiguration options (config.kwok.x-k8s.io/v1alpha1 subset).

Mirrors the reference's controller-facing options and defaults
(reference: pkg/apis/config/v1alpha1/kwok_configuration_types.go and
zz_generated.defaults.go:61-102 — PodPlayStageParallelism=4,
NodePlayStageParallelism=4, NodeLeaseParallelism=4,
NodeLeaseDurationSeconds=40).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class KwokConfiguration:
    #: controller identity, used as the Lease holder
    #: (reference: controller.go HolderIdentity)
    id: str = "kwok-controller"
    manage_all_nodes: bool = False
    manage_nodes_with_annotation_selector: str = ""
    manage_nodes_with_label_selector: str = ""
    disregard_status_with_annotation_selector: str = ""
    disregard_status_with_label_selector: str = ""
    node_play_stage_parallelism: int = 4
    pod_play_stage_parallelism: int = 4
    node_lease_parallelism: int = 4
    #: 0 disables leases entirely (manage pods ignores leases,
    #: reference controller.go:229-234)
    node_lease_duration_seconds: int = 40
    cidr: str = "10.0.0.1/24"
    node_ip: str = "10.0.0.1"
    node_name: str = "kwok-controller"
    node_port: int = 10247
    enable_crds: bool = False
    #: simulation backend: "host" (per-object reference semantics) or
    #: "device" (vectorized TPU tick kernel, host fallback per kind when
    #: a stage set does not lower)
    backend: str = "host"
    device_capacity: int = 4096
    device_tick_ms: int = 100
    #: 0 = single device; N>1 = shard SoA rows over an N-device mesh
    #: (SURVEY §2.9 scale-out; needs N visible jax devices)
    device_mesh_devices: int = 0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KwokConfiguration":
        opts = d.get("options") or d
        def g(key: str, default):
            return opts.get(key, default)
        return cls(
            id=g("id", "kwok-controller"),
            manage_all_nodes=bool(g("manageAllNodes", False)),
            manage_nodes_with_annotation_selector=g("manageNodesWithAnnotationSelector", ""),
            manage_nodes_with_label_selector=g("manageNodesWithLabelSelector", ""),
            disregard_status_with_annotation_selector=g(
                "disregardStatusWithAnnotationSelector", ""
            ),
            disregard_status_with_label_selector=g("disregardStatusWithLabelSelector", ""),
            node_play_stage_parallelism=int(g("nodePlayStageParallelism", 4)),
            pod_play_stage_parallelism=int(g("podPlayStageParallelism", 4)),
            node_lease_parallelism=int(g("nodeLeaseParallelism", 4)),
            node_lease_duration_seconds=int(g("nodeLeaseDurationSeconds", 40)),
            cidr=g("cidr", "10.0.0.1/24"),
            node_ip=g("nodeIP", "10.0.0.1"),
            node_name=g("nodeName", "kwok-controller"),
            node_port=int(g("nodePort", 10247)),
            enable_crds=bool(g("enableCRDs", False)),
            backend=g("backend", "host"),
            device_capacity=int(g("deviceCapacity", 4096)),
            device_tick_ms=int(g("deviceTickMilliseconds", 100)),
            device_mesh_devices=int(g("deviceMeshDevices", 0)),
        )
