"""Multi-document YAML config loading keyed by TypeMeta.

Mirrors the reference's config loader behavior of splitting a config
stream into typed documents by apiVersion/kind
(reference: pkg/config/config.go:271-405 Load/UnmarshalWithType and
FilterWithType :516-544).
"""

from __future__ import annotations

import io
from typing import Any, Dict, Iterable, List, Union

import yaml

from kwok_tpu.api.types import API_VERSION, Stage


def load_documents(source: Union[str, "io.TextIOBase"]) -> List[Dict[str, Any]]:
    """Load all YAML documents from a path or a string of YAML."""
    if hasattr(source, "read"):
        text = source.read()
    elif isinstance(source, str) and "\n" not in source and source.endswith((".yaml", ".yml")):
        with open(source, "r", encoding="utf-8") as f:
            text = f.read()
    else:
        text = source
    return [d for d in yaml.safe_load_all(text) if d is not None]


def filter_by_kind(docs: Iterable[Dict[str, Any]], kind: str) -> List[Dict[str, Any]]:
    """Select documents of one kwok kind (config.go:516-544)."""
    out = []
    for d in docs:
        if d.get("kind") == kind and d.get("apiVersion", API_VERSION) == API_VERSION:
            out.append(d)
    return out


def load_stages(source: Union[str, "io.TextIOBase"]) -> List[Stage]:
    """Load all Stage documents from a YAML path/string."""
    return [Stage.from_dict(d) for d in filter_by_kind(load_documents(source), "Stage")]
