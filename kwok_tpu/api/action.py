"""action/v1alpha1 API: the record/replay wire format.

Mirrors the reference's ``ResourcePatch`` action type
(reference: pkg/apis/action/v1alpha1/resource_patch_types.go:35-77):
one document per observed mutation, carrying the resource type, the
target object, the time offset from the start of the recording, the
method (create/patch/delete), and the raw object template.

The reference keys resources by GVR (group/version/resource); this
framework's store is kind-keyed with the apiVersion carried alongside
(cluster/store.py ``ResourceType``), so ``resource`` here is
``{apiVersion, kind}``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

ACTION_API_VERSION = "action.kwok.x-k8s.io/v1alpha1"

#: ResourcePatch.method values (resource_patch_types.go:66-73)
METHOD_CREATE = "create"
METHOD_PATCH = "patch"
METHOD_DELETE = "delete"


@dataclass
class ResourcePatch:
    """One recorded mutation."""

    #: {"apiVersion": ..., "kind": ...}
    resource: Dict[str, str] = field(default_factory=dict)
    #: {"name": ..., "namespace": ...} (namespace empty for cluster scope)
    target: Dict[str, str] = field(default_factory=dict)
    #: offset from recording start (reference DurationNanosecond)
    duration_nanosecond: int = 0
    method: str = METHOD_PATCH
    #: full object for create/patch (merge-patch semantics on replay)
    template: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "apiVersion": ACTION_API_VERSION,
            "kind": "ResourcePatch",
            "resource": dict(self.resource),
            "target": dict(self.target),
            "durationNanosecond": int(self.duration_nanosecond),
            "method": self.method,
        }
        if self.template is not None:
            d["template"] = self.template
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourcePatch":
        if d.get("kind") != "ResourcePatch":
            raise ValueError(f"not a ResourcePatch document: kind={d.get('kind')!r}")
        return cls(
            resource=dict(d.get("resource") or {}),
            target=dict(d.get("target") or {}),
            duration_nanosecond=int(d.get("durationNanosecond") or 0),
            method=d.get("method") or METHOD_PATCH,
            template=d.get("template"),
        )

    @staticmethod
    def is_resource_patch(doc: Dict[str, Any]) -> bool:
        return (
            doc.get("kind") == "ResourcePatch"
            and doc.get("apiVersion", ACTION_API_VERSION) == ACTION_API_VERSION
        )
