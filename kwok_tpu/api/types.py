"""Stage CRD API types (kwok.x-k8s.io/v1alpha1-compatible).

Dataclass mirror of the reference API surface
(reference: pkg/apis/v1alpha1/stage_types.go:37-271), with YAML/dict
round-trip. These are the *internal* (hub) types: the deprecated
v1alpha1 `statusTemplate`/`statusSubresource`/`statusPatchAs` fields are
folded into `patches` on load, exactly like the reference conversion
(reference: pkg/apis/internalversion/conversion.go:394-425).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

API_VERSION = "kwok.x-k8s.io/v1alpha1"

PATCH_TYPE_JSON = "json"
PATCH_TYPE_MERGE = "merge"
PATCH_TYPE_STRATEGIC = "strategic"


@dataclass
class ResourceRef:
    """Which resource kind a Stage applies to (stage_types.go:70-78)."""

    api_group: str
    kind: str

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceRef":
        return cls(api_group=d.get("apiGroup", "v1"), kind=d["kind"])

    def to_dict(self) -> Dict[str, Any]:
        return {"apiGroup": self.api_group, "kind": self.kind}


@dataclass
class SelectorRequirement:
    """One jq matchExpression (stage_types.go:106-121)."""

    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SelectorRequirement":
        return cls(
            key=d["key"],
            operator=d["operator"],
            values=[str(v) for v in d.get("values") or []],
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"key": self.key, "operator": self.operator}
        if self.values:
            out["values"] = list(self.values)
        return out


@dataclass
class StageSelector:
    """Label/annotation/jq selection (stage_types.go:88-104)."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_annotations: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[SelectorRequirement] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["StageSelector"]:
        if d is None:
            return None
        return cls(
            match_labels=dict(d.get("matchLabels") or {}),
            match_annotations=dict(d.get("matchAnnotations") or {}),
            match_expressions=[
                SelectorRequirement.from_dict(e) for e in d.get("matchExpressions") or []
            ],
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.match_labels:
            out["matchLabels"] = dict(self.match_labels)
        if self.match_annotations:
            out["matchAnnotations"] = dict(self.match_annotations)
        if self.match_expressions:
            out["matchExpressions"] = [e.to_dict() for e in self.match_expressions]
        return out


@dataclass
class ExpressionFrom:
    """An expression-backed value source (stage_types.go:130-150)."""

    expression_from: str

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["ExpressionFrom"]:
        if d is None:
            return None
        return cls(expression_from=d["expressionFrom"])

    def to_dict(self) -> Dict[str, Any]:
        return {"expressionFrom": self.expression_from}


@dataclass
class StageDelay:
    """Transition delay with optional jitter / per-object overrides
    (stage_types.go:123-151)."""

    duration_milliseconds: Optional[int] = None
    duration_from: Optional[ExpressionFrom] = None
    jitter_duration_milliseconds: Optional[int] = None
    jitter_duration_from: Optional[ExpressionFrom] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["StageDelay"]:
        if d is None:
            return None
        return cls(
            duration_milliseconds=d.get("durationMilliseconds"),
            duration_from=ExpressionFrom.from_dict(d.get("durationFrom")),
            jitter_duration_milliseconds=d.get("jitterDurationMilliseconds"),
            jitter_duration_from=ExpressionFrom.from_dict(d.get("jitterDurationFrom")),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.duration_milliseconds is not None:
            out["durationMilliseconds"] = self.duration_milliseconds
        if self.duration_from is not None:
            out["durationFrom"] = self.duration_from.to_dict()
        if self.jitter_duration_milliseconds is not None:
            out["jitterDurationMilliseconds"] = self.jitter_duration_milliseconds
        if self.jitter_duration_from is not None:
            out["jitterDurationFrom"] = self.jitter_duration_from.to_dict()
        return out


@dataclass
class StageEvent:
    """Event emitted when the stage fires (stage_types.go:216-227)."""

    type: str = ""
    reason: str = ""
    message: str = ""

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["StageEvent"]:
        if d is None:
            return None
        return cls(
            type=d.get("type", ""),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"type": self.type, "reason": self.reason, "message": self.message}


@dataclass
class FinalizerItem:
    value: str

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FinalizerItem":
        return cls(value=d["value"])

    def to_dict(self) -> Dict[str, Any]:
        return {"value": self.value}


@dataclass
class StageFinalizers:
    """Finalizer add/remove/empty ops (stage_types.go:229-243)."""

    add: List[FinalizerItem] = field(default_factory=list)
    remove: List[FinalizerItem] = field(default_factory=list)
    empty: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["StageFinalizers"]:
        if d is None:
            return None
        return cls(
            add=[FinalizerItem.from_dict(i) for i in d.get("add") or []],
            remove=[FinalizerItem.from_dict(i) for i in d.get("remove") or []],
            empty=bool(d.get("empty", False)),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.add:
            out["add"] = [i.to_dict() for i in self.add]
        if self.remove:
            out["remove"] = [i.to_dict() for i in self.remove]
        if self.empty:
            out["empty"] = True
        return out


@dataclass
class ImpersonationConfig:
    username: str

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["ImpersonationConfig"]:
        if d is None:
            return None
        return cls(username=d["username"])

    def to_dict(self) -> Dict[str, Any]:
        return {"username": self.username}


@dataclass
class StagePatch:
    """One templated patch (stage_types.go:180-214)."""

    subresource: str = ""
    root: str = ""
    template: str = ""
    type: Optional[str] = None  # json | merge | strategic; None -> merge
    impersonation: Optional[ImpersonationConfig] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StagePatch":
        return cls(
            subresource=d.get("subresource", ""),
            root=d.get("root", ""),
            template=d.get("template", ""),
            type=d.get("type"),
            impersonation=ImpersonationConfig.from_dict(d.get("impersonation")),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.subresource:
            out["subresource"] = self.subresource
        if self.root:
            out["root"] = self.root
        if self.template:
            out["template"] = self.template
        if self.type is not None:
            out["type"] = self.type
        if self.impersonation is not None:
            out["impersonation"] = self.impersonation.to_dict()
        return out


@dataclass
class StageNext:
    """Stage effects (stage_types.go:153-178), with the deprecated
    statusTemplate fields folded into patches (conversion.go:394-425)."""

    event: Optional[StageEvent] = None
    finalizers: Optional[StageFinalizers] = None
    delete: bool = False
    patches: List[StagePatch] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["StageNext"]:
        if d is None:
            return None
        patches = [StagePatch.from_dict(p) for p in d.get("patches") or []]
        status_template = d.get("statusTemplate", "")
        if status_template and not patches:
            impersonation = None
            patch_as = d.get("statusPatchAs")
            if patch_as is not None:
                impersonation = ImpersonationConfig.from_dict(patch_as)
            patches = [
                StagePatch(
                    subresource=d.get("statusSubresource") or "status",
                    root="status",
                    template=status_template,
                    impersonation=impersonation,
                )
            ]
        return cls(
            event=StageEvent.from_dict(d.get("event")),
            finalizers=StageFinalizers.from_dict(d.get("finalizers")),
            delete=bool(d.get("delete", False)),
            patches=patches,
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.event is not None:
            out["event"] = self.event.to_dict()
        if self.finalizers is not None:
            out["finalizers"] = self.finalizers.to_dict()
        if self.delete:
            out["delete"] = True
        if self.patches:
            out["patches"] = [p.to_dict() for p in self.patches]
        return out


@dataclass
class Stage:
    """A single lifecycle stage (stage_types.go:37-68)."""

    name: str
    resource_ref: ResourceRef
    selector: Optional[StageSelector] = None
    weight: int = 0
    weight_from: Optional[ExpressionFrom] = None
    delay: Optional[StageDelay] = None
    next: Optional[StageNext] = None
    immediate_next_stage: bool = False

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "Stage":
        """Parse a full Stage manifest (apiVersion/kind/metadata/spec)."""
        if "spec" in doc:
            meta = doc.get("metadata") or {}
            name = meta.get("name", "")
            spec = doc["spec"]
        else:  # bare spec with a name
            name = doc.get("name", "")
            spec = doc
        return cls(
            name=name,
            resource_ref=ResourceRef.from_dict(spec["resourceRef"]),
            selector=StageSelector.from_dict(spec.get("selector")),
            weight=int(spec.get("weight", 0)),
            weight_from=ExpressionFrom.from_dict(spec.get("weightFrom")),
            delay=StageDelay.from_dict(spec.get("delay")),
            next=StageNext.from_dict(spec.get("next")),
            immediate_next_stage=bool(spec.get("immediateNextStage", False)),
        )

    def to_dict(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {"resourceRef": self.resource_ref.to_dict()}
        if self.selector is not None:
            spec["selector"] = self.selector.to_dict()
        if self.weight:
            spec["weight"] = self.weight
        if self.weight_from is not None:
            spec["weightFrom"] = self.weight_from.to_dict()
        if self.delay is not None:
            spec["delay"] = self.delay.to_dict()
        if self.next is not None:
            spec["next"] = self.next.to_dict()
        if self.immediate_next_stage:
            spec["immediateNextStage"] = True
        return {
            "apiVersion": API_VERSION,
            "kind": "Stage",
            "metadata": {"name": self.name},
            "spec": spec,
        }
