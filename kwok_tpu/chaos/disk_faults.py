"""Seeded disk-fault injection for WAL segments and snapshot files.

The fourth injection layer (after HTTP, process, and commit-boundary —
``kwok_tpu/chaos/__init__.py:1``): the *storage* underneath the store
fails.  Each helper applies one deterministic, seeded corruption to a
file the durability layer owns, modeling the disk's real failure
modes:

- **bit-flip** — silent media corruption mid-file; the checksummed
  frame format (``kwok_tpu/cluster/wal.py:104``) must *detect* it and
  recovery must report the exact lost resourceVersions, never skip.
- **truncate** — a lost tail cut mid-record (torn final frame): the
  legal crash debris shape, but recovery must still flag the torn
  frame and bound the possible loss.
- **torn-write** — a multi-record batched append (the store bulk
  lane's single ``append_many`` write,
  ``kwok_tpu/cluster/store.py:1597``) persisted only partially: the
  batch's prefix must survive, the cut must be detected.
- **fsync-crash** — machine death at the fsync boundary: everything
  after the last fsync vanishes; nothing synced may be lost.

All offsets derive from the caller's ``random.Random``, so a fault
schedule is a pure function of the seed — the chaos-plan contract
(``kwok_tpu/chaos/plan.py:1``) extended to the disk.  Exercised by
``python -m kwok_tpu.chaos --corruption-smoke`` and the DST harness's
``disk-corrupt`` fault (``kwok_tpu/dst/faults.py:1``).
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "DISK_FAULT_KINDS",
    "DiskFaultDriver",
    "bit_flip",
    "bit_flip_line",
    "truncate_mid_record",
    "cut_at",
    "line_offsets",
    "mid_line_offset",
]

DISK_FAULT_KINDS = ("bit-flip", "truncate", "torn-write", "fsync-crash")


def line_offsets(path: str):
    """Byte offsets of each line start (the frame boundaries)."""
    offsets = [0]
    with open(path, "rb") as f:
        data = f.read()
    for i, b in enumerate(data):
        if b == 0x0A and i + 1 < len(data):
            offsets.append(i + 1)
    return offsets, len(data)


def bit_flip(
    path: str,
    rng: random.Random,
    lo_frac: float = 0.0,
    hi_frac: float = 1.0,
) -> Dict[str, int]:
    """Flip one seeded bit inside ``[lo_frac, hi_frac)`` of the file.
    Returns ``{"offset", "bit"}`` for the report/trace."""
    size = os.path.getsize(path)
    if size == 0:
        return {"offset": -1, "bit": -1}
    lo = int(size * lo_frac)
    hi = max(lo + 1, int(size * hi_frac))
    offset = rng.randrange(lo, min(hi, size))
    bit = rng.randrange(8)
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ (1 << bit)]))
        f.flush()
        os.fsync(f.fileno())
    return {"offset": offset, "bit": bit}


def bit_flip_line(
    path: str, rng: random.Random, exclude_last: bool = True
) -> Dict[str, int]:
    """Flip one seeded bit inside a seeded record line — excluding the
    final line by default, so the damage is unambiguous *mid-log*
    corruption (a flipped final line is indistinguishable from torn
    crash debris and gets the torn-tail treatment instead)."""
    offsets, size = line_offsets(path)
    if size == 0:
        return {"offset": -1, "bit": -1}
    if exclude_last and len(offsets) > 1:
        offsets = offsets[:-1]
    start = rng.choice(offsets)
    with open(path, "rb") as f:
        f.seek(start)
        line = f.readline()
    span = max(1, len(line.rstrip(b"\n")))
    offset = start + rng.randrange(span)
    bit = rng.randrange(8)
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ (1 << bit)]))
        f.flush()
        os.fsync(f.fileno())
    return {"offset": offset, "bit": bit}


def mid_line_offset(
    path: str, rng: random.Random, exclude_last: bool = False
) -> Optional[int]:
    """A seeded offset strictly inside a record line (never at a line
    boundary), so a cut there produces a torn frame the scanner can
    see — a truncation at an exact boundary is indistinguishable from
    a log that simply ends there."""
    offsets, size = line_offsets(path)
    if size == 0:
        return None
    if exclude_last and len(offsets) > 1:
        offsets = offsets[:-1]
    start = rng.choice(offsets)
    # find this line's end
    with open(path, "rb") as f:
        f.seek(start)
        line = f.readline()
    if len(line) < 3:
        return None
    return start + rng.randrange(1, len(line) - 1)


def cut_at(path: str, offset: int) -> None:
    """Truncate ``path`` to ``offset`` bytes (the crash/torn-write
    primitive)."""
    with open(path, "r+b") as f:
        f.truncate(max(0, offset))
        f.flush()
        os.fsync(f.fileno())


def truncate_mid_record(path: str, rng: random.Random) -> Dict[str, int]:
    """Cut the file mid-way through a seeded record line.  Returns the
    cut offset (or -1 when the file is too small to cut)."""
    off = mid_line_offset(path, rng)
    if off is None:
        return {"offset": -1}
    cut_at(path, off)
    return {"offset": off}


class DiskFaultDriver:
    """Execute a plan's ``disk:`` faults against a live cluster's
    storage files — the wall-clock twin of
    :class:`~kwok_tpu.chaos.process_faults.ProcessFaultDriver`,
    scheduled from the same plan ``at`` offsets.

    ``target: wal`` hits the apiserver's live log, ``target: snapshot``
    its state file (paths by the kwokctl workdir convention,
    ``kwok_tpu/ctl/components.py:61``).  ``fsync-crash`` SIGKILLs the
    apiserver first (no final save), then cuts the log tail mid-record
    — the closest external approximation of machine death at the fsync
    boundary; the supervisor's restart then exercises the tolerant
    recovery path end to end."""

    def __init__(self, runtime, plan, rng: Optional[random.Random] = None):
        self.runtime = runtime
        self.plan = plan
        self.rng = rng or random.Random(plan.seed ^ 0xD15C)
        #: [{"t", "kind", "target", "path", ...injection info}]
        self.events: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "DiskFaultDriver":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the schedule is exhausted (without cancelling
        pending faults the way :meth:`stop` does)."""
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True

    def run(self) -> None:
        t0 = time.monotonic()
        pending = sorted(self.plan.disk, key=lambda s: s.at)
        while pending and not self._stop.is_set():
            now = time.monotonic() - t0
            if now >= pending[0].at:
                self._apply(pending.pop(0), now)
                continue
            self._stop.wait(min(max(pending[0].at - now, 0.0), 0.25))

    def _target_path(self, target: str, shard: int = 0) -> str:
        # shard 0 lives at the workdir root (the single-store layout);
        # a profile aiming a corruption fault at shard N>0 must hit
        # THAT shard's files, not silently bit-flip shard 0's
        if shard:
            from kwok_tpu.cluster.sharding.layout import (
                shard_state_path,
                shard_wal_path,
            )

            if target == "snapshot":
                return shard_state_path(self.runtime.workdir, shard)
            return shard_wal_path(self.runtime.workdir, shard)
        from kwok_tpu.ctl.components import state_path, wal_path

        if target == "snapshot":
            return state_path(self.runtime.workdir)
        return wal_path(self.runtime.workdir)

    def _apply(self, spec, now: float) -> None:
        if spec.kind not in DISK_FAULT_KINDS:
            # exhaustion windows (disk-full/fsync-error/quota) are
            # armed INSIDE the apiserver daemon (chaos/fs_pressure.py
            # PressureDriver): pressure must hit the process that owns
            # the file handles, not the files from outside
            self.events.append(
                {
                    "t": round(now, 3),
                    "kind": spec.kind,
                    "target": spec.target,
                    "armed": "in-daemon",
                }
            )
            return
        path = self._target_path(spec.target, getattr(spec, "shard", 0))
        info: Dict[str, int] = {"offset": -1}
        try:
            if spec.kind == "fsync-crash":
                self.runtime.signal_component("apiserver", signal.SIGKILL)
                info = truncate_mid_record(path, self.rng)
            elif spec.kind == "bit-flip":
                info = bit_flip_line(path, self.rng, exclude_last=True)
            elif spec.kind in ("truncate", "torn-write"):
                info = truncate_mid_record(path, self.rng)
        except OSError as exc:
            info = {"offset": -1, "error": str(exc)}  # type: ignore[dict-item]
        self.events.append(
            {
                "t": round(now, 3),
                "kind": spec.kind,
                "target": spec.target,
                "path": path,
                **info,
            }
        )
