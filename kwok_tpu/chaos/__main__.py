"""Offline chaos driver: ``python -m kwok_tpu.chaos``.

Three modes over one seeded profile
(:mod:`kwok_tpu.chaos.plan`; reference chaos-as-data precedent
``kwok_tpu/stages/pod-chaos.yaml:1``):

- ``--print-schedule``  render the deterministic fault schedule as
  JSON (what WILL happen for this seed) without touching anything.
- ``--cluster NAME``    drive the profile's process faults against a
  live kwokctl cluster; ``--supervise`` also runs the component
  supervisor so kills recover.  HTTP faults live inside the apiserver
  daemon — create the cluster with ``--chaos-profile`` to enable them.
- ``--smoke``           self-contained durability check (seconds, no
  subprocesses): drive writes through an apiserver facade under
  injected 503s/resets/latency with the retrying client, then replay
  snapshot+WAL into a fresh store and assert byte-identical state —
  zero lost acknowledged writes.  tools/check.sh runs this on every
  check.
- ``--overload-smoke``  self-contained graceful-degradation check: a
  seeded best-effort flood (the plan's ``overload`` fault kind) against
  an apiserver running APF flow control
  (``kwok_tpu.cluster.flowcontrol``) while a system-priority canary
  keeps writing.  Asserts every canary write acks with bounded
  latency, the flood is shed with well-formed 429+Retry-After (zero
  connection errors), and no system-level request was rejected.
  tools/check.sh runs this on every check too.
- ``--corruption-smoke``  self-contained storage-integrity check:
  seeded disk faults (bit-flip, truncate, torn multi-record write,
  fsync-boundary crash, snapshot corruption —
  :mod:`kwok_tpu.chaos.disk_faults`) against the checksummed WAL and
  snapshot files.  Asserts every fault is *detected* (never silently
  absorbed), recovery is bounded and honest (recovered state +
  reported-lost set account for every acked write), and
  point-in-time recovery rebuilds a mid-run capture byte-identically.
  tools/check.sh runs this on every check too.
- ``--exhaustion-smoke``  self-contained resource-exhaustion check:
  seeded disk-full/fsync-error windows (:mod:`kwok_tpu.chaos.fs_pressure`)
  against a live apiserver+WAL.  Asserts degraded read-only mode
  (mutations 503+Retry-After with reason StorageDegraded; reads,
  watches and lease renewals stay live via the emergency reserve),
  /healthz-alive with zero supervisor restarts, re-arm on space
  return, and — after a crash — that durable ∪ visibly-rejected
  accounts for every acked write.  tools/check.sh runs this too.
- ``--failover-smoke``  self-contained HA check: three leader electors
  (cluster/election.py) on one APF-armed apiserver.  Asserts a single
  leader at a time, bounded takeover (2x leaseDuration after a silent
  kill, ~one renew interval after a graceful release), and that a
  stale leadership generation's writes are fenced with 409 while the
  live leader's pass.  tools/check.sh runs this on every check too.
- ``--dst``             deterministic simulation testing
  (kwok_tpu.dst): run the whole control plane in one process on a
  virtual clock, ``--seeds N`` seeded fault interleavings, Kivi-style
  invariant checks over every run's trace.  Any violating seed replays
  exactly (same seed ⇒ byte-identical trace digest).  Exits nonzero on
  any violation.  ``--dst-bug ungated-writer`` injects the test-only
  regression the acceptance gate uses to prove violations are caught;
  ``--dst-bug partial-gang`` un-atomics the gang scheduler's bind lane
  so the gang-atomicity invariant can prove it catches partial gangs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

from kwok_tpu.chaos.http_faults import HttpFaultInjector
from kwok_tpu.chaos.plan import FaultPlan, HttpFaultSpec, load_profile


def run_smoke(seed: int = 42, pods: int = 40, duration: float = 30.0) -> dict:
    """In-process chaos smoke; returns the report dict (raises on any
    lost write or non-convergence)."""
    from kwok_tpu.cluster.apiserver import APIServer
    from kwok_tpu.cluster.client import ApiUnavailable, ClusterClient, RetryPolicy
    from kwok_tpu.cluster.store import Conflict, NotFound, ResourceStore
    from kwok_tpu.cluster.wal import WriteAheadLog
    from kwok_tpu.utils.backoff import Backoff

    def must(fn, *a, **kw):
        """Drive a mutation to an acknowledged outcome, the way the
        controllers do: ApiUnavailable means the op MAY have applied
        (e.g. a chaos reset ate the response) — replay it, treating
        already-applied answers as success.  Conflict, not
        AlreadyExists: the REST client maps every 409 to the base
        Conflict, and nothing here carries rv preconditions, so a 409
        on replay can only mean the first attempt landed."""
        for _ in range(50):
            try:
                return fn(*a, **kw)
            except ApiUnavailable:
                continue
            except Conflict:
                return None  # first attempt applied; the ack was eaten
            except NotFound:
                return None  # delete applied; the ack was eaten
        raise SystemExit("chaos smoke FAILED: mutation never converged")

    plan = FaultPlan(
        seed=seed,
        duration=duration,
        http=HttpFaultSpec(
            latency_p=0.10,
            latency_s=0.01,
            reject_p=0.15,
            reject_status=503,
            retry_after=0.05,
            reset_p=0.08,
        ),
    )
    inj = HttpFaultInjector(plan)
    t_start = time.monotonic()
    with tempfile.TemporaryDirectory() as tmp:
        wal_path = os.path.join(tmp, "wal.jsonl")
        state_path = os.path.join(tmp, "state.json")
        store = ResourceStore()
        store.attach_wal(WriteAheadLog(wal_path, fsync="off"))
        with APIServer(store, fault_injector=inj) as srv:
            client = ClusterClient(
                srv.url,
                retry=RetryPolicy(
                    seed=seed,
                    max_attempts=10,
                    budget_s=30.0,
                    backoff=Backoff(duration=0.02, cap=0.5),
                ),
                client_id="chaos-smoke",
            )
            # every acked write below crossed the faulty boundary
            for i in range(pods):
                must(
                    client.create,
                    {
                        "apiVersion": "v1",
                        "kind": "Pod",
                        "metadata": {"name": f"smoke-{i}", "namespace": "default"},
                        "spec": {"nodeName": f"node-{i % 4}"},
                        "status": {},
                    },
                )
            for i in range(pods):
                must(
                    client.patch,
                    "Pod",
                    f"smoke-{i}",
                    {"status": {"phase": "Running"}},
                    "merge",
                    subresource="status",
                )
            for i in range(0, pods, 5):
                must(client.delete, "Pod", f"smoke-{i}")
            live = store.dump_state()
        # crash: throw the store away, recover snapshot-less from WAL
        recovered = ResourceStore()
        replayed = recovered.replay_wal(wal_path)
        t_recovered = time.monotonic()
        if recovered.dump_state() != live:
            raise SystemExit("chaos smoke FAILED: WAL replay diverged from live state")
        # and the snapshot+compact path: save, recover from both halves
        store.save_file(state_path)
        recovered2 = ResourceStore()
        recovered2.load_file(state_path)
        recovered2.replay_wal(wal_path)
        if recovered2.dump_state() != live:
            raise SystemExit(
                "chaos smoke FAILED: snapshot+WAL recovery diverged from live state"
            )
    expect_pods = pods - len(range(0, pods, 5))
    if recovered.count("Pod") != expect_pods:
        raise SystemExit(
            f"chaos smoke FAILED: {recovered.count('Pod')} pods after recovery, "
            f"want {expect_pods}"
        )
    return {
        "seed": seed,
        "acked_writes": pods * 2 + len(range(0, pods, 5)),
        "replayed_records": replayed,
        "faults": inj.snapshot(),
        "recovery_s": round(t_recovered - t_start, 3),
        "lost_writes": 0,
    }


def run_corruption_smoke(seed: int = 42, pods: int = 24) -> dict:
    """In-process storage-integrity smoke: every seeded disk fault —
    bit-flip, truncate, torn multi-record write, fsync-boundary crash,
    snapshot corruption — must be *detected* (never silently absorbed)
    and recovery must be bounded and honest: the recovered state plus
    the reported-lost set together account for every acked write.
    Also proves PITR: ``build_state(to_rv)`` reproduces a mid-run live
    capture byte-identically.  Raises on any silent loss."""
    import random
    import shutil

    from kwok_tpu.chaos import disk_faults
    from kwok_tpu.cluster.store import ResourceStore
    from kwok_tpu.cluster.wal import (
        WriteAheadLog,
        fsck,
        segment_files,
        write_state_file,
    )
    from kwok_tpu.snapshot.pitr import PitrArchive, boot_recover

    rng = random.Random(seed)
    t_start = time.monotonic()

    def fail(msg):
        raise SystemExit(f"corruption smoke FAILED: {msg}")

    def accounted(acked, boot):
        """Split acked rvs into (reported_lost, silent_lost) via the
        RecoveryReport's own honesty classification — the SAME
        predicate the DST recovery-honesty invariant audits."""
        rep = boot["recovery"]
        if rep is None:
            return [], sorted(acked)
        return rep.account(acked)

    results = {}
    with tempfile.TemporaryDirectory() as tmp:
        wal_p = os.path.join(tmp, "wal.jsonl")
        state_p = os.path.join(tmp, "state.json")
        pitr_root = os.path.join(tmp, "pitr")
        store = ResourceStore()
        store.attach_wal(
            WriteAheadLog(
                wal_p, fsync="off", segment_bytes=1500, archive_dir=pitr_root
            )
        )
        archive = PitrArchive(pitr_root)
        acked: set = set()

        def track(fn, *a, **kw):
            rv0 = store.resource_version
            out = fn(*a, **kw)
            acked.update(range(rv0 + 1, store.resource_version + 1))
            return out

        def daemon_save():
            state = store.dump_state(copy=False)
            write_state_file(state_p, state)
            archive.add_snapshot(state)
            store.compact_wal(int(state["resourceVersion"]))

        pod = lambda n: {  # noqa: E731
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": n, "namespace": "default"},
            "spec": {"nodeName": f"node-{rng.randrange(4)}"},
            "status": {},
        }
        cut = None
        for i in range(pods):
            track(store.create, pod(f"smoke-{i}"))
            if i == pods // 3:
                daemon_save()
            if i == pods // 2:
                track(
                    store.bulk,
                    [
                        {
                            "verb": "patch",
                            "kind": "Pod",
                            "name": f"smoke-{j}",
                            "data": {"status": {"phase": "Running"}},
                            "subresource": "status",
                        }
                        for j in range(i)
                    ],
                )
                cut = (store.resource_version, store.dump_state())
        for i in range(0, pods, 5):
            track(store.delete, "Pod", f"smoke-{i}")
        track(
            store.apply_status_batch,
            "Pod",
            [
                ("default", f"smoke-{i}", {"phase": "Succeeded"})
                for i in range(1, pods, 7)
            ],
        )
        live = store.dump_state()

        # ---- point-in-time recovery: byte-identical rebuild ---------
        built, info = archive.build_state(cut[0], live_wal=wal_p)
        if json.dumps(built, sort_keys=True) != json.dumps(
            cut[1], sort_keys=True
        ):
            fail(
                f"PITR rebuild at rv {cut[0]} diverged from the live "
                f"capture (base rv {info['base_rv']})"
            )
        results["pitr"] = {
            "to_rv": cut[0],
            "base_rv": info["base_rv"],
            "byte_identical": True,
        }

        # pristine fsck must pass
        clean = fsck(wal_p, snapshot=state_p, archive=pitr_root)
        if not clean["ok"]:
            fail(f"fsck flagged a pristine log: {clean}")

        def clone(name):
            d = os.path.join(tmp, name)
            os.makedirs(d)
            for fp in segment_files(wal_p):
                shutil.copy(fp, os.path.join(d, os.path.basename(fp)))
            shutil.copy(state_p, os.path.join(d, "state.json"))
            shutil.copytree(pitr_root, os.path.join(d, "pitr"))
            return (
                os.path.join(d, "wal.jsonl"),
                os.path.join(d, "state.json"),
                os.path.join(d, "pitr"),
            )

        def recover(paths):
            t0 = time.monotonic()
            fresh = ResourceStore()
            boot = boot_recover(fresh, paths[1], paths[0], pitr_root=paths[2])
            return fresh, boot, time.monotonic() - t0

        # ---- bit-flip: mid-log corruption must be DETECTED ----------
        paths = clone("bitflip")
        target = rng.choice(
            [f for f in segment_files(paths[0]) if os.path.getsize(f) > 0]
        )
        flip = disk_faults.bit_flip_line(target, rng, exclude_last=True)
        fresh, boot, dt = recover(paths)
        rep = boot["recovery"]
        if not rep.corruptions and not rep.torn_tail:
            fail(f"bit-flip at {target}:{flip} was silently absorbed")
        bad = fsck(paths[0], snapshot=paths[1], archive=paths[2])
        if bad["ok"]:
            fail("fsck passed a bit-flipped log")
        reported, silent = accounted(acked, boot)
        if silent:
            fail(f"bit-flip: acked rvs {silent[:10]} lost WITHOUT report")
        results["bit-flip"] = {
            "detected": True,
            "acked_lost_reported": len(reported),
            "silent_lost": 0,
            "recovery_s": round(dt, 3),
        }

        # ---- truncate: lost tail cut mid-record ---------------------
        paths = clone("truncate")
        disk_faults.truncate_mid_record(paths[0], rng)
        fresh, boot, dt = recover(paths)
        rep = boot["recovery"]
        if not rep.torn_tail and not rep.corruptions:
            fail("truncation was silently absorbed")
        if rep.tail_after_rv is None:
            fail("truncation did not bound the possible tail loss")
        reported, silent = accounted(acked, boot)
        if silent:
            fail(f"truncate: acked rvs {silent[:10]} lost WITHOUT report")
        results["truncate"] = {
            "detected": True,
            "acked_lost_reported": len(reported),
            "silent_lost": 0,
            "recovery_s": round(dt, 3),
        }

        # ---- snapshot corruption: fall back + replay, zero loss -----
        paths = clone("snapcorrupt")
        disk_faults.bit_flip(paths[1], rng, 0.2, 0.8)
        fresh, boot, dt = recover(paths)
        if not boot["fell_back"]:
            fail("corrupt snapshot was loaded without detection")
        if fresh.dump_state() != live:
            fail(
                "snapshot-fallback recovery diverged from live state "
                f"(fallback rv {boot['fallback_rv']})"
            )
        results["snapshot-corrupt"] = {
            "detected": True,
            "fallback_rv": boot["fallback_rv"],
            "silent_lost": 0,
            "recovery_s": round(dt, 3),
        }

    # ---- torn multi-record write (standalone scene) -----------------
    with tempfile.TemporaryDirectory() as tmp:
        wal_p = os.path.join(tmp, "wal.jsonl")
        s2 = ResourceStore()
        s2.attach_wal(WriteAheadLog(wal_p, fsync="off"))
        # one deferred batch -> one multi-record append_many write
        s2.bulk(
            [
                {
                    "verb": "create",
                    "data": {
                        "apiVersion": "v1",
                        "kind": "Pod",
                        "metadata": {
                            "name": f"torn-{i}",
                            "namespace": "default",
                        },
                        "spec": {},
                        "status": {},
                    },
                }
                for i in range(8)
            ]
        )
        offsets, size = disk_faults.line_offsets(wal_p)
        keep_lines = rng.randrange(2, len(offsets) - 1)
        cut_off = offsets[keep_lines] + rng.randrange(
            1, offsets[keep_lines + 1] - offsets[keep_lines] - 1
        )
        disk_faults.cut_at(wal_p, cut_off)
        t0 = time.monotonic()
        fresh = ResourceStore()
        rep = fresh.recover_wal(wal_p)
        dt = time.monotonic() - t0
        if not rep.torn_tail:
            fail("torn multi-record write was silently absorbed")
        if fresh.count("Pod") != keep_lines:
            fail(
                f"torn write: {fresh.count('Pod')} records survive, "
                f"want the batch prefix {keep_lines}"
            )
        results["torn-write"] = {
            "detected": True,
            "batch_prefix_kept": keep_lines,
            "silent_lost": 0,
            "recovery_s": round(dt, 3),
        }

    # ---- fsync-boundary crash (standalone scene) --------------------
    with tempfile.TemporaryDirectory() as tmp:
        wal_p = os.path.join(tmp, "wal.jsonl")
        s3 = ResourceStore()
        wal = WriteAheadLog(wal_p, fsync="off")
        s3.attach_wal(wal)
        for i in range(10):
            s3.create(
                {
                    "apiVersion": "v1",
                    "kind": "Pod",
                    "metadata": {"name": f"sync-{i}", "namespace": "default"},
                    "spec": {},
                    "status": {},
                }
            )
        wal.sync()
        synced_state = s3.dump_state()
        synced_size = os.path.getsize(wal_p)
        for i in range(10, 16):
            s3.create(
                {
                    "apiVersion": "v1",
                    "kind": "Pod",
                    "metadata": {"name": f"sync-{i}", "namespace": "default"},
                    "spec": {},
                    "status": {},
                }
            )
        wal.close()
        # machine crash: the unsynced tail vanishes, typically leaving
        # a partial frame behind
        offsets, size = disk_faults.line_offsets(wal_p)
        first_unsynced = next(o for o in offsets if o >= synced_size)
        disk_faults.cut_at(
            wal_p, first_unsynced + rng.randrange(1, 20)
        )
        t0 = time.monotonic()
        fresh = ResourceStore()
        rep = fresh.recover_wal(wal_p)
        dt = time.monotonic() - t0
        if fresh.dump_state() != synced_state:
            fail("fsync-boundary crash lost SYNCED data")
        if not rep.torn_tail:
            fail("fsync-boundary crash tail was silently absorbed")
        results["fsync-crash"] = {
            "detected": True,
            "synced_rv_preserved": rep.recovered_rv,
            "silent_lost": 0,
            "recovery_s": round(dt, 3),
        }

    # ---- sharded scene: one shard's damage, union-accounted ---------
    # (kwok_tpu/cluster/sharding): mid-log corruption on ONE shard's
    # WAL must fail the sharded fsck, recovery must detect it and
    # account every acked rv over the UNION of the shards (honest,
    # bounded to the damaged shard's slice), and the intact shard's
    # objects must all survive.
    from kwok_tpu.cluster.sharding import namespaces_covering_shards
    from kwok_tpu.cluster.wal import fsck_sharded
    from kwok_tpu.snapshot.sharded import open_sharded_store

    with tempfile.TemporaryDirectory() as tmp:
        opened = open_sharded_store(
            tmp, 2, namespace_finalizers=False, wal_fsync="off", pitr=False
        )
        sstore = opened["store"]
        ns_by_shard = namespaces_covering_shards(2)
        sacked: set = set()

        def strack(fn, *a, **kw):
            rv0 = sstore.resource_version
            out = fn(*a, **kw)
            sacked.update(range(rv0 + 1, sstore.resource_version + 1))
            return out

        for j in range(10):
            for s, ns in enumerate(ns_by_shard):
                p = pod(f"sh-{j}")
                p["metadata"]["namespace"] = ns
                strack(sstore.create, p)
        shard0_names = {
            (o.get("metadata") or {}).get("name")
            for o in sstore.list("Pod", namespace=ns_by_shard[0])[0]
        }
        for w in opened["wals"]:
            w.close()

        clean = fsck_sharded(tmp)
        if not clean["ok"] or clean["shards"] != 2:
            fail(f"sharded fsck flagged a pristine workdir: {clean}")

        from kwok_tpu.cluster.sharding.layout import shard_wal_path

        disk_faults.bit_flip_line(
            shard_wal_path(tmp, 1), rng, exclude_last=True
        )
        bad = fsck_sharded(tmp)
        if bad["ok"]:
            fail("sharded fsck passed a workdir with one damaged shard")

        t0 = time.monotonic()
        reopened = open_sharded_store(
            tmp, 2, namespace_finalizers=False, wal_fsync="off", pitr=False
        )
        dt = time.monotonic() - t0
        rep = reopened["report"]
        if not rep.corruptions and not rep.torn_tail:
            fail("one-shard bit-flip was silently absorbed by recovery")
        reported, silent = rep.account(sacked)
        if silent:
            fail(f"sharded: acked rvs {silent[:10]} lost WITHOUT report")
        survivors = {
            (o.get("metadata") or {}).get("name")
            for o in reopened["store"].list(
                "Pod", namespace=ns_by_shard[0]
            )[0]
        }
        if survivors != shard0_names:
            fail(
                "damage on shard 1 cost shard 0 objects: "
                f"{sorted(shard0_names - survivors)[:5]}"
            )
        for w in reopened["wals"]:
            w.close()
        results["sharded-isolation"] = {
            "detected": True,
            "acked_lost_reported": len(reported),
            "silent_lost": 0,
            "intact_shard_preserved": True,
            "recovery_s": round(dt, 3),
        }

    return {
        "seed": seed,
        "acked_writes": len(acked),
        "faults": results,
        "total_s": round(time.monotonic() - t_start, 3),
        "silently_lost_acked_writes": 0,
    }


def run_exhaustion_smoke(seed: int = 42, pods: int = 16) -> dict:
    """In-process resource-exhaustion smoke: seeded disk-full and
    fsync-error windows against a live apiserver+WAL.  Asserts the
    acceptance contract of the degraded read-only mode:

    - zero silently-lost acked writes: after a crash at the end,
      durable-after-recovery ∪ visibly-rejected accounts for every ack
      (the ``RecoveryReport.account`` predicate, same as the DST
      ``exhaustion-honesty`` invariant);
    - during a window, mutations are refused with 503 + Retry-After +
      machine-readable reason StorageDegraded while reads, watches and
      lease renewals (via the emergency reserve) stay live;
    - /healthz stays 200 and the component supervisor performs ZERO
      restarts (degraded is tracked, not "fixed");
    - writes re-arm once pressure clears (``wait_writable``), and the
      degraded-aware client retry rides the window out.
    """
    import random
    import threading

    from kwok_tpu.chaos.fs_pressure import FsPressure
    from kwok_tpu.cluster.apiserver import APIServer
    from kwok_tpu.cluster.client import APIError, ClusterClient, RetryPolicy
    from kwok_tpu.cluster.election import LeaderElector
    from kwok_tpu.cluster.store import ResourceStore
    from kwok_tpu.cluster.wal import WriteAheadLog
    from kwok_tpu.ctl.runtime import ComponentSupervisor
    from kwok_tpu.snapshot.pitr import boot_recover
    from kwok_tpu.utils.backoff import Backoff

    rng = random.Random(seed)
    t_start = time.monotonic()

    def fail(msg):
        raise SystemExit(f"exhaustion smoke FAILED: {msg}")

    def pod(n):
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": n, "namespace": "default"},
            "spec": {"nodeName": f"node-{rng.randrange(4)}"},
            "status": {},
        }

    class _LiveRuntime:
        """In-process runtime stub over the live server: alive, never
        restartable — start_component firing at all IS the failure."""

        def __init__(self, client):
            self._client = client
            self.restarts = 0

        def load_components(self):
            from kwok_tpu.ctl.components import Component

            return [Component(name="apiserver", args=[])]

        def component_alive(self, name):
            return True

        def start_component(self, comp):
            self.restarts += 1

        def client(self, timeout=2.0):
            return self._client

    results = {}
    with tempfile.TemporaryDirectory() as tmp:
        wal_p = os.path.join(tmp, "wal.jsonl")
        store = ResourceStore()
        wal = WriteAheadLog(wal_p, fsync="always")
        store.attach_wal(wal)
        acked: set = set()

        def track(fn, *a, **kw):
            rv0 = store.resource_version
            out = fn(*a, **kw)
            acked.update(range(rv0 + 1, store.resource_version + 1))
            return out

        with APIServer(store) as srv:
            client = ClusterClient(
                srv.url,
                retry=RetryPolicy(
                    seed=seed,
                    max_attempts=20,
                    budget_s=30.0,
                    backoff=Backoff(duration=0.02, cap=0.2),
                    # production clients honor the degraded Retry-After
                    # (~5s); the smoke polls fast so the whole gate
                    # stays inside check.sh's budget
                    honor_retry_after=False,
                ),
                client_id="kwokctl",
            )
            # raw client sees the 503s instead of retrying them
            raw = ClusterClient(
                srv.url,
                retry=RetryPolicy(
                    max_attempts=1,
                    budget_s=5.0,
                    backoff=Backoff(duration=0.0, cap=0.0),
                    retry_statuses=(),
                ),
                client_id="exhaustion-raw",
            )
            elector = LeaderElector(
                ClusterClient(srv.url, client_id="system:smoke"),
                "kwok-controller",
                "smoke-replica",
                lease_duration=30.0,
                rng=random.Random(seed),
            )
            elector.try_acquire_or_renew()
            if not elector.is_leader():
                fail("elector never acquired its lease pre-window")
            rt = _LiveRuntime(client)
            sup = ComponentSupervisor(rt, rng=random.Random(seed))

            for i in range(pods):
                track(client.create, pod(f"pre-{i}"))
            watcher = client.watch("Lease", namespace="kube-system")

            def run_window(kind, tag, t0):
                # t0: per-window supervisor time base — ticks must stay
                # monotonic across windows (the supervisor's budget
                # bookkeeping assumes a forward clock)
                shim = FsPressure(kind)
                wal.set_pressure(shim)
                # the in-flight write rides the reserve: acked + durable
                track(raw.create, pod(f"{tag}-inflight"))
                if store.storage_degraded() is None:
                    fail(f"{kind}: window did not degrade storage")
                okz, reason = client.readiness()
                if okz or reason != "StorageDegraded":
                    fail(f"{kind}: /readyz did not report degraded "
                         f"({okz}, {reason})")
                if not client.healthy():
                    fail(f"{kind}: /healthz went down — degraded must "
                         "stay alive")
                rejected = 0
                for i in range(4):
                    try:
                        raw.create(pod(f"{tag}-rej-{i}"))
                        fail(f"{kind}: mutation acked while degraded")
                    except APIError as exc:
                        if exc.code != 503 or exc.reason != "StorageDegraded":
                            fail(
                                f"{kind}: rejection was {exc.code}/"
                                f"{exc.reason}, want 503/StorageDegraded"
                            )
                        rejected += 1
                if not rejected:
                    fail(f"{kind}: no visible rejections in the window")
                # Retry-After must ride the 503 (parseable back-off)
                import http.client as hc

                host, port = srv.address
                c = hc.HTTPConnection(host, port, timeout=5)
                c.request(
                    "POST",
                    "/r/pods",
                    body=json.dumps(pod(f"{tag}-ra")),
                    headers={"Content-Type": "application/json"},
                )
                resp = c.getresponse()
                resp.read()
                if resp.status != 503 or not resp.getheader("Retry-After"):
                    fail(f"{kind}: 503 without Retry-After")
                c.close()
                # lease renewals ride the reserve: HA must not collapse
                rv0 = store.resource_version
                for _ in range(3):
                    elector.renew_once()
                if not elector.is_leader():
                    fail(f"{kind}: leader lost its lease in the window")
                acked.update(range(rv0 + 1, store.resource_version + 1))
                # reads and watches stay live
                items, _ = client.list("Pod")
                if not items:
                    fail(f"{kind}: reads went dark while degraded")
                ev = watcher.next(timeout=5.0)
                if ev is None:
                    fail(f"{kind}: watch stream starved while degraded")
                # supervisor: degraded is tracked, never restarted
                for t in (0.0, 0.5, 1.0, 1.5):
                    sup.tick(now=t0 + t)
                if rt.restarts:
                    fail(f"{kind}: supervisor restarted a degraded "
                         "component")
                if sup.degraded.get("apiserver") != "StorageDegraded":
                    fail(f"{kind}: supervisor did not track degraded "
                         f"state ({sup.degraded})")
                # degraded-aware retry: a retrying client rides it out
                done = {}

                def late_write():
                    done["obj"] = client.create(pod(f"{tag}-retried"))

                th = threading.Thread(target=late_write, daemon=True)
                th.start()
                time.sleep(0.3)
                wal.set_pressure(None)
                if not client.wait_writable(timeout=10.0):
                    fail(f"{kind}: writes never re-armed after the "
                         "window cleared")
                th.join(timeout=10.0)
                if th.is_alive() or "obj" not in done:
                    fail(f"{kind}: retrying client never converged "
                         "after re-arm")
                rv = int(
                    (done["obj"].get("metadata") or {}).get(
                        "resourceVersion", 0
                    )
                )
                acked.add(rv)
                # post-window writes flow normally again
                track(client.create, pod(f"{tag}-post"))
                for t in (2.0, 2.5):
                    sup.tick(now=t0 + t)
                if sup.degraded:
                    fail(f"{kind}: supervisor still sees degraded "
                         "after re-arm")
                return {
                    "rejected": rejected,
                    "retry_stats": client.retry_stats(),
                    "shim": shim.snapshot(),
                }

            results["disk-full"] = run_window("disk-full", "df", t0=0.0)
            results["fsync-error"] = run_window(
                "fsync-error", "fe", t0=100.0
            )
            if client.retry_stats()["degraded"] == 0:
                fail("degraded retries were never counted distinctly")
            watcher.stop()
            elector.stop(release=True)
            live = store.dump_state()

        # crash: recover from the WAL alone; every ack must be
        # accounted durable (nothing was reported lost, nothing silent)
        wal.close()
        fresh = ResourceStore()
        boot = boot_recover(fresh, None, wal_p)
        rep = boot["recovery"]
        if rep is None:
            fail("no recovery report from boot_recover")
        reported, silent = rep.account(acked)
        if silent:
            fail(f"acked rvs {silent[:10]} lost WITHOUT report")
        if reported:
            fail(
                f"acked rvs {reported[:10]} reported lost — exhaustion "
                "windows must not lose acked writes at all"
            )
        if fresh.dump_state() != live:
            fail("post-crash recovery diverged from live state")

    # ---- sharded scene: one shard's full disk degrades ONLY it ------
    # (kwok_tpu/cluster/sharding): writes routed to the pressured
    # shard 503 with reason StorageDegraded, the other shard stays
    # writable, /readyz names the degraded shard set, and clearing
    # the pressure re-arms just that shard.
    from kwok_tpu.cluster.sharding import namespaces_covering_shards
    from kwok_tpu.snapshot.sharded import open_sharded_store

    with tempfile.TemporaryDirectory() as tmp:
        opened = open_sharded_store(
            tmp, 2, namespace_finalizers=False, wal_fsync="off", pitr=False
        )
        sstore = opened["store"]
        wals = opened["wals"]
        # one namespace per shard
        ns_by_shard = namespaces_covering_shards(2)

        def ns_pod(ns, n):
            p = pod(n)
            p["metadata"]["namespace"] = ns
            return p

        with APIServer(sstore) as srv:
            sraw = ClusterClient(
                srv.url,
                retry=RetryPolicy(
                    max_attempts=1,
                    budget_s=5.0,
                    backoff=Backoff(duration=0.0, cap=0.0),
                    retry_statuses=(),
                ),
                client_id="exhaustion-sharded",
            )
            for s, ns in enumerate(ns_by_shard):
                sraw.create(ns_pod(ns, "warm"))
            shim = FsPressure("disk-full")
            wals[1].set_pressure(shim)
            # the first write into the window rides shard 1's reserve
            # (acked + durable), then the shard degrades
            sraw.create(ns_pod(ns_by_shard[1], "inflight"))
            deg = sstore.storage_degraded()
            if deg is None or deg.get("shards") != [1]:
                fail(f"sharded: degraded shard set wrong: {deg}")
            try:
                sraw.create(ns_pod(ns_by_shard[1], "rej"))
                fail("sharded: degraded shard acked a write")
            except APIError as exc:
                if exc.code != 503 or exc.reason != "StorageDegraded":
                    fail(
                        f"sharded: rejection was {exc.code}/{exc.reason}, "
                        "want 503/StorageDegraded"
                    )
            # the OTHER shard keeps accepting writes mid-window
            sraw.create(ns_pod(ns_by_shard[0], "cross"))
            # /readyz names the degraded shard set
            import http.client as hc

            host, port = srv.address
            c = hc.HTTPConnection(host, port, timeout=5)
            c.request("GET", "/readyz")
            resp = c.getresponse()
            body = json.loads(resp.read() or b"{}")
            c.close()
            if resp.status != 503 or (
                (body.get("storage") or {}).get("shards") != [1]
            ):
                fail(
                    f"sharded: /readyz did not report the degraded "
                    f"shard set ({resp.status}, {body})"
                )
            # reads stay live across ALL shards
            items, _ = sraw.list("Pod")
            if len(items) < 3:
                fail("sharded: reads went dark while one shard degraded")
            wals[1].set_pressure(None)
            if not sstore.probe_writable():
                fail("sharded: shard never re-armed after the window")
            sraw.create(ns_pod(ns_by_shard[1], "post"))
            if sstore.storage_degraded() is not None:
                fail("sharded: still degraded after re-arm")
        for w in wals:
            w.close()
        results["sharded-isolation"] = {
            "degraded_shard": 1,
            "other_shard_writable": True,
            "readyz_shards": [1],
        }

    return {
        "seed": seed,
        "acked_writes": len(acked),
        "windows": results,
        "rearms": 2,
        "supervisor_restarts": 0,
        "silently_lost_acked_writes": 0,
        "total_s": round(time.monotonic() - t_start, 3),
    }


def run_overload_smoke(
    seed: int = 42, duration: float = 2.0
) -> dict:
    """In-process overload smoke; returns the report dict (raises on
    any lost canary write, hung/reset shed connection, or system-level
    rejection)."""
    from kwok_tpu.chaos.http_faults import OverloadDriver
    from kwok_tpu.chaos.plan import OverloadWindow
    from kwok_tpu.cluster.apiserver import APIServer
    from kwok_tpu.cluster.client import ClusterClient, RetryPolicy
    from kwok_tpu.cluster.flowcontrol import (
        DEFAULT_LEVELS,
        FlowConfig,
        FlowController,
        PriorityLevel,
    )
    from kwok_tpu.cluster.store import ResourceStore
    from kwok_tpu.utils.backoff import Backoff

    plan = FaultPlan(
        seed=seed,
        duration=duration + 30,
        http=HttpFaultSpec(
            overloads=[
                OverloadWindow(
                    at=0.0, duration=duration, rps=2000, clients=8
                )
            ]
        ),
    )
    # a deliberately tiny budget: best-effort gets one seat and almost
    # no queue, so the flood saturates it instantly while system keeps
    # its own seats
    levels = tuple(
        lv
        if lv.name != "best-effort"
        else PriorityLevel(
            "best-effort", shares=lv.shares, queues=2,
            queue_wait_s=0.1, queue_limit=2,
        )
        for lv in DEFAULT_LEVELS
    )
    flow = FlowController(
        FlowConfig(max_inflight=8, levels=levels), seed=seed
    )
    store = ResourceStore()
    # a populated cluster: the flood lists pods, and the point of the
    # smoke is a flood whose per-request cost outruns one best-effort
    # seat — an empty list would be served faster than it arrives
    store.bulk(
        [
            {
                "verb": "create",
                "data": {
                    "apiVersion": "v1",
                    "kind": "Pod",
                    "metadata": {
                        "name": f"ballast-{i}",
                        "namespace": "default",
                    },
                    "spec": {"nodeName": f"node-{i % 8}"},
                    "status": {"phase": "Running"},
                },
            }
            for i in range(2000)
        ]
    )
    t_start = time.monotonic()
    with APIServer(store, flow=flow) as srv:
        driver = OverloadDriver(plan, srv.url).start()
        client = ClusterClient(
            srv.url,
            retry=RetryPolicy(
                seed=seed,
                max_attempts=10,
                budget_s=30.0,
                backoff=Backoff(duration=0.02, cap=0.5),
            ),
            client_id="kwokctl",  # system priority by default schema
        )
        canaries = 0
        worst_latency = 0.0
        while time.monotonic() - t_start < duration:
            t0 = time.monotonic()
            client.create(
                {
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {
                        "name": f"canary-{canaries}",
                        "namespace": "default",
                    },
                    "data": {"i": str(canaries)},
                }
            )
            worst_latency = max(worst_latency, time.monotonic() - t0)
            canaries += 1
            time.sleep(0.01)
        if not driver.wait(timeout=30):
            driver.stop()
            raise SystemExit("overload smoke FAILED: flood never finished")
        counters = driver.snapshot()
        levels_snap = flow.snapshot()
        if store.count("ConfigMap") != canaries:
            raise SystemExit(
                f"overload smoke FAILED: {store.count('ConfigMap')}/"
                f"{canaries} canary writes survived the flood"
            )
        if counters["shed"] == 0:
            raise SystemExit(
                "overload smoke FAILED: the flood was never shed "
                f"(flow control inactive? {counters})"
            )
        if counters["shed_without_retry_after"]:
            raise SystemExit(
                "overload smoke FAILED: "
                f"{counters['shed_without_retry_after']} 429s lacked "
                "Retry-After"
            )
        if counters["conn_errors"]:
            raise SystemExit(
                "overload smoke FAILED: "
                f"{counters['conn_errors']} flood connections hung/reset "
                "instead of a typed rejection"
            )
        if levels_snap["system"]["rejected"]:
            raise SystemExit(
                "overload smoke FAILED: system-priority traffic was shed "
                f"({levels_snap['system']})"
            )
    return {
        "seed": seed,
        "canary_writes": canaries,
        "canary_worst_latency_s": round(worst_latency, 3),
        "flood": counters,
        "levels": levels_snap,
        "lost_writes": 0,
    }


def run_fleet_smoke(
    seed: int = 42, tenants: int = 1000, flood_seconds: float = 1.5
) -> dict:
    """In-process fleet smoke (kwok_tpu.fleet): one apiserver hosting
    ``tenants`` virtual control planes.  Four phases, SystemExit on any
    violation:

    1. cold-start every tenant with its first write (per-tenant APF
       level + namespace bootstrap + shard pin on first touch) and
       bound the cold-start latency;
    2. seeded neighbor flood: saturate ONE tenant's priority level
       from threads while a victim tenant keeps issuing its own
       traffic — the victim must see ZERO 429s and a bounded p99, the
       host system level must shed nothing, and the flood itself must
       have been shed (else the probe is vacuous);
    3. scale-to-zero: advance the registry's injected clock past the
       cold threshold, sweep, assert every binding was dropped, then
       cold-start one tenant again within the bound — with its data
       intact across the park/unpark;
    4. leak check: sampled tenants each see exactly their own objects
       through the scoped surface while the host store carries every
       tenant's (prefixed) truth.
    """
    import random
    import threading
    import urllib.error
    import urllib.request

    from kwok_tpu.cluster.apiserver import APIServer
    from kwok_tpu.cluster.flowcontrol import FlowController
    from kwok_tpu.cluster.store import ResourceStore
    from kwok_tpu.fleet import FleetRegistry, fleet_flow_config
    from kwok_tpu.fleet.tenant import fleet_tenant_ids
    from kwok_tpu.utils.clock import FakeClock

    cold_start_bound_s = 2.0
    victim_p99_bound_s = 1.0

    ids = fleet_tenant_ids(tenants)
    clock = FakeClock(0.0)
    store = ResourceStore()
    registry = FleetRegistry(
        store, ids, clock=clock, idle_after_s=60.0, cold_after_s=120.0
    )
    # tiny per-tenant budget: one guaranteed seat, almost no queue —
    # the flood saturates its own level instantly while every other
    # level keeps its seats
    flow = FlowController(
        fleet_flow_config(ids, max_inflight=16, queue_wait_s=0.1, queue_limit=2),
        seed=seed,
    )

    def percentile(vals, q):
        if not vals:
            return 0.0
        s = sorted(vals)
        return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]

    with APIServer(store, flow=flow, fleet=registry) as srv:

        def req(method, path, tenant=None, body=None, timeout=10.0):
            data = json.dumps(body).encode() if body is not None else None
            r = urllib.request.Request(
                srv.url + path, data=data, method=method
            )
            if tenant is not None:
                r.add_header("X-Kwok-Tenant", tenant)
            if data is not None:
                r.add_header("Content-Type", "application/json")
            try:
                with urllib.request.urlopen(r, timeout=timeout) as resp:
                    return resp.status, json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                e.read()
                return e.code, None

        # ----- phase 1: cold-start every tenant with its first write
        cold_lat = []
        for tid in ids:
            t0 = time.monotonic()
            status, _ = req(
                "POST",
                "/r/configmaps",
                tenant=tid,
                body={
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {"name": f"{tid}-cm", "namespace": "default"},
                    "data": {"owner": tid},
                },
            )
            cold_lat.append(time.monotonic() - t0)
            if status not in (200, 201):
                raise SystemExit(
                    f"fleet smoke FAILED: tenant {tid} first write -> {status}"
                )
        cold_p99 = percentile(cold_lat, 0.99)
        if cold_p99 > cold_start_bound_s:
            raise SystemExit(
                f"fleet smoke FAILED: cold-start p99 {cold_p99:.3f}s "
                f"exceeds {cold_start_bound_s}s across {tenants} tenants"
            )
        snap = registry.snapshot()
        if snap["warm"] != tenants or snap["cold_starts"] != tenants:
            raise SystemExit(
                f"fleet smoke FAILED: expected {tenants} warm tenants "
                f"after first touch, got {snap}"
            )

        # ----- phase 2: seeded neighbor flood -----------------------
        rng = random.Random(seed)
        flooder = ids[rng.randrange(len(ids))]
        victim = ids[(ids.index(flooder) + 1) % len(ids)]
        # quiet baseline for the victim: its own list latency with no
        # neighbor load, so the report can carry an isolation RATIO
        # (flooded p99 / quiet p99) alongside the absolute bound
        baseline_lat = []
        for _ in range(30):
            t0 = time.monotonic()
            req("GET", "/r/configmaps", tenant=victim)
            baseline_lat.append(time.monotonic() - t0)
        baseline_p99 = percentile(baseline_lat, 0.99)
        stop = threading.Event()
        flood_counts = {"ok": 0, "shed": 0, "errors": 0}
        lock = threading.Lock()

        def flood_worker():
            while not stop.is_set():
                try:
                    status, _ = req(
                        "GET", "/r/configmaps", tenant=flooder, timeout=5.0
                    )
                except Exception:  # noqa: BLE001 — hung/reset socket
                    with lock:
                        flood_counts["errors"] += 1
                    continue
                with lock:
                    if status == 429:
                        flood_counts["shed"] += 1
                    elif status == 200:
                        flood_counts["ok"] += 1
                    else:
                        flood_counts["errors"] += 1

        threads = [
            threading.Thread(target=flood_worker, daemon=True)
            for _ in range(6)
        ]
        for th in threads:
            th.start()
        victim_lat = []
        victim_shed = 0
        t_flood0 = time.monotonic()
        while time.monotonic() - t_flood0 < flood_seconds:
            t0 = time.monotonic()
            status, _ = req("GET", "/r/configmaps", tenant=victim)
            victim_lat.append(time.monotonic() - t0)
            if status == 429:
                victim_shed += 1
        stop.set()
        for th in threads:
            th.join(timeout=10.0)
        levels_snap = flow.snapshot()
        if flood_counts["shed"] == 0:
            raise SystemExit(
                "fleet smoke FAILED: the tenant flood was never shed "
                f"(per-tenant level inactive? {flood_counts})"
            )
        if flood_counts["errors"]:
            raise SystemExit(
                f"fleet smoke FAILED: {flood_counts['errors']} flood "
                "requests hung/reset instead of a typed rejection"
            )
        if victim_shed:
            raise SystemExit(
                f"fleet smoke FAILED: neighbor {victim} saw "
                f"{victim_shed} 429s while {flooder} was flooded"
            )
        victim_p99 = percentile(victim_lat, 0.99)
        if victim_p99 > victim_p99_bound_s:
            raise SystemExit(
                f"fleet smoke FAILED: neighbor p99 {victim_p99:.3f}s "
                f"exceeds {victim_p99_bound_s}s under {flooder}'s flood"
            )
        if levels_snap["system"]["rejected"]:
            raise SystemExit(
                "fleet smoke FAILED: the host system level was shed "
                f"during a tenant flood ({levels_snap['system']})"
            )
        if levels_snap[victim]["rejected"]:
            raise SystemExit(
                f"fleet smoke FAILED: victim level {victim} recorded "
                f"rejections ({levels_snap[victim]})"
            )

        # ----- phase 3: scale-to-zero + cold-start bound ------------
        clock.advance(300.0)
        registry.sweep(force=True)
        snap = registry.snapshot()
        if snap["cold"] != tenants:
            raise SystemExit(
                "fleet smoke FAILED: expected every tenant parked after "
                f"the idle horizon, got {snap}"
            )
        reborn = ids[rng.randrange(len(ids))]
        t0 = time.monotonic()
        status, listing = req("GET", "/r/configmaps", tenant=reborn)
        restart_s = time.monotonic() - t0
        if status != 200 or restart_s > cold_start_bound_s:
            raise SystemExit(
                f"fleet smoke FAILED: re-cold-start of {reborn} -> "
                f"{status} in {restart_s:.3f}s (bound {cold_start_bound_s}s)"
            )
        names = [
            (o.get("metadata") or {}).get("name")
            for o in (listing or {}).get("items", [])
        ]
        if names != [f"{reborn}-cm"]:
            raise SystemExit(
                f"fleet smoke FAILED: {reborn} lost or gained state "
                f"across scale-to-zero: {names}"
            )

        # ----- phase 4: cross-tenant leak check ---------------------
        sample = [ids[0], ids[len(ids) // 2], ids[-1], flooder, victim]
        for tid in dict.fromkeys(sample):
            _status, listing = req("GET", "/r/configmaps", tenant=tid)
            names = sorted(
                (o.get("metadata") or {}).get("name")
                for o in (listing or {}).get("items", [])
            )
            if names != [f"{tid}-cm"]:
                raise SystemExit(
                    f"fleet smoke FAILED: tenant {tid} sees {names} — "
                    "cross-tenant leak or lost write"
                )
        if store.count("ConfigMap") != tenants:
            raise SystemExit(
                "fleet smoke FAILED: host store carries "
                f"{store.count('ConfigMap')} ConfigMaps, want {tenants}"
            )

    return {
        "seed": seed,
        "tenants": tenants,
        "cold_start_p50_s": round(percentile(cold_lat, 0.5), 4),
        "cold_start_p99_s": round(cold_p99, 4),
        "flood": {"tenant": flooder, **flood_counts},
        "victim": {
            "tenant": victim,
            "requests": len(victim_lat),
            "shed": victim_shed,
            "p99_s": round(victim_p99, 4),
            "baseline_p99_s": round(baseline_p99, 4),
            # denominator floored at 5ms: a sub-millisecond quiet
            # baseline would turn pure GIL jitter into a huge ratio
            "isolation_ratio": round(victim_p99 / max(baseline_p99, 0.005), 2),
        },
        "recold_start_s": round(restart_s, 4),
        "leaks": 0,
    }


def run_failover_smoke(seed: int = 42, lease_duration: float = 2.5) -> dict:
    """In-process HA smoke: three electors on one apiserver (APF on).

    Asserts the acceptance bounds of the leader-election subsystem
    (cluster/election.py) with real wall-clock timing:

    - exactly one leader at a time (the standby never self-promotes
      while the leader renews),
    - after the leader goes silent (SIGKILL analog: stop WITHOUT
      releasing), a standby holds the lease within 2x leaseDuration,
    - after a graceful step-down (release, the SIGTERM path), a
      standby holds it within ~one renew interval (asserted at
      <= leaseDuration, reported exactly),
    - the dead ex-leader's fence token is rejected with 409 while the
      live leader's token passes (split-brain write fencing).
    """
    import random

    from kwok_tpu.cluster.apiserver import APIServer
    from kwok_tpu.cluster.client import ClusterClient
    from kwok_tpu.cluster.election import LeaderElector
    from kwok_tpu.cluster.flowcontrol import FlowConfig, FlowController
    from kwok_tpu.cluster.store import Conflict, ResourceStore

    lease_name = "kwok-controller"
    store = ResourceStore()
    flow = FlowController(FlowConfig(max_inflight=16), seed=seed)

    def wait_until(cond, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(0.02)
        return cond()

    with APIServer(store, flow=flow) as srv:

        def mk(identity: str, bump: int) -> LeaderElector:
            return LeaderElector(
                # lease traffic rides the system priority level, like
                # the daemons' electors (X-Kwok-Client "system:...")
                ClusterClient(srv.url, client_id=f"system:{identity}"),
                lease_name,
                identity,
                lease_duration=lease_duration,
                rng=random.Random(seed + bump),
            )

        a = mk("replica-a", 1).start()
        if not wait_until(a.is_leader, 2 * lease_duration):
            raise SystemExit("failover smoke FAILED: first elector never led")
        b = mk("replica-b", 2).start()
        time.sleep(0.3)
        if b.is_leader():
            raise SystemExit("failover smoke FAILED: two concurrent leaders")
        stale_fence = a.fence()

        # --- hard failure: the leader falls silent (SIGKILL analog) ---
        t0 = time.monotonic()
        a.stop(release=False)
        if not wait_until(b.is_leader, 2 * lease_duration + 2.0):
            raise SystemExit(
                "failover smoke FAILED: standby never took over after kill"
            )
        takeover_kill_s = time.monotonic() - t0
        if takeover_kill_s > 2 * lease_duration:
            raise SystemExit(
                "failover smoke FAILED: takeover after kill took "
                f"{takeover_kill_s:.2f}s > 2x leaseDuration "
                f"({2 * lease_duration:.2f}s)"
            )

        # --- fencing: the dead generation cannot write, the live can ---
        cm = {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "fence-probe", "namespace": "default"},
            "data": {},
        }
        stale_client = ClusterClient(
            srv.url, fence_provider=lambda: stale_fence
        )
        try:
            stale_client.create(dict(cm))
        except Conflict:
            pass
        else:
            raise SystemExit(
                "failover smoke FAILED: stale-leader write was NOT fenced"
            )
        ClusterClient(srv.url, fence_provider=b.fence).create(dict(cm))

        # --- graceful step-down: release -> immediate handover ---
        c = mk("replica-c", 3).start()
        time.sleep(0.3)  # let c start polling (and observe b's lease)
        t1 = time.monotonic()
        b.stop(release=True)
        if not wait_until(c.is_leader, 2 * lease_duration + 2.0):
            raise SystemExit(
                "failover smoke FAILED: standby never took over after release"
            )
        takeover_release_s = time.monotonic() - t1
        if takeover_release_s > lease_duration:
            raise SystemExit(
                "failover smoke FAILED: graceful takeover took "
                f"{takeover_release_s:.2f}s > leaseDuration "
                f"({lease_duration:.2f}s; expected ~one renew interval)"
            )
        transitions = c.transitions
        c.stop(release=True)
    return {
        "seed": seed,
        "lease_duration_s": lease_duration,
        "takeover_after_kill_s": round(takeover_kill_s, 3),
        "takeover_after_release_s": round(takeover_release_s, 3),
        "lease_transitions": transitions,
        "stale_writes_fenced": 1,
        "split_brain_writes": 0,
    }


def drive_cluster(plan: FaultPlan, cluster: str, supervise: bool) -> dict:
    from kwok_tpu.chaos.disk_faults import DiskFaultDriver
    from kwok_tpu.chaos.process_faults import ProcessFaultDriver
    from kwok_tpu.ctl.runtime import BinaryRuntime, ComponentSupervisor

    rt = BinaryRuntime(cluster)
    if not rt.exists():
        raise SystemExit(f"cluster {cluster!r} does not exist (kwokctl create cluster)")
    sup = None
    if supervise:
        import random

        sup = ComponentSupervisor(rt, rng=random.Random(plan.seed)).start()
    driver = ProcessFaultDriver(rt, plan, client=rt.client(timeout=5.0))
    disk = DiskFaultDriver(rt, plan).start() if plan.disk else None
    try:
        driver.run()
        if disk is not None:
            # the process schedule may finish first; scheduled disk
            # faults still fire at their own offsets
            disk.wait(
                timeout=max((s.at for s in plan.disk), default=0.0) + 15.0
            )
            disk.stop()
        if supervise:
            # let the supervisor finish recovering what the last fault
            # broke before reporting
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if all(rt.running_components().values()):
                    break
                time.sleep(0.25)
    finally:
        if disk is not None:
            disk.stop()
        if sup is not None:
            sup.stop()
    return {
        "process_events": driver.events,
        "disk_events": disk.events if disk is not None else [],
        "supervisor_events": sup.events if sup is not None else [],
        "recovery_times_s": (
            [round(r, 3) for r in sup.recovery_times] if sup is not None else []
        ),
    }


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kwok-tpu-chaos", description=__doc__)
    p.add_argument("--profile", default="", help="chaos profile YAML")
    p.add_argument("--seed", type=int, default=None, help="override the profile seed")
    p.add_argument(
        "--print-schedule",
        action="store_true",
        help="print the deterministic fault schedule and exit",
    )
    p.add_argument("--cluster", default="", help="drive process faults against this cluster")
    p.add_argument(
        "--supervise",
        action="store_true",
        help="run the component supervisor while driving faults",
    )
    p.add_argument(
        "--smoke",
        action="store_true",
        help="run the in-process durability smoke (used by tools/check.sh)",
    )
    p.add_argument(
        "--overload-smoke",
        action="store_true",
        help="run the in-process overload/graceful-shedding smoke "
        "(used by tools/check.sh)",
    )
    p.add_argument(
        "--corruption-smoke",
        action="store_true",
        help="run the in-process storage-integrity smoke: seeded disk "
        "faults (bit-flip/truncate/torn-write/fsync-crash/snapshot "
        "corruption) must be detected, recovery bounded and honest, "
        "PITR byte-identical (used by tools/check.sh)",
    )
    p.add_argument(
        "--exhaustion-smoke",
        action="store_true",
        help="run the in-process resource-exhaustion smoke: seeded "
        "disk-full/fsync-error windows -> degraded read-only mode "
        "(503+Retry-After, reads/watches/lease renewals live, zero "
        "supervisor restarts), re-arm on space return, zero "
        "silently-lost acked writes (used by tools/check.sh)",
    )
    p.add_argument(
        "--failover-smoke",
        action="store_true",
        help="run the in-process leader-election failover smoke: "
        "bounded takeover after kill/release + stale-leader write "
        "fencing (used by tools/check.sh)",
    )
    p.add_argument(
        "--fleet-smoke",
        action="store_true",
        help="run the in-process multi-tenant fleet smoke: N virtual "
        "control planes on one apiserver — cold-start bound, neighbor "
        "flood shed WITHOUT starving the victim tenant or the system "
        "level, scale-to-zero + re-cold-start with state intact, zero "
        "cross-tenant leaks (used by tools/check.sh)",
    )
    p.add_argument(
        "--fleet-tenants",
        type=int,
        default=1000,
        help="fleet smoke tenant count",
    )
    p.add_argument(
        "--lease-seconds",
        type=float,
        default=2.5,
        help="failover smoke election lease duration",
    )
    p.add_argument(
        "--dst",
        action="store_true",
        help="deterministic simulation run(s): whole control plane on "
        "a virtual clock + invariant checks (kwok_tpu.dst)",
    )
    p.add_argument(
        "--seeds", type=int, default=10, help="how many DST seeds to explore"
    )
    p.add_argument(
        "--seed-start", type=int, default=0, help="first DST seed"
    )
    p.add_argument(
        "--dst-duration",
        type=float,
        default=40.0,
        help="virtual seconds of scenario+faults per DST seed",
    )
    p.add_argument(
        "--dst-bug",
        default=None,
        choices=[
            None,
            "ungated-writer",
            "partial-gang",
            "cross-shard-txn",
            "tenant-leak",
            "shard-void-leak",
            "fanin-stale-resume",
        ],
        help="inject a test-only regression (must be caught): "
        "ungated-writer reconciles without the lease, partial-gang "
        "binds PodGroups per-pod instead of atomically, "
        "cross-shard-txn makes the shard router place txn ops "
        "per-object and split atomic batches into per-shard sub-txns, "
        "tenant-leak un-scopes one fleet tenant's watch stream, "
        "shard-void-leak skips a rolled-back write's void accounting "
        "(union rv-continuity hole), fanin-stale-resume pins a "
        "caught-up shard's resume at horizon 0 in the watch fan-in "
        "(stale replay breaks per-stream rv monotonicity)",
    )
    p.add_argument(
        "--dst-fleet-tenants",
        type=int,
        default=2,
        help="fleet tenants the DST co-hosts (kwok_tpu.fleet; "
        "0 disables the fleet composition)",
    )
    p.add_argument(
        "--dst-shards",
        type=int,
        default=2,
        help="store shards the DST composes (kwok_tpu.cluster.sharding; "
        "1 = the single-store composition)",
    )
    p.add_argument(
        "--dst-verbose",
        action="store_true",
        help="print one JSON line per seed as it finishes",
    )
    p.add_argument(
        "--dst-search",
        action="store_true",
        help="coverage-guided fault search (kwok_tpu.dst.search): "
        "mutate fault schedules toward novel trace coverage instead "
        "of walking consecutive seeds; on violation, delta-debug to a "
        "minimal fault set and verify a byte-identical replay.  With "
        "--dst-bug armed, exit 0 iff the bug was found, minimized and "
        "replay-verified; without, exit 0 iff the budget ran clean",
    )
    p.add_argument(
        "--search-budget",
        type=int,
        default=48,
        help="schedule executions the guided search may spend",
    )
    p.add_argument(
        "--search-seed",
        type=int,
        default=0,
        help="seed of the search's own rng (mutations + corpus picks) "
        "— the whole search replays from this one value",
    )
    p.add_argument(
        "--search-out",
        default=None,
        metavar="FILE",
        help="write the minimized violation's replay artifact here "
        "(the --dst-replay regression-pinning format)",
    )
    p.add_argument(
        "--dst-replay",
        default=None,
        metavar="FILE",
        help="re-execute a --search-out artifact and verify the "
        "recorded trace digest + violations byte-identically "
        "(exit 0 iff both match)",
    )
    p.add_argument("--pods", type=int, default=40, help="smoke population")
    p.add_argument(
        "--flood-seconds",
        type=float,
        default=2.0,
        help="overload smoke flood duration",
    )
    return p


def run_dst(args) -> int:
    """Explore N seeds; print the aggregate report; nonzero exit on
    any invariant violation (the check.sh gate contract)."""
    from kwok_tpu.dst import SimOptions, run_seed

    opts = SimOptions(
        duration=args.dst_duration,
        bug=args.dst_bug,
        store_shards=args.dst_shards,
        fleet_tenants=args.dst_fleet_tenants,
    )
    violating = {}
    runs = []
    for i in range(args.seeds):
        seed = args.seed_start + i
        report = run_seed(seed, opts)
        runs.append(report)
        if args.dst_verbose:
            print(json.dumps(report), flush=True)
        if report["violations"]:
            violating[seed] = report["violations"]
    summary = {
        "seeds": args.seeds,
        "start": args.seed_start,
        "steps": sum(r["steps"] for r in runs),
        "crashes": sum(r["crashes"] for r in runs),
        "converged": sum(1 for r in runs if r["converged"]),
        "violating_seeds": sorted(violating),
        "violations": violating,
    }
    print(json.dumps(summary))
    return 1 if violating else 0


def run_dst_search(args) -> int:
    """Coverage-guided fault search; one JSON stats line.  Exit
    contract: with an injected bug armed, success means found +
    minimized + replay-verified; on a clean tree, success means the
    whole budget ran without a violation."""
    from kwok_tpu.dst import SimOptions
    from kwok_tpu.dst.search import (
        guided_search,
        replay_artifact,
        violation_artifact,
    )

    opts = SimOptions(
        duration=args.dst_duration,
        bug=args.dst_bug,
        store_shards=args.dst_shards,
        fleet_tenants=args.dst_fleet_tenants,
    )
    log = (lambda m: print(m, flush=True)) if args.dst_verbose else None
    res = guided_search(
        opts, budget=args.search_budget, search_seed=args.search_seed, log=log
    )
    stats = res.stats()
    stats["search_seed"] = args.search_seed
    stats["bug"] = args.dst_bug
    if res.found is not None:
        art = violation_artifact(opts, res.found, res.minimized)
        rep = replay_artifact(art)
        stats["replay_ok"] = rep["ok"]
        if args.search_out:
            with open(args.search_out, "w") as f:
                json.dump(art, f, indent=1, sort_keys=True)
            stats["artifact"] = args.search_out
        print(json.dumps(stats))
        # armed bug rediscovered and pinned = success; a violation on a
        # clean tree is a real finding = failure
        ok = rep["ok"] and (args.dst_bug is not None)
        return 0 if ok else 1
    print(json.dumps(stats))
    return 1 if args.dst_bug is not None else 0


def run_dst_replay(args) -> int:
    """Re-execute a pinned violation artifact; exit 0 iff the trace
    digest and the violation set replay byte-identically."""
    from kwok_tpu.dst.search import replay_artifact

    with open(args.dst_replay) as f:
        doc = json.load(f)
    rep = replay_artifact(doc)
    print(json.dumps(rep))
    return 0 if rep["ok"] else 1


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.dst_replay:
        return run_dst_replay(args)
    if args.dst_search:
        return run_dst_search(args)
    if args.dst:
        return run_dst(args)
    if args.smoke:
        report = run_smoke(seed=args.seed if args.seed is not None else 42, pods=args.pods)
        print(json.dumps(report))
        return 0
    if args.overload_smoke:
        report = run_overload_smoke(
            seed=args.seed if args.seed is not None else 42,
            duration=args.flood_seconds,
        )
        print(json.dumps(report))
        return 0
    if args.corruption_smoke:
        report = run_corruption_smoke(
            seed=args.seed if args.seed is not None else 42,
            pods=args.pods,
        )
        print(json.dumps(report))
        return 0
    if args.exhaustion_smoke:
        report = run_exhaustion_smoke(
            seed=args.seed if args.seed is not None else 42,
            pods=args.pods,
        )
        print(json.dumps(report))
        return 0
    if args.fleet_smoke:
        report = run_fleet_smoke(
            seed=args.seed if args.seed is not None else 42,
            tenants=args.fleet_tenants,
            flood_seconds=args.flood_seconds,
        )
        print(json.dumps(report))
        return 0
    if args.failover_smoke:
        report = run_failover_smoke(
            seed=args.seed if args.seed is not None else 42,
            lease_duration=args.lease_seconds,
        )
        print(json.dumps(report))
        return 0
    plan = load_profile(args.profile) if args.profile else FaultPlan()
    if args.seed is not None:
        plan.seed = args.seed
    if args.print_schedule:
        print(json.dumps(plan.to_dict(), indent=2))
        return 0
    if args.cluster:
        report = drive_cluster(plan, args.cluster, args.supervise)
        print(json.dumps(report, indent=2))
        return 0
    build_parser().print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
