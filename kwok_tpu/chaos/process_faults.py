"""Process-layer fault driver: kill / pause / restart real components.

Walks a :class:`~kwok_tpu.chaos.plan.FaultPlan`'s process schedule
against a live cluster through the runtime's component ops
(``kwok_tpu.ctl.runtime.BinaryRuntime``), the same layer the reference
runtime exposes Start/Stop per component on
(reference runtime/config.go:30-147):

- ``kill``     SIGKILL — no graceful shutdown, no final state save;
               recovery is the supervisor's problem (and the WAL's).
- ``stop``     SIGSTOP, then SIGCONT after ``resumeAfter`` seconds — a
               livelocked-but-alive component (liveness probes pass,
               work stalls).
- ``restart``  graceful stop + start through the runtime, the rolling-
               restart case.

The driver is wall-clock scheduled from plan ``at`` offsets and
records every action with timestamps, so tests can correlate injected
faults with observed recovery.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import List, Optional

from kwok_tpu.chaos.plan import FaultPlan, ProcessFaultSpec

__all__ = ["ProcessFaultDriver"]


class ProcessFaultDriver:
    """Execute a plan's process faults against a runtime."""

    def __init__(self, runtime, plan: FaultPlan):
        self.runtime = runtime
        self.plan = plan
        #: [{"t": wall-offset, "component", "action"}] in execution order
        self.events: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._resumes: List[tuple] = []  # (due_offset, component)

    def start(self) -> "ProcessFaultDriver":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # never leave a component SIGSTOPped behind a cancelled run
        for _, comp in self._resumes:
            self.runtime.signal_component(comp, signal.SIGCONT)
        self._resumes = []

    def run(self) -> None:
        """Blocking: replay the schedule, then resume any still-paused
        components, then return."""
        t0 = time.monotonic()
        pending = list(self.plan.process)
        while (pending or self._resumes) and not self._stop.is_set():
            now = time.monotonic() - t0
            # SIGCONT resumes that came due
            for due, comp in list(self._resumes):
                if now >= due:
                    self.runtime.signal_component(comp, signal.SIGCONT)
                    self._record(now, comp, "resume")
                    self._resumes.remove((due, comp))
            if pending and now >= pending[0].at:
                spec = pending.pop(0)
                self._apply(spec, now)
                continue
            next_due = min(
                [p.at for p in pending[:1]] + [d for d, _ in self._resumes],
                default=None,
            )
            if next_due is None:
                break
            self._stop.wait(min(max(next_due - now, 0.0), 0.25))
        for _, comp in self._resumes:
            self.runtime.signal_component(comp, signal.SIGCONT)
            self._record(time.monotonic() - t0, comp, "resume")
        self._resumes = []

    def _apply(self, spec: ProcessFaultSpec, now: float) -> None:
        if spec.action == "kill":
            self.runtime.signal_component(spec.component, signal.SIGKILL)
        elif spec.action == "stop":
            self.runtime.signal_component(spec.component, signal.SIGSTOP)
            self._resumes.append((now + max(spec.resume_after, 0.0), spec.component))
        elif spec.action == "restart":
            self.runtime.stop_component(spec.component)
            for comp in self.runtime.load_components():
                if comp.name == spec.component:
                    self.runtime.start_component(comp)
                    break
        self._record(now, spec.component, spec.action)

    def _record(self, now: float, component: str, action: str) -> None:
        self.events.append(
            {"t": round(now, 3), "component": component, "action": action}
        )
