"""Process-layer fault driver: kill / pause / restart real components.

Walks a :class:`~kwok_tpu.chaos.plan.FaultPlan`'s process schedule
against a live cluster through the runtime's component ops
(``kwok_tpu.ctl.runtime.BinaryRuntime``), the same layer the reference
runtime exposes Start/Stop per component on
(reference runtime/config.go:30-147):

- ``kill``     SIGKILL — no graceful shutdown, no final state save;
               recovery is the supervisor's problem (and the WAL's).
- ``stop``     SIGSTOP, then SIGCONT after ``resumeAfter`` seconds — a
               livelocked-but-alive component (liveness probes pass,
               work stalls).
- ``restart``  graceful stop + start through the runtime, the rolling-
               restart case.
- ``leader-kill``  resolve the replica of ``component`` currently
               holding its election Lease (cluster/election.py; lease
               name == component base name in kube-system) and SIGKILL
               that instance — the targeted fault behind the bounded-
               failover assertion.

The driver is wall-clock scheduled from plan ``at`` offsets and
records every action with timestamps, so tests can correlate injected
faults with observed recovery.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import List, Optional

from kwok_tpu.chaos.plan import FaultPlan, ProcessFaultSpec

__all__ = ["ProcessFaultDriver"]


class ProcessFaultDriver:
    """Execute a plan's process faults against a runtime."""

    def __init__(self, runtime, plan: FaultPlan, client=None):
        self.runtime = runtime
        self.plan = plan
        #: cluster client for leader-kill holder resolution; lazily
        #: built from the runtime when not supplied
        self._client = client
        #: [{"t": wall-offset, "component", "action"}] in execution order
        self.events: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._resumes: List[tuple] = []  # (due_offset, component)

    def start(self) -> "ProcessFaultDriver":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # never leave a component SIGSTOPped behind a cancelled run
        for _, comp in self._resumes:
            self.runtime.signal_component(comp, signal.SIGCONT)
        self._resumes = []

    def run(self) -> None:
        """Blocking: replay the schedule, then resume any still-paused
        components, then return."""
        t0 = time.monotonic()
        pending = list(self.plan.process)
        while (pending or self._resumes) and not self._stop.is_set():
            now = time.monotonic() - t0
            # SIGCONT resumes that came due
            for due, comp in list(self._resumes):
                if now >= due:
                    self.runtime.signal_component(comp, signal.SIGCONT)
                    self._record(now, comp, "resume")
                    self._resumes.remove((due, comp))
            if pending and now >= pending[0].at:
                spec = pending.pop(0)
                self._apply(spec, now)
                continue
            next_due = min(
                [p.at for p in pending[:1]] + [d for d, _ in self._resumes],
                default=None,
            )
            if next_due is None:
                break
            self._stop.wait(min(max(next_due - now, 0.0), 0.25))
        for _, comp in self._resumes:
            self.runtime.signal_component(comp, signal.SIGCONT)
            self._record(time.monotonic() - t0, comp, "resume")
        self._resumes = []

    def _resolve_leader(self, component: str) -> str:
        """Holder of ``component``'s election Lease (instance names
        double as holder identities, ctl/components.py replica_name).

        Tries the Lease named exactly like the component first, then
        scans kube-system for a lease whose holder IS one of the
        component's instances (``component`` or ``component-N``) — the
        scheduler seat needs this, its components are ``scheduler[-N]``
        but its election lease is ``kwok-scheduler``.  Falls back to
        the base name when unresolvable so the fault still fires at
        *something*."""
        try:
            if self._client is None:
                self._client = self.runtime.client(timeout=5.0)
            try:
                lease = self._client.get(
                    "Lease", component, namespace="kube-system"
                )
                holder = (lease.get("spec") or {}).get("holderIdentity")
                if holder:
                    return holder
            except Exception:  # noqa: BLE001 — no lease by that name;
                # match by holder instance name below
                pass
            for lease in self._client.list("Lease", namespace="kube-system")[0]:
                holder = (lease.get("spec") or {}).get("holderIdentity") or ""
                if holder == component or holder.startswith(component + "-"):
                    return holder
        except Exception:  # noqa: BLE001 — apiserver down: base name
            pass
        return component

    def _apply(self, spec: ProcessFaultSpec, now: float) -> None:
        if spec.action == "leader-kill":
            target = self._resolve_leader(spec.component)
            self.runtime.signal_component(target, signal.SIGKILL)
            self._record(now, target, "leader-kill")
            return
        if spec.action == "kill":
            self.runtime.signal_component(spec.component, signal.SIGKILL)
        elif spec.action == "stop":
            self.runtime.signal_component(spec.component, signal.SIGSTOP)
            self._resumes.append((now + max(spec.resume_after, 0.0), spec.component))
        elif spec.action == "restart":
            self.runtime.stop_component(spec.component)
            for comp in self.runtime.load_components():
                if comp.name == spec.component:
                    self.runtime.start_component(comp)
                    break
        self._record(now, spec.component, spec.action)

    def _record(self, now: float, component: str, action: str) -> None:
        self.events.append(
            {"t": round(now, 3), "component": component, "action": action}
        )
