"""Fault plans: the deterministic, seeded schedule every injector obeys.

A plan is data, not behavior — the YAML profile shape mirrors how the
reference declares chaos as data in its stage sets
(``kwok_tpu/stages/node-chaos.yaml:1``), extended from object state to
infrastructure.  One ``seed`` drives every random decision (HTTP fault
draws, retry jitter via the client's seeded RetryPolicy, process fault
ordering), so a chaos run is reproducible: same seed + same workload →
the same decision sequence.

Profile YAML::

    kind: ChaosProfile
    seed: 42
    duration: 30            # seconds of active fault injection
    http:
      latency:   {p: 0.10, seconds: 0.05}
      reject:    {p: 0.05, status: 503, retryAfter: 0.2}
      reset:     {p: 0.02}
      watchDrop: {p: 0.01}  # per 0.25s watch-loop tick
      partitions:
        - {client: kwok-controller, at: 5, duration: 3}
      overload:             # best-effort request floods (APF exercise)
        - {at: 2, duration: 5, rps: 200, clients: 4}
    process:
      - {component: apiserver, at: 8, action: kill}
      - {component: kube-controller-manager, at: 12, action: stop, resumeAfter: 2}
      - {component: kwok-controller, at: 20, action: leader-kill}
    disk:
      - {at: 15, kind: bit-flip, target: wal}
      - {at: 25, kind: truncate, target: snapshot}

``action`` is ``kill`` (SIGKILL; the supervisor restarts), ``stop``
(SIGSTOP, SIGCONT after ``resumeAfter``), ``restart`` (graceful
stop + start), or ``leader-kill`` (resolve which replica of
``component`` currently holds its election Lease — cluster/election.py
— and SIGKILL that one; the targeted fault the failover bound is
asserted under).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import yaml

__all__ = [
    "HttpFaultSpec",
    "OverloadWindow",
    "PartitionWindow",
    "ProcessFaultSpec",
    "DiskFaultSpec",
    "DISK_FAULT_KINDS",
    "FaultPlan",
    "load_profile",
]

PROCESS_ACTIONS = ("kill", "stop", "restart", "leader-kill")

# storage-layer fault vocabulary: media bit flips, lost tails,
# partially-persisted batched appends, machine death at the fsync
# boundary — owned by the module that implements the kinds, so a new
# kind is automatically valid in profiles
from kwok_tpu.chaos.disk_faults import DISK_FAULT_KINDS  # noqa: E402

# exhaustion vocabulary (the disk *refuses* instead of lying):
# disk-full / fsync-error / quota windows, armed inside the apiserver
# daemon against its own WAL handles (kwok_tpu.chaos.fs_pressure)
from kwok_tpu.chaos.fs_pressure import EXHAUSTION_KINDS  # noqa: E402

DISK_TARGETS = ("wal", "snapshot")


@dataclass(frozen=True)
class PartitionWindow:
    """One client's view of the apiserver goes dark for a window:
    requests carrying a matching ``X-Kwok-Client`` are reset without a
    response while ``at <= t-t0 < at + duration``."""

    client: str
    at: float
    duration: float

    def active(self, elapsed: float) -> bool:
        return self.at <= elapsed < self.at + self.duration


@dataclass(frozen=True)
class OverloadWindow:
    """One scheduled best-effort request flood: ``clients`` worker
    threads issuing ~``rps`` total requests/second against ``path``
    while ``at <= t-t0 < at + duration``.  Each worker identifies as
    ``{clientPrefix}-{i}`` — unknown to the default flow schema, so the
    flood classifies as best-effort and exercises the APF shedding
    path without touching higher priority levels."""

    at: float
    duration: float
    rps: float = 100.0
    clients: int = 4
    path: str = "/r/pods"
    client_prefix: str = "chaos-flood"

    def active(self, elapsed: float) -> bool:
        return self.at <= elapsed < self.at + self.duration

    @classmethod
    def from_dict(cls, d: dict) -> "OverloadWindow":
        return cls(
            at=float(d.get("at", 0.0)),
            duration=float(d.get("duration", 0.0)),
            rps=float(d.get("rps", 100.0)),
            clients=int(d.get("clients", 4)),
            path=str(d.get("path") or "/r/pods"),
            client_prefix=str(d.get("clientPrefix") or "chaos-flood"),
        )

    def to_dict(self) -> dict:
        return {
            "at": self.at,
            "duration": self.duration,
            "rps": self.rps,
            "clients": self.clients,
            "path": self.path,
            "clientPrefix": self.client_prefix,
        }


@dataclass
class HttpFaultSpec:
    """Per-request fault probabilities at the apiserver HTTP boundary."""

    latency_p: float = 0.0
    latency_s: float = 0.05
    reject_p: float = 0.0
    reject_status: int = 503
    retry_after: Optional[float] = 0.2
    reset_p: float = 0.0
    watch_drop_p: float = 0.0
    partitions: List[PartitionWindow] = field(default_factory=list)
    overloads: List[OverloadWindow] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "HttpFaultSpec":
        lat = d.get("latency") or {}
        rej = d.get("reject") or {}
        res = d.get("reset") or {}
        drop = d.get("watchDrop") or {}
        ra = rej.get("retryAfter", 0.2)
        return cls(
            latency_p=float(lat.get("p", 0.0)),
            latency_s=float(lat.get("seconds", 0.05)),
            reject_p=float(rej.get("p", 0.0)),
            reject_status=int(rej.get("status", 503)),
            retry_after=None if ra is None else float(ra),
            reset_p=float(res.get("p", 0.0)),
            watch_drop_p=float(drop.get("p", 0.0)),
            partitions=[
                PartitionWindow(
                    client=str(p.get("client") or ""),
                    at=float(p.get("at", 0.0)),
                    duration=float(p.get("duration", 0.0)),
                )
                for p in d.get("partitions") or []
            ],
            overloads=[
                OverloadWindow.from_dict(o) for o in d.get("overload") or []
            ],
        )

    def to_dict(self) -> dict:
        return {
            "latency": {"p": self.latency_p, "seconds": self.latency_s},
            "reject": {
                "p": self.reject_p,
                "status": self.reject_status,
                "retryAfter": self.retry_after,
            },
            "reset": {"p": self.reset_p},
            "watchDrop": {"p": self.watch_drop_p},
            "partitions": [
                {"client": p.client, "at": p.at, "duration": p.duration}
                for p in self.partitions
            ],
            "overload": [o.to_dict() for o in self.overloads],
        }


@dataclass(frozen=True)
class DiskFaultSpec:
    """One scheduled storage fault against the cluster's WAL or
    snapshot files.  Corruption kinds (bit-flip / truncate / torn-write
    / fsync-crash) are point faults kwok_tpu.chaos.disk_faults applies
    from outside (the exact byte offset is drawn from the plan seed at
    injection time); exhaustion kinds (disk-full / fsync-error / quota)
    are *windows* — ``duration`` seconds of refused syscalls — armed
    inside the apiserver daemon via kwok_tpu.chaos.fs_pressure.  Either
    way ``--print-schedule`` shows when/what and the run stays
    reproducible."""

    at: float
    kind: str  # bit-flip | truncate | torn-write | fsync-crash
    #           | disk-full | fsync-error | quota
    target: str = "wal"  # wal | snapshot
    #: window length for exhaustion kinds (ignored by point faults)
    duration: float = 0.0
    #: which store shard's files the fault hits (sharded clusters,
    #: kwok_tpu/cluster/sharding — 0 is also the single-store layout)
    shard: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "DiskFaultSpec":
        kind = str(d.get("kind") or "bit-flip")
        if kind not in DISK_FAULT_KINDS + EXHAUSTION_KINDS:
            raise ValueError(
                f"disk fault kind {kind!r} not in "
                f"{DISK_FAULT_KINDS + EXHAUSTION_KINDS}"
            )
        target = str(d.get("target") or "wal")
        if target not in DISK_TARGETS:
            raise ValueError(
                f"disk fault target {target!r} not in {DISK_TARGETS}"
            )
        if kind in EXHAUSTION_KINDS and target != "wal":
            raise ValueError(
                f"exhaustion fault {kind!r} only targets the wal"
            )
        duration = float(d.get("duration", 0.0))
        if kind in EXHAUSTION_KINDS and duration <= 0:
            # a zero-length window installs and removes the shim in the
            # same instant — a fault that "ran" without testing anything
            raise ValueError(
                f"exhaustion fault {kind!r} needs a positive duration"
            )
        shard = int(d.get("shard", 0))
        if shard < 0:
            raise ValueError(f"disk fault shard {shard} must be >= 0")
        return cls(
            at=float(d.get("at", 0.0)),
            kind=kind,
            target=target,
            duration=duration,
            shard=shard,
        )

    def to_dict(self) -> dict:
        out = {"at": self.at, "kind": self.kind, "target": self.target}
        if self.kind in EXHAUSTION_KINDS:
            out["duration"] = self.duration
        if self.shard:
            out["shard"] = self.shard
        return out


@dataclass(frozen=True)
class ProcessFaultSpec:
    """One scheduled process-layer fault."""

    component: str
    at: float
    action: str  # kill | stop | restart
    resume_after: float = 0.0

    @classmethod
    def from_dict(cls, d: dict) -> "ProcessFaultSpec":
        action = str(d.get("action") or "kill")
        if action not in PROCESS_ACTIONS:
            raise ValueError(
                f"process fault action {action!r} not in {PROCESS_ACTIONS}"
            )
        return cls(
            component=str(d.get("component") or ""),
            at=float(d.get("at", 0.0)),
            action=action,
            resume_after=float(d.get("resumeAfter", 0.0)),
        )

    def to_dict(self) -> dict:
        return {
            "component": self.component,
            "at": self.at,
            "action": self.action,
            "resumeAfter": self.resume_after,
        }


@dataclass
class FaultPlan:
    """Everything a chaos run needs, reproducible from ``seed``."""

    seed: int = 0
    duration: float = 30.0
    http: HttpFaultSpec = field(default_factory=HttpFaultSpec)
    process: List[ProcessFaultSpec] = field(default_factory=list)
    disk: List[DiskFaultSpec] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        kind = d.get("kind")
        if kind not in (None, "ChaosProfile"):
            raise ValueError(f"not a ChaosProfile document: kind={kind!r}")
        return cls(
            seed=int(d.get("seed", 0)),
            duration=float(d.get("duration", 30.0)),
            http=HttpFaultSpec.from_dict(d.get("http") or {}),
            process=sorted(
                (ProcessFaultSpec.from_dict(p) for p in d.get("process") or []),
                key=lambda p: (p.at, p.component),
            ),
            disk=sorted(
                (DiskFaultSpec.from_dict(p) for p in d.get("disk") or []),
                key=lambda p: (p.at, p.kind),
            ),
        )

    def to_dict(self) -> dict:
        return {
            "kind": "ChaosProfile",
            "seed": self.seed,
            "duration": self.duration,
            "http": self.http.to_dict(),
            "process": [p.to_dict() for p in self.process],
            "disk": [p.to_dict() for p in self.disk],
        }


def load_profile(path: str) -> FaultPlan:
    with open(path, "r", encoding="utf-8") as f:
        doc = yaml.safe_load(f) or {}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: chaos profile must be a mapping")
    return FaultPlan.from_dict(doc)
