"""Fault injection as a first-class subsystem.

The reference's robustness story is chaos-in-data: the ``pod-chaos`` /
``node-chaos`` stage sets flip *object state* adversarially
(``kwok_tpu/stages/pod-chaos.yaml:1``, reference
kustomize/stage/pod/chaos) while the infrastructure underneath is
assumed healthy.  This rebuild runs a real multi-process control plane,
so the infrastructure itself must be breakable on demand — the
Jepsen-style stance that failure paths stay correct only if they are
exercised continuously (PAPERS.md).  Three injection layers, all driven
by one deterministic seeded :class:`~kwok_tpu.chaos.plan.FaultPlan`:

- **HTTP boundary** (:mod:`kwok_tpu.chaos.http_faults`): added latency,
  429/503 rejections with Retry-After, connection resets, watch-stream
  drops, and per-client partitions, hooked into the apiserver facade
  via its ``fault_injector`` seam.
- **process layer** (:mod:`kwok_tpu.chaos.process_faults`): SIGKILL /
  SIGSTOP+SIGCONT / restart of control-plane components through
  ``kwok_tpu.ctl.runtime``; recovery is the supervisor's job.
- **store commit path**: ``ResourceStore.set_crash_hook`` fires at the
  before-/after-commit boundaries so WAL recovery is testable at the
  exact instants a crash hurts.
- **storage exhaustion** (:mod:`kwok_tpu.chaos.fs_pressure`): seeded
  disk-full / fsync-error / quota windows against the WAL's own
  syscalls (the disk *refuses*; :mod:`kwok_tpu.chaos.disk_faults` is
  the disk *lying*), driving degraded read-only mode, the emergency
  reserve, and the re-arm probe end to end.

Profiles are YAML (``kwokctl create cluster --chaos-profile`` wires
them into the apiserver daemon); ``python -m kwok_tpu.chaos`` is the
offline driver (schedule printing, process-fault driving, and the
self-contained durability smoke used by tools/check.sh).
"""

from kwok_tpu.chaos.plan import (  # noqa: F401
    DiskFaultSpec,
    FaultPlan,
    HttpFaultSpec,
    OverloadWindow,
    PartitionWindow,
    ProcessFaultSpec,
    load_profile,
)
from kwok_tpu.chaos.http_faults import (  # noqa: F401
    HttpFaultInjector,
    OverloadDriver,
)
from kwok_tpu.chaos.fs_pressure import (  # noqa: F401
    EXHAUSTION_KINDS,
    FsPressure,
    PressureDriver,
)

__all__ = [
    "DiskFaultSpec",
    "FaultPlan",
    "HttpFaultSpec",
    "OverloadWindow",
    "PartitionWindow",
    "ProcessFaultSpec",
    "load_profile",
    "HttpFaultInjector",
    "OverloadDriver",
    "EXHAUSTION_KINDS",
    "FsPressure",
    "PressureDriver",
]
