"""Seeded filesystem-pressure injection: disk-full / fsync-error /
quota windows against the WAL's own syscalls.

The fifth injection layer (after HTTP, process, commit-boundary and
disk corruption — ``kwok_tpu/chaos/__init__.py:1``): the disk does not
*lie* (that is ``kwok_tpu/chaos/disk_faults.py:1``'s corruption
vocabulary), it *refuses*.  A :class:`FsPressure` shim installs into
the write-ahead log's pressure seam
(``kwok_tpu/cluster/wal.py:1`` ``WriteAheadLog.set_pressure``) and is
consulted before every one of the log's own write/fsync syscalls:

- ``disk-full`` — every write raises ENOSPC until headroom is freed;
  releasing the WAL's preallocated emergency reserve credits the shim
  (``freed``), exactly like unlinking a real file frees real blocks,
  so the reserve-powered retry and lease renewals behave as they would
  on a genuinely full disk.
- ``quota`` — the EDQUOT twin (per-tenant storage budgets; the
  KUBEDIRECT-shape multi-tenant direction in ROADMAP.md).
- ``fsync-error`` — writes land but every fsync raises EIO: the
  fsyncgate shape, driving the poison-handle seal-and-reopen path.

Window *state* is toggled by the owner (the daemon's
:class:`PressureDriver` on wall-clock offsets, the DST harness at
virtual instants, smokes inline), so the shim itself is clock-free and
consumes no randomness at check time — a pressure schedule is a pure
function of the plan, byte-identical per seed.
"""

from __future__ import annotations

import errno
import logging
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["EXHAUSTION_KINDS", "FsPressure", "PressureDriver"]

log = logging.getLogger(__name__)

#: fault kinds the pressure shim models (the ``disk:`` profile section
#: accepts these alongside the corruption kinds of disk_faults.py)
EXHAUSTION_KINDS = ("disk-full", "fsync-error", "quota")

_ERRNOS = {
    "disk-full": errno.ENOSPC,
    "quota": getattr(errno, "EDQUOT", errno.ENOSPC),
}


class FsPressure:
    """One pressure window's state: a duck-typed shim for
    ``WriteAheadLog.set_pressure`` (``on_write``/``on_fsync`` raise the
    injected OSError; ``freed`` credits released reserve space)."""

    def __init__(self, kind: str, free_bytes: int = 0):
        if kind not in EXHAUSTION_KINDS:
            raise ValueError(
                f"pressure kind {kind!r} not in {EXHAUSTION_KINDS}"
            )
        self.kind = kind
        #: simulated free space: writes consume it, ``freed`` refills
        #: it (disk-full/quota only; fsync-error never blocks writes)
        self._free = int(free_bytes)
        self.writes_failed = 0
        self.fsyncs_failed = 0
        self.bytes_written = 0

    def on_write(self, nbytes: int) -> None:
        if self.kind == "fsync-error":
            return
        if nbytes <= self._free:
            self._free -= nbytes
            self.bytes_written += nbytes
            return
        self.writes_failed += 1
        eno = _ERRNOS[self.kind]
        raise OSError(eno, os.strerror(eno))

    def on_fsync(self) -> None:
        if self.kind != "fsync-error":
            return
        self.fsyncs_failed += 1
        raise OSError(errno.EIO, os.strerror(errno.EIO))

    def freed(self, nbytes: int) -> None:
        """Space was genuinely released (the WAL unlinked its reserve):
        credit the simulated free-block budget with it."""
        self._free += int(nbytes)

    def snapshot(self) -> Dict[str, int]:
        return {
            "writes_failed": self.writes_failed,
            "fsyncs_failed": self.fsyncs_failed,
            "bytes_written": self.bytes_written,
            "free_bytes": self._free,
        }


class PressureDriver:
    """Arm a plan's exhaustion windows against a live WriteAheadLog on
    wall-clock offsets — the in-daemon twin of
    :class:`~kwok_tpu.chaos.disk_faults.DiskFaultDriver` (corruption
    faults hit files from outside; pressure faults must sit inside the
    process that owns the file handles).  ``cmd/apiserver`` starts one
    when its ``--chaos-profile`` carries ``disk:`` entries with
    exhaustion kinds; after each window it force-probes the re-arm path
    so the cluster leaves degraded mode without waiting for traffic."""

    def __init__(self, plan, wal, store=None, wals=None):
        self.plan = plan
        self.wal = wal
        #: per-shard WAL handles of a sharded store (index = shard);
        #: a spec's ``shard:`` picks its target, out-of-range entries
        #: fall back to the primary ``wal`` (shard 0's handle)
        self.wals = list(wals) if wals else [wal]
        #: when given, re-arm probes route through
        #: ``store.probe_writable()`` — the store mutex serializes them
        #: against request-thread appends (a bare ``wal.try_rearm()``
        #: from this thread would race the unlocked WAL's sequence
        #: bookkeeping); shim install/remove stays a plain reference
        #: swap, which is safe without the lock
        self.store = store
        #: [{"t", "kind", "event", ...}] — window open/close log
        self.events: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def specs(plan) -> List:
        """The plan's ``disk:`` entries this driver owns."""
        return [s for s in plan.disk if s.kind in EXHAUSTION_KINDS]

    def start(self) -> "PressureDriver":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def _rearm(self) -> bool:
        if self.store is not None:
            return bool(self.store.probe_writable())
        return bool(self.wal.try_rearm())

    def _wal_for(self, spec) -> tuple:
        """(wal, shard index actually pressured): an out-of-range
        ``shard:`` (a stale profile after a shard-count change) falls
        back to the primary WAL — the event log must record THAT
        index, not the spec's, or a per-shard isolation readout
        concludes the wrong shard was degraded."""
        shard = int(getattr(spec, "shard", 0))
        if 0 <= shard < len(self.wals):
            return self.wals[shard], shard
        log.warning(
            "pressure window spec shard=%d out of range (%d shards); "
            "falling back to shard 0",
            shard,
            len(self.wals),
        )
        return self.wal, 0

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # never leave a dangling shim behind a cancelled schedule
        for w in self.wals:
            w.set_pressure(None)
        self._rearm()

    def run(self) -> None:
        t0 = time.monotonic()
        pending = sorted(self.specs(self.plan), key=lambda s: s.at)
        for spec in pending:
            now = time.monotonic() - t0
            if spec.at > now and self._stop.wait(spec.at - now):
                return
            shim = FsPressure(spec.kind)
            wal, shard = self._wal_for(spec)
            wal.set_pressure(shim)
            self.events.append(
                {
                    "t": round(time.monotonic() - t0, 3),
                    "kind": spec.kind,
                    "shard": shard,
                    "event": "window-open",
                }
            )
            self._stop.wait(max(spec.duration, 0.0))
            wal.set_pressure(None)
            rearmed = self._rearm()
            self.events.append(
                {
                    "t": round(time.monotonic() - t0, 3),
                    "kind": spec.kind,
                    "event": "window-close",
                    "rearmed": bool(rearmed),
                    **shim.snapshot(),
                }
            )
            if self._stop.is_set():
                return
