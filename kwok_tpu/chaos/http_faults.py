"""HTTP-boundary fault injector — the apiserver's ``fault_injector``
duck type.

Sits behind the seam ``kwok_tpu.cluster.apiserver`` exposes (the
handler asks ``on_request``/``on_watch_tick`` before dispatching; this
module never imports the server, keeping chaos above cluster in the
layer map).  Decisions come from one seeded ``random.Random`` under a
lock, so a run's decision *sequence* is deterministic for a given
seed; health endpoints are never faulted (liveness must stay truthful
or recovery itself flaps — the same reason the reference's chaos
stages leave the kubelet's own heartbeat machinery alone,
``kwok_tpu/stages/node-chaos.yaml:1``).

Actions returned to the handler::

    {"action": "latency", "seconds": s}            sleep then serve
    {"action": "reject", "status": 429|503,
     "retry_after": s|None}                        typed rejection
    {"action": "reset"}                            close with no reply
    None                                           serve normally

``on_watch_tick`` returning True drops the watch stream mid-flight.

:class:`OverloadDriver` is the injector's flood arm: it executes the
plan's ``overload`` windows (seeded best-effort request floods) against
a server URL, recording per-response outcomes so a chaos run can assert
the APF layer shed the flood with well-formed 429s
(``kwok_tpu.cluster.flowcontrol``) rather than hung or reset
connections.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional

from kwok_tpu.chaos.plan import FaultPlan

__all__ = ["HttpFaultInjector", "OverloadDriver"]

#: paths that must stay truthful — see module docstring
_EXEMPT = ("/healthz", "/readyz", "/livez")


class HttpFaultInjector:
    """Seeded per-request fault decisions over a :class:`FaultPlan`."""

    def __init__(self, plan: FaultPlan, clock=None):
        self.plan = plan
        self._clock = clock or time.monotonic
        self._rng = random.Random(plan.seed)
        self._mut = threading.Lock()
        self._t0 = self._clock()
        #: injected-fault counters by kind, for smoke asserts and the
        #: daemon's shutdown report
        self.counters: Dict[str, int] = {
            "latency": 0,
            "reject": 0,
            "reset": 0,
            "watch_drop": 0,
            "partition": 0,
        }

    def start(self) -> None:
        """(Re)open the active-fault window from now."""
        with self._mut:
            self._t0 = self._clock()

    @property
    def elapsed(self) -> float:
        return self._clock() - self._t0

    def active(self) -> bool:
        return self.elapsed < self.plan.duration

    # ------------------------------------------------------------- handler API

    def on_request(
        self, method: str, path: str, client_id: str
    ) -> Optional[dict]:
        if path.split("?", 1)[0] in _EXEMPT:
            return None
        spec = self.plan.http
        with self._mut:
            elapsed = self._clock() - self._t0
            if elapsed >= self.plan.duration:
                return None
            for part in spec.partitions:
                if part.client and part.client == client_id and part.active(elapsed):
                    self.counters["partition"] += 1
                    return {"action": "reset"}
            draw = self._rng.random()
            # one draw, stacked thresholds: keeps the decision sequence
            # a pure function of (seed, request ordinal)
            if draw < spec.reset_p:
                self.counters["reset"] += 1
                return {"action": "reset"}
            draw -= spec.reset_p
            if draw < spec.reject_p:
                self.counters["reject"] += 1
                return {
                    "action": "reject",
                    "status": spec.reject_status,
                    "retry_after": spec.retry_after,
                }
            draw -= spec.reject_p
            if draw < spec.latency_p:
                self.counters["latency"] += 1
                return {"action": "latency", "seconds": spec.latency_s}
        return None

    def on_watch_tick(self, client_id: str) -> bool:
        spec = self.plan.http
        if spec.watch_drop_p <= 0.0:
            return False
        with self._mut:
            elapsed = self._clock() - self._t0
            if elapsed >= self.plan.duration:
                return False
            for part in spec.partitions:
                if part.client and part.client == client_id and part.active(elapsed):
                    self.counters["watch_drop"] += 1
                    return True
            if self._rng.random() < spec.watch_drop_p:
                self.counters["watch_drop"] += 1
                return True
        return False

    def snapshot(self) -> Dict[str, int]:
        with self._mut:
            return dict(self.counters)


class OverloadDriver:
    """Execute a plan's ``overload`` windows: seeded best-effort
    request floods against ``url``.

    Each window runs ``clients`` worker threads pacing toward the
    window's total rps with seeded jitter.  Workers use raw
    ``http.client`` — no retries, one fresh connection per request — so
    every response (or connection failure) is observed exactly once::

        sent                 requests issued
        ok                   2xx answers
        shed                 429 answers
        shed_without_retry_after   429s missing the Retry-After header
        other_status         any other HTTP status (injected 503s etc.)
        conn_errors          socket-level failures (no parseable reply)

    The graceful-degradation contract under a pure overload plan is
    ``shed > 0`` with ``shed_without_retry_after == 0`` and
    ``conn_errors == 0`` — load is refused loudly, never dropped on the
    floor."""

    def __init__(self, plan: FaultPlan, url: str, clock=None):
        self.plan = plan
        self.url = url
        self._clock = clock or time.monotonic
        self._seed = plan.seed
        self._mut = threading.Lock()
        self._stop = threading.Event()
        self._threads: list = []
        self.counters: Dict[str, int] = {
            "sent": 0,
            "ok": 0,
            "shed": 0,
            "shed_without_retry_after": 0,
            "other_status": 0,
            "conn_errors": 0,
        }

    def start(self) -> "OverloadDriver":
        """Schedule every overload window from now; returns self."""
        t0 = self._clock()
        for wi, win in enumerate(self.plan.http.overloads):
            for ci in range(max(1, win.clients)):
                t = threading.Thread(
                    target=self._worker,
                    args=(t0, wi, win, ci),
                    daemon=True,
                )
                t.start()
                self._threads.append(t)
        return self

    def _worker(self, t0: float, wi: int, win, ci: int) -> None:
        from urllib.parse import urlsplit

        u = urlsplit(self.url)
        hostport = u.netloc
        rng = random.Random(f"{self._seed}/{wi}/{ci}")
        period = max(1, win.clients) / max(win.rps, 0.1)
        client_id = f"{win.client_prefix}-{ci}"
        # wait for the window to open
        while not self._stop.is_set():
            delta = (t0 + win.at) - self._clock()
            if delta <= 0:
                break
            if self._stop.wait(min(delta, 0.1)):
                return
        while not self._stop.is_set():
            if self._clock() - t0 >= win.at + win.duration:
                return
            self._one_request(hostport, win.path, client_id)
            # seeded jitter keeps workers from phase-locking while the
            # mean pacing stays at the window's rps
            self._stop.wait(period * (0.5 + rng.random()))

    def _one_request(self, hostport: str, path: str, client_id: str) -> None:
        import http.client

        if self.url.startswith("https://"):
            import ssl

            # the flood is hostile-by-design traffic; it does not get
            # the cluster CA, so it skips verification like any
            # anonymous internet client would fail to do properly
            conn = http.client.HTTPSConnection(
                hostport, timeout=10, context=ssl._create_unverified_context()
            )
        else:
            conn = http.client.HTTPConnection(hostport, timeout=10)
        outcome = "conn_errors"
        retry_after_missing = False
        try:
            conn.request(
                "GET", path, headers={"X-Kwok-Client": client_id}
            )
            resp = conn.getresponse()
            resp.read()
            if resp.status == 429:
                outcome = "shed"
                retry_after_missing = resp.getheader("Retry-After") is None
            elif 200 <= resp.status < 300:
                outcome = "ok"
            else:
                outcome = "other_status"
        except (OSError, http.client.HTTPException):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
        with self._mut:
            self.counters["sent"] += 1
            self.counters[outcome] += 1
            if retry_after_missing:
                self.counters["shed_without_retry_after"] += 1

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every window's workers finished; False on
        timeout."""
        deadline = None if timeout is None else self._clock() + timeout
        for t in self._threads:
            left = None if deadline is None else max(0.0, deadline - self._clock())
            t.join(left)
            if t.is_alive():
                return False
        return True

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    def snapshot(self) -> Dict[str, int]:
        with self._mut:
            return dict(self.counters)
